"""Native C++ fastpath tests: the library builds in this image (g++ is
available) and every kernel is bit-identical to its NumPy/Python fallback.
The reference has no native components (SURVEY.md §2.0); this layer is the
framework's host-side runtime, so parity with the Python semantics is the
whole contract."""

import numpy as np
import pytest

from replicatinggpt_tpu import native
from replicatinggpt_tpu.tokenizers import ByteBPETokenizer, CharTokenizer


@pytest.mark.slow
def test_native_library_builds():
    assert native.available(), (
        "native fastpath failed to build; run "
        "python -m replicatinggpt_tpu.native.build for the compiler error")


def test_encode_lut_matches_python(tiny_corpus):
    tok = CharTokenizer.from_text(tiny_corpus)
    assert tok._lut is not None  # Shakespeare is ASCII
    ids = tok.encode_np(tiny_corpus)
    assert ids.dtype == np.int32
    assert ids.tolist() == tok.encode(tiny_corpus)


def test_encode_lut_rejects_unmapped_bytes(tiny_corpus):
    tok = CharTokenizer.from_text(tiny_corpus)
    with pytest.raises((ValueError, KeyError)):
        native.encode_lut("é".encode("utf-8"), tok._lut)


def test_non_ascii_vocab_falls_back(tiny_corpus):
    tok = CharTokenizer.from_text(tiny_corpus + "é")
    assert tok._lut is None
    s = (tiny_corpus + "é")[:5000]
    assert tok.encode_np(s).tolist() == tok.encode(s)


@pytest.mark.slow
def test_gather_batch_matches_numpy():
    rng = np.random.default_rng(0)
    data = rng.integers(0, 1000, size=10_000).astype(np.int32)
    offsets = rng.integers(0, len(data) - 65, size=32)
    x, y = native.gather_batch(data, offsets, 64)
    idx = offsets[:, None] + np.arange(65)[None, :]
    win = data[idx]
    np.testing.assert_array_equal(x, win[:, :-1])
    np.testing.assert_array_equal(y, win[:, 1:])


def test_bpe_native_matches_python(tiny_corpus):
    tok = ByteBPETokenizer.train(tiny_corpus[:20_000], vocab_size=350)
    s = tiny_corpus[:12_000]
    got = tok.encode_np(s)
    assert got.tolist() == tok.encode(s)
    # round-trip through decode for good measure
    assert tok.decode(got.tolist()) == s


def test_bpe_cache_not_confused_across_tokenizers(tiny_corpus):
    # regression: the C++ merge cache was once keyed on the rule array's
    # pointer; a second tokenizer whose arrays landed on a recycled buffer
    # address silently reused the first tokenizer's merges
    s = tiny_corpus[:12_000]
    a = ByteBPETokenizer.train(tiny_corpus[:20_000], vocab_size=350)
    _ = a.encode_np(s)  # populate the native cache
    del a
    b = ByteBPETokenizer.train(tiny_corpus[5_000:25_000], vocab_size=350)
    assert b.encode_np(s).tolist() == b.encode(s)


def test_bpe_custom_vocab_disables_native(tiny_corpus):
    # a vocab whose base slots are not byte-symbol order makes the id-space
    # kernel unsound; encode_np must fall back to the Python path
    tok = ByteBPETokenizer.train(tiny_corpus[:20_000], vocab_size=300)
    shuffled = list(tok.vocab)
    shuffled[0], shuffled[1] = shuffled[1], shuffled[0]
    weird = ByteBPETokenizer(tok.merges, vocab=shuffled)
    assert weird._native_merge_table() is None
    s = tiny_corpus[:6_000]
    assert weird.encode_np(s).tolist() == weird.encode(s)


def test_bpe_native_on_adversarial_text():
    # repeated merges, unicode, whitespace runs, empty-ish words
    base = "aaaa bbbb aaaabbbb  \n\t ab ab ab abab ! ?? 'tis l'éclair 123"
    text = base * 200  # push over the 4096-char native threshold
    tok = ByteBPETokenizer.train(text, vocab_size=300)
    assert tok.encode_np(text).tolist() == tok.encode(text)


@pytest.mark.slow
def test_random_batcher_stream_unchanged_by_native(tiny_corpus):
    # the seeded token stream must not depend on which gather path runs
    from replicatinggpt_tpu.data.loader import RandomBatcher
    tok = CharTokenizer.from_text(tiny_corpus)
    data = tok.encode_np(tiny_corpus)
    b = RandomBatcher(data, 4, 16, seed=7)
    x, y = b.next_batch()
    rng = np.random.default_rng(7)
    ix = rng.integers(0, len(data) - 16, size=4)
    np.testing.assert_array_equal(x, np.stack([data[i:i + 16] for i in ix]))
    np.testing.assert_array_equal(
        y, np.stack([data[i + 1:i + 17] for i in ix]))
