"""Speculative-decoding tests (serve/speculative.py + the engine's
jitted multi-slot verify step): greedy token parity with offline
generate() for EVERY drafter, zero-recompile steady state over a
64-request speculative replay, accept-rate sanity on repetitive
prompts, drafter units, and the bench CPU-fallback contract."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replicatinggpt_tpu.config import ModelConfig
from replicatinggpt_tpu.models.gpt import (decode_step_multi, init_kv_cache,
                                           init_params, verify_step_multi)
from replicatinggpt_tpu.sample import GenerateConfig, generate
from replicatinggpt_tpu.serve import (Engine, EngineConfig, ModelDrafter,
                                      NGramDrafter, ReplayConfig, Request,
                                      SamplingParams, compile_counts,
                                      draft_config_from_preset, make_drafter,
                                      run_replay)
from replicatinggpt_tpu.serve.requests import (FINISH_LENGTH_CAP,
                                               FINISH_MAX_TOKENS)
from replicatinggpt_tpu.serve.speculative import DraftContext

CFG = ModelConfig(vocab_size=65, block_size=32, n_layer=2, n_head=2,
                  n_embd=32, dropout=0.0, attn_dropout=0.0, dtype="float32")
DRAFT_CFG = dataclasses.replace(CFG, n_layer=1)


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def draft_params():
    return init_params(jax.random.PRNGKey(1), DRAFT_CFG)


def _drafters(draft_params, pool):
    return {
        "ngram": lambda: NGramDrafter(k=4, ngram=3),
        # deliberately a BAD drafter (random init, different seed):
        # correctness must not depend on drafter quality, only speed does
        "model": lambda: ModelDrafter(draft_params, DRAFT_CFG, k=4,
                                      pool_size=pool),
    }


def _requests(n=6, greedy=True, seed=3, max_new=None):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        P = int(rng.integers(1, CFG.block_size // 2))
        prompt = rng.integers(0, CFG.vocab_size, (P,)).astype(np.int32)
        out.append(Request(
            id=f"r{i}", prompt=prompt,
            max_new_tokens=max_new or int(rng.integers(4, 14)),
            sampling=SamplingParams(greedy=greedy), rng_seed=i))
    return out


def _offline_greedy(params, reqs):
    return {r.id: np.asarray(generate(
        params, r.prompt[None, :], CFG,
        GenerateConfig(max_new_tokens=r.max_new_tokens, greedy=True))
    )[0].tolist() for r in reqs}


# ---------------------------------------------------------------------------
# parity: speculative greedy == offline generate, every drafter
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kind", ["ngram", "model"])
def test_spec_greedy_parity_every_drafter(params, draft_params, kind):
    """Speculative drain output must be token-for-token identical to
    offline generate() at temp=0 — acceptance/rejection/bonus paths
    must all reproduce the plain greedy stream exactly."""
    reqs = _requests(6)
    want = _offline_greedy(params, reqs)
    eng = Engine(params, CFG, EngineConfig(pool_size=3, max_queue=16),
                 drafter=_drafters(draft_params, 3)[kind]())
    for r in reqs:
        assert eng.submit(r) is None
    got = {r.id: r.tokens for r in eng.drain()}
    assert got == want


def test_spec_greedy_parity_packed_cache_layout(params):
    """verify_step_multi's packed (L,B,S,C) write/attend path must
    produce the same greedy tokens."""
    pc = dataclasses.replace(CFG, decode_cache_layout="packed")
    reqs = _requests(4)
    want = _offline_greedy(params, reqs)
    eng = Engine(params, pc, EngineConfig(pool_size=2, max_queue=8),
                 drafter=NGramDrafter(k=4))
    for r in reqs:
        assert eng.submit(r) is None
    got = {r.id: r.tokens for r in eng.drain()}
    assert got == want


def test_spec_length_cap_edge(params):
    """A slot whose window butts against the end of the cache buffer
    must clamp its draft count (never clamp-write past seq_len) and
    still match offline greedy up to the cap."""
    P = CFG.block_size - 4
    room = CFG.block_size - P + 1
    eng = Engine(params, CFG, EngineConfig(pool_size=1, max_queue=2),
                 drafter=NGramDrafter(k=4))
    assert eng.submit(Request(id="cap", prompt=np.ones((P,), np.int32),
                              max_new_tokens=100,
                              sampling=SamplingParams(greedy=True))) is None
    out = eng.drain()
    assert out[0].finish_reason == FINISH_LENGTH_CAP
    assert len(out[0].tokens) == room
    want = np.asarray(generate(
        params, np.ones((1, P), np.int32), CFG,
        GenerateConfig(max_new_tokens=room, greedy=True)))[0].tolist()
    assert out[0].tokens == want


def test_spec_continues_after_buffer_filling_request_finishes(params):
    """A released slot's stale frontier can sit at seq_len (a request
    that finished by filling its buffer); later speculative steps for
    OTHER slots must keep running — the window bound only constrains
    active slots (regression: the bounds check crashed every step after
    such a finish)."""
    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=4),
                 drafter=NGramDrafter(k=4))
    P = CFG.block_size - 2
    filler = Request(id="fill", prompt=np.ones((P,), np.int32),
                     max_new_tokens=100,
                     sampling=SamplingParams(greedy=True))
    longer = Request(id="long", prompt=np.array([3, 4], np.int32),
                     max_new_tokens=20,
                     sampling=SamplingParams(greedy=True))
    assert eng.submit(filler) is None
    assert eng.submit(longer) is None
    res = {r.id: r for r in eng.drain()}       # crashes without the fix
    assert res["fill"].finish_reason == FINISH_LENGTH_CAP
    assert len(res["long"].tokens) == 20
    want = np.asarray(generate(
        params, np.array([[3, 4]], np.int32), CFG,
        GenerateConfig(max_new_tokens=20, greedy=True)))[0].tolist()
    assert res["long"].tokens == want


def test_model_drafter_cache_stays_aligned(params):
    """With draft params == target params, greedy drafting must predict
    the target's greedy stream exactly — accept rate 1.0. This pins the
    draft-cache alignment property: the draft scan commits K/V for ALL
    k proposals, so a fully-accepted window leaves no stale position
    behind (regression: stopping the scan at k left d_k's K/V unwritten
    and degraded every later proposal after a full acceptance)."""
    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=8),
                 drafter=ModelDrafter(params, CFG, k=3, pool_size=2))
    for r in _requests(4, max_new=10, seed=11):
        assert eng.submit(r) is None
    eng.drain()
    assert eng.metrics_summary()["speculative"]["accept_rate"] == 1.0


def test_verify_step_multi_matches_decode_step_multi(params):
    """A W-wide verify window over already-committed tokens must score
    each position like the sequential decode steps it replaces (same
    math per row/position — the parity guarantee's foundation)."""
    B, W = 2, 3
    rng = np.random.default_rng(0)
    toks = rng.integers(0, CFG.vocab_size, (B, W + 1)).astype(np.int32)
    # sequential reference: W+1 single steps from position 0
    cache_s = init_kv_cache(CFG, B)
    seq_logits = []
    for j in range(W + 1):
        lg, cache_s = decode_step_multi(
            params, jnp.asarray(toks[:, j]),
            jnp.full((B,), j, jnp.int32), cache_s, CFG)
        seq_logits.append(np.asarray(lg))
    # one verify pass over the same window at base position 0
    cache_v = init_kv_cache(CFG, B)
    logits, cache_v = verify_step_multi(
        params, jnp.asarray(toks), jnp.zeros((B,), jnp.int32),
        jnp.full((B,), W, jnp.int32), cache_v, CFG)
    logits = np.asarray(logits)
    for j in range(W + 1):
        np.testing.assert_allclose(logits[:, j], seq_logits[j],
                                   atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(cache_v["k"]),
                               np.asarray(cache_s["k"]), atol=1e-6)


# ---------------------------------------------------------------------------
# stochastic speculation: reproducible, valid, completes
# ---------------------------------------------------------------------------

def test_spec_stochastic_reproducible_and_valid(params):
    def run():
        eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=16),
                     drafter=NGramDrafter(k=3))
        reqs = [Request(id=f"s{i}", prompt=np.array([7, 7, 7, 7], np.int32),
                        max_new_tokens=10,
                        sampling=SamplingParams(temperature=0.9, top_k=12),
                        rng_seed=42 + i) for i in range(3)]
        for r in reqs:
            assert eng.submit(r) is None
        return {r.id: r.tokens for r in eng.drain()}

    a, b = run(), run()
    assert a == b                       # per-slot rng chains, seeded
    assert all(len(t) == 10 for t in a.values())
    assert all(0 <= t < CFG.vocab_size for ts in a.values() for t in ts)


# ---------------------------------------------------------------------------
# steady state: zero recompiles over a 64-request speculative replay
# ---------------------------------------------------------------------------

def test_spec_steady_state_64_requests_zero_recompiles(params):
    """64-request replay with --spec semantics: zero new programs after
    the warmup engine (CompileGuard also enforces this live from inside
    every step — a recompile raises rather than just counting)."""
    rcfg = ReplayConfig(n_requests=64, rate=5000.0, seed=0,
                        prompt_len_max=12, max_new_tokens=6, greedy=True,
                        spec="ngram", spec_k=4)
    s = run_replay(params, CFG, rcfg,
                   EngineConfig(pool_size=8, max_queue=128))
    assert s["n_completed"] == 64
    assert s["recompiles_after_warmup"] == 0
    assert s["generated_tokens"] == 64 * 6
    assert s["compile_guards"]["verify"]["compiles"] <= 1
    assert s["speculative"]["drafter"] == "ngram"
    assert s["speculative"]["k"] == 4


# ---------------------------------------------------------------------------
# accept rate + tokens/step on a repetitive trace
# ---------------------------------------------------------------------------

def test_spec_accept_rate_repetitive_prompt(params):
    """On repetitive greedy traces the n-gram drafter should accept
    most drafts: accept_rate in (0, 1] and > 0.5, mean committed
    tokens per slot-step > 1.0 (the speculative multiplier; 1.0 exactly
    is plain decode)."""
    rcfg = ReplayConfig(n_requests=12, rate=5000.0, seed=2,
                        prompt_len_min=6, prompt_len_max=12,
                        max_new_tokens=12, greedy=True,
                        prompt_mode="repeat", spec="ngram", spec_k=4)
    s = run_replay(params, CFG, rcfg,
                   EngineConfig(pool_size=4, max_queue=32))
    sp = s["speculative"]
    assert 0.0 < sp["accept_rate"] <= 1.0
    assert sp["accept_rate"] > 0.5
    assert sp["mean_tokens_per_step"] > 1.0
    assert s["counters"]["spec_accepted_tokens"] > 0
    assert sp["draft_overhead_s"]["n"] > 0


def test_spec_metrics_in_summary(params, draft_params):
    """metrics_summary/replay must report accept_rate,
    mean_tokens_per_step and draft overhead next to TTFT/tok-s."""
    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=8),
                 drafter=ModelDrafter(draft_params, DRAFT_CFG, k=2,
                                      pool_size=2))
    for r in _requests(3, max_new=5):
        assert eng.submit(r) is None
    res = eng.drain()
    assert all(r.finish_reason == FINISH_MAX_TOKENS for r in res)
    s = eng.metrics_summary()
    sp = s["speculative"]
    assert sp["drafter"] == "model"
    assert sp["mean_tokens_per_step"] >= 1.0
    assert "accept_rate" in sp and "draft_overhead_s" in sp
    assert s["compile_guards"]["verify"]["compiles"] <= 1
    from replicatinggpt_tpu.serve import format_summary
    s.update(n_requests=3, n_completed=3, n_rejected=0,
             generated_tokens=sum(len(r.tokens) for r in res),
             wall_s=1.0, aggregate_tokens_per_s=1.0,
             recompiles_after_warmup=0)
    assert "accept rate" in format_summary(s)


# ---------------------------------------------------------------------------
# drafter units
# ---------------------------------------------------------------------------

def test_ngram_drafter_lookup():
    d = NGramDrafter(k=3, ngram=2)
    hist = np.array([5, 6, 7, 8, 9, 5, 6], np.int32)
    ctx = DraftContext(tok=np.array([6], np.int32),
                       pos=np.array([6], np.int32),
                       active=np.array([True]), histories=[hist])
    toks, lens = d.draft(ctx)
    # trailing 2-gram [5, 6] occurred at index 0; continuation 7, 8, 9
    assert lens[0] == 3
    assert toks[0].tolist() == [7, 8, 9]
    # no earlier occurrence -> nothing proposed
    ctx2 = DraftContext(tok=np.array([4], np.int32),
                        pos=np.array([3], np.int32),
                        active=np.array([True]),
                        histories=[np.array([1, 2, 3, 4], np.int32)])
    toks2, lens2 = d.draft(ctx2)
    assert lens2[0] == 0
    # inactive slots propose nothing
    ctx3 = DraftContext(tok=np.array([6], np.int32),
                        pos=np.array([6], np.int32),
                        active=np.array([False]), histories=[None])
    assert d.draft(ctx3)[1][0] == 0


def test_make_drafter_and_draft_preset():
    assert make_drafter("off", 4, 3, 2) is None
    d = make_drafter("ngram", 5, 2, 2)
    assert isinstance(d, NGramDrafter) and d.k == 5 and d.ngram == 2
    with pytest.raises(ValueError):
        make_drafter("model", 4, 3, 2)          # params/cfg required
    with pytest.raises(ValueError):
        make_drafter("bogus", 4, 3, 2)
    big = dataclasses.replace(CFG, vocab_size=101, block_size=64)
    dc = draft_config_from_preset(big, "test-tiny")
    assert dc.vocab_size == 101 and dc.block_size == 64
    assert dc.dtype == big.dtype


def test_engine_rejects_mismatched_draft_model(params, draft_params):
    bad_cfg = dataclasses.replace(DRAFT_CFG, vocab_size=66)
    bad_params = init_params(jax.random.PRNGKey(2), bad_cfg)
    with pytest.raises(AssertionError):
        Engine(params, CFG, EngineConfig(pool_size=2, max_queue=8),
               drafter=ModelDrafter(bad_params, bad_cfg, k=2, pool_size=2))


def test_cache_pool_positions_exposed(params):
    """CachePool.positions is the engine's live per-slot frontier —
    host data a drafter can read without any device sync."""
    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=8))
    prompt = np.arange(5, dtype=np.int32)
    assert eng.submit(Request(id="a", prompt=prompt, max_new_tokens=3,
                              sampling=SamplingParams(greedy=True))) is None
    eng.step()                            # admit + first decode
    slot = eng.pool.slot_of("a")
    assert eng.pool.positions[slot] == 5  # P-1 at admit, +1 per token
    eng.drain()


# ---------------------------------------------------------------------------
# serve-replay CLI with --spec
# ---------------------------------------------------------------------------

def test_serve_replay_cli_spec_smoke(capsys):
    from replicatinggpt_tpu.cli import main
    rc = main(["serve-replay", "--preset", "test-tiny", "--n-requests",
               "12", "--pool-size", "4", "--rate", "5000",
               "--request-max-new-tokens", "6", "--greedy",
               "--spec", "ngram", "--spec-k", "3",
               "--prompt-mode", "repeat"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "12 completed" in out
    assert "speculative (ngram, k=3)" in out
    assert "recompiles after warmup: 0" in out


# ---------------------------------------------------------------------------
# bench.py backend CPU fallback (satellite): a failed accelerator probe
# must degrade to a tagged CPU artifact, not a zero-valued error line
# ---------------------------------------------------------------------------

def test_bench_probe_fallback_tags_artifact(monkeypatch, capsys):
    import json
    import sys as _sys

    import bench

    monkeypatch.setattr(bench, "_EMITTED", False)
    monkeypatch.setattr(bench, "_EMIT_TAGS", {})
    calls = []

    def fake_probe(platform, tries, wait_s):
        calls.append(platform)
        if platform != "cpu":
            raise RuntimeError("backend unavailable after 5 probes: wedged")

    monkeypatch.setattr(bench, "probe_backend", fake_probe)
    monkeypatch.setattr(bench, "start_watchdog", lambda *a, **k: None)
    monkeypatch.setattr(bench, "bench_serve", lambda args: bench.emit(
        {"metric": "serve_replay_aggregate_tokens_per_sec", "value": 1.0,
         "unit": "tokens/sec", "vs_baseline": 0.0}))
    monkeypatch.setattr(_sys, "argv",
                        ["bench.py", "--mode", "serve", "--platform", "tpu"])
    prev_prng = jax.config.jax_default_prng_impl
    try:
        bench.main()
    finally:
        # bench.main flips the global PRNG impl; tests share the process
        jax.config.update("jax_default_prng_impl", prev_prng)
    line = capsys.readouterr().out.strip().splitlines()[-1]
    payload = json.loads(line)
    assert calls == ["tpu", "cpu"]      # accelerator probe, then fallback
    assert payload["backend"] == "cpu-fallback"
    assert payload["value"] == 1.0      # a real measurement, not zeros
    assert "error" not in payload
