"""HTTP/SSE front-door tests (serve/http.py): real sockets on
loopback, speaking real HTTP/1.1 against the asyncio server — SSE
token streaming with greedy parity, backpressure status mapping,
cancel (explicit and by client disconnect mid-stream, which must free
the slot and KV pages promptly), healthz, and Prometheus metrics."""

import asyncio
import json

import jax
import numpy as np
import pytest

from replicatinggpt_tpu.config import ModelConfig
from replicatinggpt_tpu.models.gpt import init_params
from replicatinggpt_tpu.sample import GenerateConfig, generate
from replicatinggpt_tpu.serve import EngineConfig, Router, RouterConfig
from replicatinggpt_tpu.serve.http import ServeApp

pytestmark = pytest.mark.fleet

CFG = ModelConfig(vocab_size=65, block_size=32, n_layer=2, n_head=2,
                  n_embd=32, dropout=0.0, attn_dropout=0.0,
                  dtype="float32")


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


async def _request(host, port, method, path, body=None):
    """One HTTP exchange; returns (status, parsed-or-raw body)."""
    r, w = await asyncio.open_connection(host, port)
    payload = json.dumps(body).encode() if body is not None else b""
    w.write(f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
            f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload)
    await w.drain()
    data = await r.read()
    w.close()
    await w.wait_closed()
    head, _, rest = data.partition(b"\r\n\r\n")
    status = int(head.split(b" ")[1])
    try:
        return status, json.loads(rest)
    except ValueError:
        return status, rest


def _sse_events(raw: bytes):
    """Parse an SSE byte stream into (event, data) pairs."""
    out = []
    for block in raw.decode().split("\n\n"):
        ev, data = "message", None
        for line in block.splitlines():
            if line.startswith("event: "):
                ev = line[len("event: "):]
            elif line.startswith("data: "):
                data = json.loads(line[len("data: "):])
        if data is not None:
            out.append((ev, data))
    return out


async def _stream(host, port, rid):
    r, w = await asyncio.open_connection(host, port)
    w.write(f"GET /v1/stream/{rid} HTTP/1.1\r\nHost: t\r\n\r\n".encode())
    await w.drain()
    data = await r.read()
    w.close()
    await w.wait_closed()
    body = data.partition(b"\r\n\r\n")[2]
    return _sse_events(body)


def _app(params, **router_kw):
    router = Router(params, CFG,
                    RouterConfig(**{"n_replicas": 1, **router_kw}),
                    EngineConfig(pool_size=2, max_queue=4))
    return ServeApp(router)


def _offline(params, prompt, n):
    return np.asarray(generate(
        params, np.asarray(prompt, np.int32)[None, :], CFG,
        GenerateConfig(max_new_tokens=n, greedy=True)))[0].tolist()


def test_submit_stream_greedy_parity(params):
    """Submit + SSE stream: the delivered token sequence equals offline
    greedy generate, ends with one done event, and the id is freed
    after delivery."""
    want = _offline(params, [1, 2, 3], 8)

    async def main():
        app = _app(params, n_replicas=2)
        host, port = await app.start()
        try:
            st, body = await _request(
                host, port, "POST", "/v1/submit",
                {"id": "a", "prompt": [1, 2, 3], "max_new_tokens": 8,
                 "greedy": True})
            assert st == 200 and body["status"] == "accepted"
            events = await _stream(host, port, "a")
            toks = [d["token"] for ev, d in events if ev == "message"]
            done = [d for ev, d in events if ev == "done"]
            assert toks == want
            assert len(done) == 1
            assert done[0]["finish_reason"] == "max_tokens"
            assert done[0]["n_tokens"] == 8
            # delivered -> popped -> unknown now
            st, _ = await _request(host, port, "GET", "/v1/result/a")
            assert st == 404
        finally:
            await app.stop()

    asyncio.run(main())


def test_generate_roundtrip_and_result_endpoint(params):
    async def main():
        app = _app(params)
        host, port = await app.start()
        try:
            # one-shot generate: submit + stream in one response
            r, w = await asyncio.open_connection(host, port)
            payload = json.dumps({"prompt": [5, 6], "max_new_tokens": 4,
                                  "greedy": True}).encode()
            w.write(b"POST /v1/generate HTTP/1.1\r\nHost: t\r\n"
                    + f"Content-Length: {len(payload)}\r\n\r\n".encode()
                    + payload)
            await w.drain()
            data = await r.read()
            w.close()
            await w.wait_closed()
            events = _sse_events(data.partition(b"\r\n\r\n")[2])
            toks = [d["token"] for ev, d in events if ev == "message"]
            assert toks == _offline(params, [5, 6], 4)
            # non-streaming path: submit then poll the result endpoint
            st, _ = await _request(host, port, "POST", "/v1/submit",
                                   {"id": "poll", "prompt": [9],
                                    "max_new_tokens": 3, "greedy": True})
            assert st == 200
            while True:
                st, body = await _request(host, port, "GET",
                                          "/v1/result/poll")
                if st == 200:
                    break
                assert st == 202
                await asyncio.sleep(0.01)
            assert body["tokens"] == _offline(params, [9], 3)
        finally:
            await app.stop()

    asyncio.run(main())


def test_backpressure_and_validation_status_codes(params):
    async def main():
        app = _app(params)      # pool 2, queue 4
        host, port = await app.start()
        # freeze the fleet while the submit storm lands so the bounded
        # queue's backpressure is deterministic (the driver would
        # otherwise race the storm and drain between round trips)
        real_step = app.router.step
        app.router.step = lambda: []
        try:
            statuses = []
            for i in range(12):
                st, _ = await _request(
                    host, port, "POST", "/v1/submit",
                    {"id": f"b{i}", "prompt": [1, 2],
                     "max_new_tokens": 20, "greedy": True})
                statuses.append(st)
            assert statuses[:4] == [200] * 4     # max_queue accepted
            assert set(statuses[4:]) == {429}    # the rest pushed back
            st, body = await _request(host, port, "POST", "/v1/submit",
                                      {"prompt": []})
            assert st == 400                 # empty prompt
            st, _ = await _request(host, port, "POST", "/v1/submit",
                                   {"prompt": [1] * 100,
                                    "max_new_tokens": 2})
            assert st == 413                 # prompt > block_size
            st, _ = await _request(host, port, "POST", "/v1/submit",
                                   {"prompt": "nope"})
            assert st == 400
            # non-numeric deadline_s: a 400, not a dropped connection
            st, body = await _request(host, port, "POST", "/v1/submit",
                                      {"prompt": [1],
                                       "deadline_s": "ten"})
            assert st == 400 and "bad request field" in body["error"]
            # out-of-range token id: the embedding gather would clamp
            # it silently — the front door must 400 it instead
            st, body = await _request(host, port, "POST", "/v1/submit",
                                      {"prompt": [1, 10_000],
                                       "max_new_tokens": 2})
            assert st == 400 and "[0, 65)" in body["error"]
            # bools pass isinstance(int) — they are not token ids
            st, _ = await _request(host, port, "POST", "/v1/submit",
                                   {"prompt": [True]})
            assert st == 400
            # malformed Content-Length: a 400 response, not an
            # uncaught ValueError dropping the socket
            r, w = await asyncio.open_connection(host, port)
            w.write(b"POST /v1/submit HTTP/1.1\r\nHost: t\r\n"
                    b"Content-Length: ten\r\n\r\n")
            await w.drain()
            data = await r.read()
            w.close()
            await w.wait_closed()
            assert b" 400 " in data.split(b"\r\n", 1)[0]
            assert b"malformed request" in data
            st, _ = await _request(host, port, "GET",
                                   "/v1/stream/nonexistent")
            assert st == 404
            st, _ = await _request(host, port, "GET", "/no/such/route")
            assert st == 404
            # duplicate in-flight id -> 400 (fleet-wide dedupe; b0 is
            # pinned in the frozen queue, so this is deterministic)
            st, body = await _request(
                host, port, "POST", "/v1/submit",
                {"id": "b0", "prompt": [3], "max_new_tokens": 2})
            assert st == 400
            assert body["error"] == "rejected_bad_request"
        finally:
            app.router.step = real_step
            await app.stop()

    asyncio.run(main())


def test_cancel_endpoint_mid_stream(params):
    """Explicit cancel of a long-running request: the stream closes
    with a done event carrying finish_reason=cancelled and the partial
    token count; the slot frees for the next request."""
    async def main():
        app = _app(params)
        host, port = await app.start()
        router = app.router
        try:
            st, _ = await _request(host, port, "POST", "/v1/submit",
                                   {"id": "long", "prompt": [1],
                                    "max_new_tokens": 28,
                                    "greedy": True})
            assert st == 200
            stream_task = asyncio.ensure_future(
                _stream(host, port, "long"))
            while not (router.take_new_tokens("long") or
                       router.result("long")):
                await asyncio.sleep(0.005)
            st, body = await _request(host, port, "POST",
                                      "/v1/cancel/long")
            assert st == 200 and body["cancelled"]
            events = await stream_task
            done = [d for ev, d in events if ev == "done"]
            assert len(done) == 1
            assert done[0]["finish_reason"] == "cancelled"
            eng = router.replicas[0].engine
            # slot + pages released (radix-cached prefix pages may stay)
            assert eng.pool.n_free == eng.pool.n_slots
        finally:
            await app.stop()

    asyncio.run(main())


def test_client_disconnect_mid_stream_releases_slot_and_pages(params):
    """The satellite behavior at the HTTP layer: a client that vanishes
    mid-SSE cancels its request — the engine releases the slot and its
    reserved KV pages promptly, not at what would have been
    completion."""
    async def main():
        app = _app(params)
        host, port = await app.start()
        router = app.router
        eng = router.replicas[0].engine
        try:
            st, _ = await _request(host, port, "POST", "/v1/submit",
                                   {"id": "gone", "prompt": [2, 3],
                                    "max_new_tokens": 28,
                                    "greedy": True})
            assert st == 200
            r, w = await asyncio.open_connection(host, port)
            w.write(b"GET /v1/stream/gone HTTP/1.1\r\nHost: t\r\n\r\n")
            await w.drain()
            await r.readuntil(b"data: ")      # first token is flowing
            pages_held = eng.pool.alloc.pages_in_use
            assert pages_held > 0 and eng.pool.n_free < eng.pool.n_slots
            # vanish mid-stream (RST, not graceful close)
            sock = w.get_extra_info("socket")
            import socket as socketmod
            sock.setsockopt(socketmod.SOL_SOCKET, socketmod.SO_LINGER,
                            b"\x01\x00\x00\x00\x00\x00\x00\x00")
            w.close()
            for _ in range(400):
                if (eng.pool.n_free == eng.pool.n_slots
                        and not eng._active.any()):
                    break
                await asyncio.sleep(0.005)
            assert eng.pool.n_free == eng.pool.n_slots
            assert not eng._active.any()
            # reserved (non-radix) pages are back: only refcount-0
            # radix-cached prefix pages may remain resident
            assert (eng.pool.alloc.ref > 0).sum() == 0
        finally:
            await app.stop()

    asyncio.run(main())


def test_client_disconnect_pops_terminal_result(params):
    """Regression: a client that vanished mid-SSE used to leak its
    terminal result forever — the cancelled RequestResult surfaced on a
    later step with nobody left to pop it, growing results/_delivered/
    _ttft by one entry per disconnect. The driver's abandoned sweep
    must pop it the moment it surfaces."""
    async def main():
        app = _app(params)
        host, port = await app.start()
        router = app.router
        try:
            st, _ = await _request(host, port, "POST", "/v1/submit",
                                   {"id": "leak", "prompt": [2, 3],
                                    "max_new_tokens": 28,
                                    "greedy": True})
            assert st == 200
            r, w = await asyncio.open_connection(host, port)
            w.write(b"GET /v1/stream/leak HTTP/1.1\r\nHost: t\r\n\r\n")
            await w.drain()
            await r.readuntil(b"data: ")      # stream is flowing
            sock = w.get_extra_info("socket")
            import socket as socketmod
            sock.setsockopt(socketmod.SOL_SOCKET, socketmod.SO_LINGER,
                            b"\x01\x00\x00\x00\x00\x00\x00\x00")
            w.close()                         # vanish (RST)
            for _ in range(600):
                if (not app._abandoned
                        and not router.knows("leak")):
                    break
                await asyncio.sleep(0.005)
            assert "leak" not in router.results
            assert "leak" not in router._delivered
            assert "leak" not in router._ttft
            assert not app._abandoned
        finally:
            await app.stop()

    asyncio.run(main())


def test_driver_death_is_loud_and_fails_server(params):
    """Regression: an exception from router.step() used to sit in the
    never-awaited driver future while the server kept accepting
    connections that could never complete. The done-callback must mark
    the app not running, fail the step future (waking blocked SSE
    handlers with the error), close the listener, and stop() must
    re-raise the original exception."""
    async def main():
        app = _app(params)
        host, port = await app.start()
        boom = RuntimeError("scheduler invariant violated")

        def exploding_step():
            raise boom

        app.router.step = exploding_step
        st, _ = await _request(host, port, "POST", "/v1/submit",
                               {"id": "d", "prompt": [1],
                                "max_new_tokens": 4, "greedy": True})
        assert st == 200          # accepted before the step explodes
        for _ in range(400):
            if not app._running:
                break
            await asyncio.sleep(0.005)
        assert not app._running
        assert app._driver.done()
        # blocked waiters get the failure instead of spinning
        assert app._step_fut.done()
        assert app._step_fut.exception() is boom
        # the listener is closed: new connections are refused
        with pytest.raises(OSError):
            await asyncio.open_connection(host, port)
        with pytest.raises(RuntimeError, match="scheduler invariant"):
            await app.stop()

    asyncio.run(main())


def test_stream_drains_ledger_before_done_event():
    """The drain()-suspension race: if the request finishes (with more
    tokens) while the SSE handler is suspended in writer.drain(), the
    handler must drain the delivery ledger once more before emitting
    `done` — otherwise the tail tokens are silently dropped while
    done.n_tokens still counts them."""
    from replicatinggpt_tpu.serve.requests import RequestResult

    class ScriptedRouter:
        """The handler takes [7], suspends in drain(), and by the time
        it polls result() the request is terminal with tokens [8, 9]
        still undelivered — they must come out of the final ledger
        drain, not be dropped."""

        def __init__(self):
            self._takes = [[7], [8, 9]]
            self._results = [RequestResult(
                id="r", tokens=[7, 8, 9], finish_reason="max_tokens")]
            self.popped = False

        def take_new_tokens(self, rid):
            return self._takes.pop(0) if self._takes else []

        def result(self, rid):
            return self._results.pop(0) if self._results else None

        def pop_result(self, rid):
            self.popped = True

        def knows(self, rid):
            return True

    class FakeWriter:
        def __init__(self):
            self.data = b""

        def write(self, b):
            self.data += b

        async def drain(self):
            pass

    router = ScriptedRouter()
    app = ServeApp.__new__(ServeApp)       # no server/driver needed
    app.router = router
    app.idle_sleep_s = 0.0
    app.step_wait_s = 0.0
    app.idle_timeout_s = 0.0
    app._step_fut = None
    w = FakeWriter()
    asyncio.run(app._stream("r", w))
    events = _sse_events(w.data.partition(b"\r\n\r\n")[2])
    toks = [d["token"] for ev, d in events if ev == "message"]
    done = [d for ev, d in events if ev == "done"]
    assert toks == [7, 8, 9]               # tail NOT dropped
    assert len(done) == 1 and done[0]["n_tokens"] == 3
    assert router.popped


def test_serve_cli_subprocess_smoke(tmp_path):
    """`python -m replicatinggpt_tpu serve` end to end in a real
    subprocess: binds an ephemeral port, answers /healthz, completes a
    /v1/generate round trip over SSE, and shuts down cleanly on
    SIGINT (closing the per-replica journals)."""
    import http.client
    import os
    import re
    import signal
    import subprocess
    import sys
    import time

    jdir = tmp_path / "journals"
    jdir.mkdir()
    sink = tmp_path / "events.jsonl"
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", "replicatinggpt_tpu", "serve",
         "--preset", "test-tiny", "--replicas", "2", "--port", "0",
         "--pool-size", "2", "--journal-dir", str(jdir),
         "--trace-jsonl", str(sink)],
        stderr=subprocess.PIPE, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    try:
        port = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stderr.readline()
            if not line and proc.poll() is not None:
                raise AssertionError("serve exited before binding")
            m = re.search(r"serving on http://[\d.]+:(\d+)", line)
            if m:
                port = int(m.group(1))
                break
        assert port is not None, "never saw the serving banner"

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/healthz")
        r = conn.getresponse()
        health = json.loads(r.read())
        assert r.status == 200 and health["ok"]
        assert len(health["replicas"]) == 2

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request("POST", "/v1/generate", json.dumps(
            {"prompt": [1, 2, 3], "max_new_tokens": 4, "greedy": True}))
        r = conn.getresponse()
        assert r.status == 200
        assert r.getheader("Content-Type") == "text/event-stream"
        events = _sse_events(r.read())
        toks = [d["token"] for ev, d in events if ev == "message"]
        done = [d for ev, d in events if ev == "done"]
        assert len(toks) == 4
        assert len(done) == 1 and done[0]["finish_reason"] == "max_tokens"

        proc.send_signal(signal.SIGINT)
        rc = proc.wait(timeout=30)
        assert rc == 0
        # journals exist and are closed with the submit+finish records
        recs = (jdir / "replica0.jsonl").read_text() \
            + (jdir / "replica1.jsonl").read_text()
        assert '"ev": "submit"' in recs and '"ev": "finish"' in recs
        # --trace-jsonl alone (no --trace-out) must produce the sink
        evs = sink.read_text()
        assert '"request"' in evs and '"router_step"' in evs
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
        proc.stderr.close()


@pytest.mark.multiproc
@pytest.mark.slow
def test_serve_cli_multiproc_subprocess_smoke(tmp_path):
    """`python -m replicatinggpt_tpu serve --multiproc` end to end:
    the serve process spawns a real worker subprocess, /readyz gates
    on the warmed worker, a /v1/generate SSE round trip decodes
    through the RPC protocol, and SIGINT shuts the whole tree down
    (worker journal lock freed, records flushed)."""
    import http.client
    import os
    import re
    import signal
    import subprocess
    import sys
    import time

    jdir = tmp_path / "journals"
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", "replicatinggpt_tpu", "serve",
         "--preset", "test-tiny", "--replicas", "1", "--port", "0",
         "--pool-size", "2", "--multiproc",
         "--journal-dir", str(jdir)],
        stderr=subprocess.PIPE, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    try:
        port = None
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            line = proc.stderr.readline()
            if not line and proc.poll() is not None:
                raise AssertionError("serve exited before binding")
            m = re.search(r"serving on http://[\d.]+:(\d+)", line)
            if m:
                port = int(m.group(1))
                break
        assert port is not None, "never saw the serving banner"

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/readyz")
        r = conn.getresponse()
        ready = json.loads(r.read())
        assert r.status == 200 and ready["ok"], ready

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request("POST", "/v1/generate", json.dumps(
            {"prompt": [1, 2, 3], "max_new_tokens": 4, "greedy": True}))
        r = conn.getresponse()
        assert r.status == 200
        events = _sse_events(r.read())
        toks = [d["token"] for ev, d in events if ev == "message"]
        done = [d for ev, d in events if ev == "done"]
        assert len(toks) == 4
        assert len(done) == 1 and done[0]["finish_reason"] == "max_tokens"

        proc.send_signal(signal.SIGINT)
        rc = proc.wait(timeout=60)
        assert rc == 0
        # per-worker PRIVATE dir (no shared journal files): the
        # worker's journal + the router's own ledger both flushed
        wjournal = jdir / "worker0" / "journal.jsonl"
        recs = wjournal.read_text()
        assert '"ev": "submit"' in recs and '"ev": "finish"' in recs
        ledger = (jdir / "router_ledger.jsonl").read_text()
        assert '"ev": "submit"' in ledger and '"ev": "finish"' in ledger
        # the worker process died with the tree: its flock is free
        from replicatinggpt_tpu.serve import RequestJournal
        RequestJournal(str(wjournal), lock=True).close()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
        proc.stderr.close()


def test_healthz_readyz_and_metrics(params):
    """Liveness vs readiness: /healthz answers 200 whenever the server
    process is up (external supervisors RESTART on its failure);
    /readyz answers 200 iff >= 1 routable warmed replica can take
    traffic (load balancers GATE on it) — 503 through a drain of every
    replica, 200 again on undrain, and still 503-from-readyz (but
    200-from-healthz) once every replica is dead."""
    async def main():
        app = _app(params, n_replicas=2)
        host, port = await app.start()
        router = app.router
        try:
            st, body = await _request(host, port, "GET", "/healthz")
            assert st == 200 and body["ok"] and body["live"]
            assert len(body["replicas"]) == 2
            assert {"alive", "wedged", "queue_depth", "slots_active",
                    "pages_in_use"} <= set(body["replicas"][0])
            st, body = await _request(host, port, "GET", "/readyz")
            assert st == 200 and body["ok"]
            assert body["ready_replicas"] == 2
            st, _ = await _request(host, port, "POST", "/v1/submit",
                                   {"id": "m", "prompt": [4],
                                    "max_new_tokens": 2,
                                    "greedy": True})
            assert st == 200
            st, raw = await _request(host, port, "GET", "/metrics")
            assert st == 200
            text = raw.decode()
            assert "tpu_gpt_fleet_fleet_requests_routed" in text
            assert "tpu_gpt_fleet_replica0_queue_depth" in text
            # drain every replica (the single-survivor rolling-restart
            # window): NOT ready, but still very much alive
            router.drain_replica(0)
            router.drain_replica(1)
            st, body = await _request(host, port, "GET", "/readyz")
            assert st == 503 and not body["ok"]
            assert body["draining"] == [0, 1]
            st, body = await _request(host, port, "GET", "/healthz")
            assert st == 200 and body["live"]
            router.undrain_replica(0)
            st, body = await _request(host, port, "GET", "/readyz")
            assert st == 200 and body["ready_replicas"] == 1
            # both replicas dead: readiness 503, liveness still 200 —
            # restarting the ROUTER would not help a dead fleet
            router.undrain_replica(1)
            router._kill(0, router.n_steps)
            router._kill(1, router.n_steps)
            st, body = await _request(host, port, "GET", "/readyz")
            assert st == 503 and body["n_alive"] == 0
            st, body = await _request(host, port, "GET", "/healthz")
            assert st == 200 and body["live"]
        finally:
            await app.stop()

    asyncio.run(main())


def test_slow_loris_connections_are_dropped(params):
    """The idle-socket satellite: a peer that never completes its
    headers, or promises a body it never sends, is answered 408 and
    dropped after idle_timeout_s instead of pinning a handler task
    forever. A fast client on the same server is unaffected."""
    async def main():
        app = _app(params)
        app.idle_timeout_s = 0.3
        host, port = await app.start()
        try:
            # stall mid-headers
            r, w = await asyncio.open_connection(host, port)
            w.write(b"POST /v1/submit HTTP/1.1\r\nHost:")   # never \r\n\r\n
            await w.drain()
            t0 = asyncio.get_event_loop().time()
            data = await asyncio.wait_for(r.read(), timeout=10)
            took = asyncio.get_event_loop().time() - t0
            assert b" 408 " in data.split(b"\r\n", 1)[0]
            assert b"request idle timeout" in data
            assert took < 5, f"loris held the handler {took:.1f}s"
            w.close()
            await w.wait_closed()
            # stall mid-body (Content-Length promised, body withheld)
            r, w = await asyncio.open_connection(host, port)
            w.write(b"POST /v1/submit HTTP/1.1\r\nHost: t\r\n"
                    b"Content-Length: 64\r\n\r\n{\"pro")
            await w.drain()
            data = await asyncio.wait_for(r.read(), timeout=10)
            assert b" 408 " in data.split(b"\r\n", 1)[0]
            w.close()
            await w.wait_closed()
            # an honest client still gets served
            st, body = await _request(host, port, "GET", "/healthz")
            assert st == 200 and body["ok"]
        finally:
            await app.stop()

    asyncio.run(main())
