"""Model tests: shapes, causality, determinism, config flavors, scan vs
unrolled equivalence, init statistics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replicatinggpt_tpu.config import ModelConfig
from replicatinggpt_tpu.models.gpt import (forward, init_params, param_count)

TINY = ModelConfig(vocab_size=65, block_size=32, n_layer=2, n_head=2,
                   n_embd=32, dropout=0.0, attn_dropout=0.0,
                   dtype="float32")


def _batch(cfg, B=4, seed=0):
    return jax.random.randint(jax.random.PRNGKey(seed), (B, cfg.block_size),
                              0, cfg.vocab_size)


def test_forward_shapes_and_loss():
    params = init_params(jax.random.PRNGKey(0), TINY)
    x = _batch(TINY)
    logits, loss = forward(params, x, TINY, targets=x)
    assert logits.shape == (4, TINY.block_size, TINY.vocab_size)
    assert logits.dtype == jnp.float32
    # random init → loss near ln(vocab)
    assert abs(float(loss) - np.log(TINY.vocab_size)) < 0.5


def test_forward_without_targets_returns_none_loss():
    params = init_params(jax.random.PRNGKey(0), TINY)
    logits, loss = forward(params, _batch(TINY), TINY)
    assert loss is None


def test_causality():
    """Changing token t must not change logits at positions < t."""
    params = init_params(jax.random.PRNGKey(0), TINY)
    x = _batch(TINY)
    base, _ = forward(params, x, TINY)
    t = TINY.block_size // 2
    x2 = x.at[:, t].set((x[:, t] + 1) % TINY.vocab_size)
    pert, _ = forward(params, x2, TINY)
    np.testing.assert_allclose(base[:, :t], pert[:, :t], atol=1e-5)
    # and position t itself must change (attention is not degenerate)
    assert not np.allclose(base[:, t], pert[:, t], atol=1e-5)


def test_shorter_sequence_than_block_size():
    params = init_params(jax.random.PRNGKey(0), TINY)
    x = _batch(TINY)[:, :7]
    logits, _ = forward(params, x, TINY)
    assert logits.shape == (4, 7, TINY.vocab_size)


@pytest.mark.slow
def test_dropout_rng_determinism():
    cfg = ModelConfig(vocab_size=65, block_size=16, n_layer=2, n_head=2,
                      n_embd=32, dropout=0.5, attn_dropout=0.5,
                      dtype="float32")
    params = init_params(jax.random.PRNGKey(0), cfg)
    x = _batch(cfg)
    r = jax.random.PRNGKey(42)
    a, _ = forward(params, x, cfg, rng=r, train=True)
    b, _ = forward(params, x, cfg, rng=r, train=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c, _ = forward(params, x, cfg, rng=jax.random.PRNGKey(43), train=True)
    assert not np.allclose(a, c)
    # eval path ignores dropout entirely
    d, _ = forward(params, x, cfg, rng=None, train=False)
    e, _ = forward(params, x, cfg, rng=r, train=False)
    np.testing.assert_array_equal(np.asarray(d), np.asarray(e))


def test_dropout_keep_rate_and_unbiasedness():
    """_dropout draws uint8 bits and thresholds at round(rate*256): the
    empirical keep rate must match the quantized rate and the inverted
    scaling must keep the estimator exactly unbiased."""
    from replicatinggpt_tpu.models.gpt import _dropout

    rate = 0.2
    t = int(round(rate * 256))
    q = t / 256.0
    x = jnp.ones((512, 512), jnp.float32)
    y = np.asarray(_dropout(x, rate, jax.random.PRNGKey(0), train=True))
    keep_frac = (y != 0).mean()
    assert abs(keep_frac - (1.0 - q)) < 0.005, keep_frac
    # kept entries carry exactly the quantized inverse-keep scale
    np.testing.assert_allclose(y[y != 0], 1.0 / (1.0 - q), rtol=1e-6)
    assert abs(y.mean() - 1.0) < 0.01, y.mean()
    # rate 0 / eval are identity
    np.testing.assert_array_equal(
        np.asarray(_dropout(x, 0.0, jax.random.PRNGKey(0), True)),
        np.asarray(x))
    np.testing.assert_array_equal(
        np.asarray(_dropout(x, rate, jax.random.PRNGKey(0), False)),
        np.asarray(x))


def test_tied_vs_untied_head():
    tied = init_params(jax.random.PRNGKey(0), TINY)
    assert "lm_head" not in tied  # GPT-2.py:104 tying
    untied_cfg = ModelConfig(**{**TINY.__dict__, "tied_head": False})
    untied = init_params(jax.random.PRNGKey(0), untied_cfg)
    assert untied["lm_head"].shape == (TINY.n_embd, TINY.vocab_size)
    # tied model: wte grad flows from head — param counts differ by V*C
    assert (param_count(untied) - param_count(tied)
            == TINY.vocab_size * TINY.n_embd)


def test_relu_vs_gelu_differ():
    relu_cfg = ModelConfig(**{**TINY.__dict__, "activation": "relu"})
    params = init_params(jax.random.PRNGKey(0), TINY)
    x = _batch(TINY)
    a, _ = forward(params, x, TINY)
    b, _ = forward(params, x, relu_cfg)
    assert not np.allclose(a, b)


def test_scan_vs_unrolled_equivalence():
    unroll_cfg = ModelConfig(**{**TINY.__dict__, "scan_layers": False})
    params = init_params(jax.random.PRNGKey(0), TINY)
    x = _batch(TINY)
    a, _ = forward(params, x, TINY)
    b, _ = forward(params, x, unroll_cfg)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.slow
def test_remat_matches_no_remat():
    remat_cfg = ModelConfig(**{**TINY.__dict__, "remat": True})
    params = init_params(jax.random.PRNGKey(0), TINY)
    x = _batch(TINY)
    a, la = forward(params, x, TINY, targets=x)
    b, lb = forward(params, x, remat_cfg, targets=x)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    # gradients must match too (remat is a pure recompute transform)
    from replicatinggpt_tpu.train.steps import loss_fn
    ga = jax.grad(loss_fn)(params, (x, x), TINY)
    gb = jax.grad(loss_fn)(params, (x, x), remat_cfg)
    for pa, pb in zip(jax.tree_util.tree_leaves(ga),
                      jax.tree_util.tree_leaves(gb)):
        np.testing.assert_allclose(np.asarray(pa), np.asarray(pb), atol=1e-4)


def test_init_statistics():
    cfg = ModelConfig(vocab_size=256, block_size=64, n_layer=4, n_head=4,
                      n_embd=128, dtype="float32")
    params = init_params(jax.random.PRNGKey(0), cfg)
    std = float(jnp.std(params["wte"]))
    assert 0.015 < std < 0.025  # 0.02 init (GPT-2 paper)
    # residual projections scaled down by sqrt(2L)
    proj_std = float(jnp.std(params["blocks"]["attn_out_kernel"]))
    assert proj_std < 0.012
    assert float(jnp.abs(params["blocks"]["qkv_bias"]).max()) == 0.0


def test_bf16_forward_finite():
    cfg = ModelConfig(**{**TINY.__dict__, "dtype": "bfloat16"})
    params = init_params(jax.random.PRNGKey(0), cfg)
    logits, loss = forward(params, _batch(cfg), cfg, targets=_batch(cfg))
    assert logits.dtype == jnp.float32  # loss path always f32
    assert np.isfinite(float(loss))


def test_remat_policy_numerics_and_validation():
    """remat_policy only changes what is saved vs recomputed — loss and
    grads must match the full-remat path exactly; bad names fail loudly."""
    import dataclasses

    import pytest as _pytest

    from replicatinggpt_tpu.config import ModelConfig
    from replicatinggpt_tpu.models.gpt import forward, init_params

    base = ModelConfig(vocab_size=64, block_size=32, n_layer=2, n_head=2,
                       n_embd=64, dropout=0.0, attn_dropout=0.0,
                       dtype="float32", remat=True)
    params = init_params(jax.random.PRNGKey(0), base)
    x = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
    y = jax.random.randint(jax.random.PRNGKey(2), (2, 32), 0, 64)

    def loss_for(policy):
        cfg = dataclasses.replace(base, remat_policy=policy)

        def loss(p):
            _, l = forward(p, x, cfg, targets=y)
            return l

        return loss

    l_full, g_full = jax.value_and_grad(loss_for("full"))(params)
    for policy in ("dots", "dots_no_batch"):
        l_p, g_p = jax.value_and_grad(loss_for(policy))(params)
        np.testing.assert_allclose(float(l_p), float(l_full), rtol=1e-6)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
            g_p, g_full)

    with _pytest.raises(ValueError, match="remat_policy"):
        jax.value_and_grad(loss_for("typo"))(params)


def test_chunked_ce_loss_matches_one_shot():
    """cfg.loss_chunk computes the same training loss AND gradients as
    the one-shot logits head (per-row CE is independent under softmax;
    only the final mean's f32 reduction order differs), returning
    (None, loss) — the full logits array is never built."""
    import dataclasses

    import jax
    import numpy as np

    from replicatinggpt_tpu.config import ModelConfig
    from replicatinggpt_tpu.models.gpt import forward, init_params

    cfg = ModelConfig(vocab_size=97, block_size=16, n_layer=2, n_head=2,
                      n_embd=64, dropout=0.0, attn_dropout=0.0,
                      dtype="float32")
    ccfg = dataclasses.replace(cfg, loss_chunk=8)  # B*T=64 rows, 8 chunks
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.integers(0, 97, (4, 16)), jnp.int32)
    y = jnp.asarray(rng.integers(0, 97, (4, 16)), jnp.int32)

    def loss(p, c):
        lg, l = forward(p, x, c, targets=y)
        if c.loss_chunk:
            assert lg is None
        else:
            assert lg is not None
        return l

    l0, g0 = jax.value_and_grad(lambda p: loss(p, cfg))(params)
    l1, g1 = jax.value_and_grad(lambda p: loss(p, ccfg))(params)
    np.testing.assert_allclose(float(l1), float(l0), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        g1, g0)

    # non-divisible chunk must fail loudly: a silent fallback would let
    # an A/B arm measure the one-shot head while claiming the chunked one
    nd = dataclasses.replace(cfg, loss_chunk=7)
    with pytest.raises(ValueError, match="loss_chunk"):
        forward(params, x, nd, targets=y)


def test_chunked_ce_through_train_step():
    """One jitted train step with loss_chunk on: finite loss, params
    move, loss matches the unchunked step's at the first step."""
    import dataclasses

    import jax
    import numpy as np

    from replicatinggpt_tpu.config import ModelConfig, TrainConfig
    from replicatinggpt_tpu.train.state import create_train_state
    from replicatinggpt_tpu.train.steps import make_train_step

    cfg = ModelConfig(vocab_size=97, block_size=16, n_layer=2, n_head=2,
                      n_embd=64, dropout=0.0, attn_dropout=0.0,
                      dtype="float32")
    tcfg = TrainConfig(batch_size=4, lr=1e-3)
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.integers(0, 97, (4, 16)), jnp.int32)
    batch = (x, jnp.asarray(rng.integers(0, 97, (4, 16)), jnp.int32))

    losses = {}
    for chunk in (0, 16):
        c = dataclasses.replace(cfg, loss_chunk=chunk)
        state = create_train_state(jax.random.PRNGKey(0), c, tcfg)
        step = make_train_step(c, tcfg, donate=False)
        new_state, metrics = step(state, batch)
        l = float(jax.device_get(metrics["loss"]))
        assert np.isfinite(l)
        assert not np.allclose(np.asarray(new_state.params["wte"]),
                               np.asarray(state.params["wte"]))
        losses[chunk] = l
    np.testing.assert_allclose(losses[16], losses[0], rtol=1e-6)
