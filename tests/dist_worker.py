"""Subprocess worker for the REAL multi-process distributed tests.

Each instance is one `jax.distributed` process (CPU backend, gloo
cross-process collectives). It runs the full training runner — global-batch
assembly via make_array_from_process_local_data, DP grad psum under GSPMD,
multi-host logging gate, checkpoint-boundary stop agreement — and the
coordinator dumps a JSON summary (end step, per-leaf param sums of squares,
eval history) for the parent test to compare against a single-process run.

Sequential sampling is forced so the assembled global token stream is
bit-identical for any process count (SequentialBatcher's sharded-cursor
contract), making final params directly comparable.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--process-id", type=int, required=True)
    p.add_argument("--num-processes", type=int, required=True)
    p.add_argument("--port", required=True)
    p.add_argument("--out", required=True)
    p.add_argument("--max-iters", type=int, default=20)
    p.add_argument("--steps-per-dispatch", type=int, default=1)
    p.add_argument("--grad-accum-steps", type=int, default=1)
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-every", type=int, default=0)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--stop-on-proc", type=int, default=-1,
                   help="process whose stop_event reads set from step 0 "
                        "(-1: no stop_event at all)")
    args = p.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    if args.num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=f"127.0.0.1:{args.port}",
            num_processes=args.num_processes,
            process_id=args.process_id)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)

    import numpy as np

    from replicatinggpt_tpu.config import MeshConfig, get_config
    from replicatinggpt_tpu.parallel.mesh import make_mesh
    from replicatinggpt_tpu.train.runner import train

    cfg = get_config("test-tiny")
    cfg = cfg.replace(
        train=dataclasses.replace(
            cfg.train, max_iters=args.max_iters, eval_interval=10,
            eval_iters=2, log_interval=0, batch_size=8,
            sampling="sequential",
            steps_per_dispatch=args.steps_per_dispatch,
            grad_accum_steps=args.grad_accum_steps,
            checkpoint_every=args.checkpoint_every),
        mesh=MeshConfig(data=jax.device_count()),
        dataset=os.path.join(repo, "datasets", "shakespeare.txt"))
    mesh = make_mesh(cfg.mesh)

    class _Flag:
        def __init__(self, value: bool):
            self._v = value

        def is_set(self) -> bool:
            return self._v

    stop_event = None
    if args.stop_on_proc >= 0:
        stop_event = _Flag(args.stop_on_proc == jax.process_index())

    ckm = None
    if args.checkpoint_dir:
        from replicatinggpt_tpu.train.checkpoint import CheckpointManager
        ckm = CheckpointManager(args.checkpoint_dir)

    res = train(cfg, mesh=mesh, checkpoint_manager=ckm,
                resume=args.resume, stop_event=stop_event)
    end_step = int(jax.device_get(res.state.step))
    param_sq = [float(np.square(np.asarray(jax.device_get(leaf),
                                           np.float64)).sum())
                for leaf in jax.tree_util.tree_leaves(res.state.params)]
    if ckm is not None:
        ckm.wait()
        checkpoint_steps = [int(s) for s in ckm.mngr.all_steps()]
        ckm.close()
    else:
        checkpoint_steps = []
    if jax.process_index() == 0:
        with open(args.out, "w") as f:
            json.dump({"end_step": end_step,
                       "param_sq": param_sq,
                       "checkpoint_steps": checkpoint_steps,
                       "history": res.history}, f)


if __name__ == "__main__":
    main()
