"""Paged KV cache + radix prefix reuse (serve/pages.py, the paged
device programs in models/gpt.py, and ops/paged_pallas.py): allocator
fuzz vs a reference model, prefix-hit/COW/eviction engine behavior with
greedy parity and pinned-flat compile counts, paged-vs-contiguous
program equivalence, the Pallas fast path in interpret mode, and the
metrics_summary key schema bench dashboards depend on."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replicatinggpt_tpu.config import ModelConfig
from replicatinggpt_tpu.models.gpt import init_params
from replicatinggpt_tpu.sample import GenerateConfig, generate
from replicatinggpt_tpu.serve import (Engine, EngineConfig, PageAllocator,
                                      ReplayConfig, Request, SamplingParams,
                                      Scheduler, compile_counts, run_replay)
from replicatinggpt_tpu.serve.requests import FINISH_MAX_TOKENS

CFG = ModelConfig(vocab_size=65, block_size=32, n_layer=2, n_head=2,
                  n_embd=32, dropout=0.0, attn_dropout=0.0, dtype="float32")


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _offline_greedy(params, reqs, cfg=CFG):
    return {r.id: np.asarray(generate(
        params, r.prompt[None, :], cfg,
        GenerateConfig(max_new_tokens=r.max_new_tokens, greedy=True))
    )[0].tolist() for r in reqs}


def _greedy(rid, prompt, max_new=6):
    return Request(id=rid, prompt=np.asarray(prompt, np.int32),
                   max_new_tokens=max_new,
                   sampling=SamplingParams(greedy=True))


# ---------------------------------------------------------------------------
# allocator fuzz vs a host-side reference model (satellite)
# ---------------------------------------------------------------------------

def _check_allocator(alloc: PageAllocator, live):
    """Reference-model invariants: refcounts equal slot references
    exactly, free/in-use/radix sets are consistent, nothing leaks."""
    counts = np.zeros_like(alloc.ref)
    for claim, _pos in live.values():
        for p in claim.pages:
            counts[p] += 1
    assert (counts == alloc.ref).all(), "refcount drift vs live claims"
    free = list(alloc._free)
    assert len(set(free)) == len(free), "double-freed page"
    used = {p for claim, _ in live.values() for p in claim.pages}
    assert not (set(free) & used), "page simultaneously free and mapped"
    assert not (set(free) & set(alloc.page_node)), "cached page on free list"
    leaked = [p for p in range(alloc.n_pages)
              if p not in free and alloc.ref[p] == 0
              and p not in alloc.page_node]
    assert not leaked, f"leaked pages {leaked}"
    for claim, _ in live.values():
        assert len(set(claim.pages)) == len(claim.pages), \
            "one slot double-mapped a physical page"
    # a page shared by >= 2 slots can only have come from the radix
    for p in np.nonzero(counts >= 2)[0]:
        assert int(p) in alloc.page_node or any(
            p in (s for pair in [c.cow] for s, _ in pair)
            for c, _ in live.values()), f"untracked shared page {p}"


def test_page_allocator_fuzz():
    """A few hundred seeded random acquire/advance/release ops against
    the reference model: refcounts, no double-map, no leaks, claimed
    prefixes byte-identical to the prompts that registered them."""
    rng = np.random.default_rng(42)
    psz = 4
    alloc = PageAllocator(n_pages=20, page_size=psz, prefix_cache=True)
    seen = []           # past prompts, replayed verbatim for full hits
    live = {}           # id -> (claim, simulated next-write pos)
    content = {}        # phys page -> token bytes (set at registration)
    next_id = 0
    for step in range(400):
        op = rng.choice(["acquire", "advance", "release"],
                        p=[0.45, 0.3, 0.25])
        if op == "acquire":
            if seen and rng.random() < 0.35:
                # verbatim repeat of an earlier prompt: the full-prefix-
                # hit arm, which is the only path to copy-on-write
                prompt = seen[int(rng.integers(len(seen)))].copy()
            else:
                P = int(rng.integers(1, 17))
                # tiny alphabet so partial prefixes collide often too
                prompt = rng.integers(0, 3, (P,)).astype(np.int32)
                seen.append(prompt)
            P = int(prompt.size)
            cap = int(rng.integers(1, 9))
            can = alloc.can_acquire(prompt, cap)
            claim = alloc.acquire(prompt, cap)
            assert (claim is not None) == can, \
                "can_acquire disagreed with acquire"
            if claim is None:
                continue
            assert claim.claimed_tokens % psz == 0
            assert claim.claimed_tokens <= P
            # claimed pages must hold exactly the prompt's prefix bytes
            for g in range(claim.claimed_tokens // psz):
                want = prompt[g * psz:(g + 1) * psz].tobytes()
                got_page = claim.pages[g]
                if claim.cow and g == claim.claimed_tokens // psz - 1:
                    got_page = claim.cow[0][0]   # COW source held the bytes
                assert content[got_page] == want, "stale prefix claim"
            assert len(claim.pages) == alloc.n_pages_for(P, cap)
            alloc.register(claim, P - 1)
            live[next_id] = (claim, P - 1)
            next_id += 1
        elif op == "advance" and live:
            cid = int(rng.choice(list(live)))
            claim, pos = live[cid]
            pos += int(rng.integers(1, 5))
            alloc.register(claim, pos)
            live[cid] = (claim, pos)
        elif op == "release" and live:
            cid = int(rng.choice(list(live)))
            claim, _ = live.pop(cid)
            alloc.release(claim)
        # sync the content shadow with registrations/evictions
        for claim, _pos in live.values():
            for g in range(claim.next_reg):
                p = claim.pages[g]
                if p in alloc.page_node:
                    content[p] = claim.prompt[g * psz:(g + 1) * psz]\
                        .tobytes()
        for p in list(content):
            if p not in alloc.page_node:
                del content[p]
        _check_allocator(alloc, live)
    assert alloc.prefix_hits > 0, "fuzz never exercised a prefix hit"
    assert alloc.evictions > 0, "fuzz never exercised eviction"
    assert alloc.cow_copies > 0, "fuzz never exercised copy-on-write"


def test_allocator_rejects_when_exhausted_and_recovers():
    alloc = PageAllocator(n_pages=4, page_size=4, prefix_cache=True)
    a = alloc.acquire(np.arange(8, dtype=np.int32), cap=8)   # 4 pages
    assert a is not None and alloc.pages_free == 0
    assert not alloc.can_acquire(np.arange(4, dtype=np.int32), cap=1)
    assert alloc.acquire(np.arange(4, dtype=np.int32), cap=1) is None
    alloc.register(a, 20)
    alloc.release(a)
    # the two full prompt pages stay as radix cache (refcount 0) and are
    # evictable; a new request can reclaim through them
    assert alloc.can_acquire(np.ones((12,), np.int32), cap=4)
    b = alloc.acquire(np.ones((12,), np.int32), cap=4)
    assert b is not None
    assert alloc.evictions > 0


# ---------------------------------------------------------------------------
# engine: prefix hits, copy-on-write, eviction — parity + flat compiles
# ---------------------------------------------------------------------------

def test_prefix_hit_skips_prefill_with_parity(params):
    """Identical page-aligned prompt twice: the second admission claims
    the whole prefix (zero prefill dispatches beyond the COW split) and
    still produces the exact offline greedy stream."""
    prompt = (np.arange(16, dtype=np.int32) % 13) + 1     # P == 2 pages
    ecfg = EngineConfig(pool_size=2, max_queue=8, page_size=8)
    eng = Engine(params, CFG, ecfg)
    a, b = _greedy("a", prompt), _greedy("b", prompt.copy())
    want = _offline_greedy(params, [a, b])
    eng.submit(a)
    res = {r.id: r.tokens for r in eng.drain()}
    prefill_calls = eng._prefill_guard.calls
    counts = compile_counts()
    eng.submit(b)
    res.update({r.id: r.tokens for r in eng.drain()})
    assert res == want
    assert eng._prefill_guard.calls == prefill_calls   # fully cached
    assert compile_counts() == counts                  # COW + hit: no compile
    pg = eng.metrics_summary()["pages"]
    assert pg["prefix_hit_tokens"] == 16
    assert pg["cow_copies"] == 1                       # frontier page split
    assert eng.metrics.counters["prefill_tokens"] == 16  # first request only


def test_concurrent_shared_prompts_parity(params):
    """Several requests with one shared prompt admitted in the SAME
    step: later admissions claim the earlier one's just-registered
    pages; every stream matches offline."""
    prompt = (np.arange(16, dtype=np.int32) % 11).astype(np.int32)
    eng = Engine(params, CFG, EngineConfig(pool_size=4, max_queue=8,
                                           page_size=8))
    reqs = [_greedy(f"c{i}", prompt.copy(), max_new=5) for i in range(4)]
    want = _offline_greedy(params, reqs)
    for r in reqs:
        assert eng.submit(r) is None
    got = {r.id: r.tokens for r in eng.drain()}
    assert got == want
    assert eng.metrics_summary()["pages"]["prefix_hits"] == 3


def test_eviction_under_page_pressure_parity_and_flat_compiles(params):
    """Acceptance: a physical pool much smaller than slots*max_pages —
    admissions, prefix hits, LRU evictions and a COW split all happen
    mid-replay and compile_counts stays pinned flat, with every greedy
    stream identical to offline generate()."""
    # seed chosen for a trace OFF the f32 knife edge: generate() runs one
    # fused jitted scan while the engine dispatches separate programs, so
    # CPU f32 rounding can differ by ~1e-2 in logits — on near-tie prompts
    # that flips an argmax for the CONTIGUOUS engine exactly as for the
    # paged one (verified bit-identical), i.e. it is not a paging effect
    rng = np.random.default_rng(1)
    shared = ((np.arange(16) % 9) + 2).astype(np.int32)
    ecfg = EngineConfig(pool_size=2, max_queue=64, page_size=8, n_pages=6)
    eng = Engine(params, CFG, ecfg)
    eng.submit(_greedy("warm", shared, max_new=2))
    eng.drain()
    base = compile_counts()
    reqs = []
    for i in range(10):
        if i % 3 == 0:
            prompt = shared.copy()                 # prefix-hit + COW arm
        else:
            P = int(rng.integers(3, 20))
            prompt = rng.integers(0, CFG.vocab_size, (P,))\
                .astype(np.int32)
        reqs.append(_greedy(f"e{i}", prompt, max_new=4))
    want = _offline_greedy(params, reqs)
    for r in reqs:
        assert eng.submit(r) is None
    got = {r.id: r.tokens for r in eng.drain()}
    assert compile_counts() == base     # zero recompiles through it all
    assert got == want
    pg = eng.metrics_summary()["pages"]
    assert pg["evictions"] > 0
    assert pg["cow_copies"] > 0
    assert pg["prefix_hit_tokens"] > 0
    assert eng.pool.n_free == 2         # no leaked slots
    counts = np.zeros((eng.pool.n_pages,), np.int64)
    assert eng.pool.alloc.ref.max() == 0  # no leaked page refs
    del counts


def test_admission_gates_on_free_pages_not_just_slots(params):
    """With pages scarcer than slots, a request that cannot reserve its
    whole lifetime stays QUEUED (strict FIFO) until a finish frees
    pages — and then completes with parity."""
    ecfg = EngineConfig(pool_size=4, max_queue=8, page_size=8, n_pages=4,
                        prefix_cache=False)
    eng = Engine(params, CFG, ecfg)
    big = _greedy("big", np.arange(1, 17, dtype=np.int32), max_new=16)
    big2 = _greedy("big2", np.arange(2, 18, dtype=np.int32), max_new=16)
    want = _offline_greedy(params, [big, big2])
    assert eng.submit(big) is None
    assert eng.submit(big2) is None
    eng.step()
    # big took the whole 4-page pool; big2 must wait despite 3 free slots
    assert eng.pool.slot_of("big") is not None
    assert eng.pool.slot_of("big2") is None
    assert eng.pool.n_free == 3
    res = {r.id: r.tokens for r in eng.drain()}
    assert res == want


def test_duplicate_request_id_rejected_in_flight(params):
    """Ids key results, cancellation, the journal and the pools'
    reverse indexes — a duplicate of an IN-FLIGHT id must be rejected
    at submit (and the id becomes reusable after the first finishes)."""
    eng = Engine(params, CFG, EngineConfig(pool_size=1, max_queue=4))
    assert eng.submit(_greedy("dup", [1, 2], max_new=3)) is None
    assert eng.submit(_greedy("other", [3], max_new=3)) is None  # queued
    for req_again in ([4, 5], [6]):        # active dup AND queued dup
        rej = eng.submit(_greedy("dup" if req_again == [4, 5] else "other",
                                 req_again, max_new=2))
        assert rej is not None
        assert rej.finish_reason == "rejected_bad_request"
    res = {r.id: r for r in eng.drain()}
    assert set(res) == {"dup", "other"}
    assert eng.submit(_greedy("dup", [7], max_new=2)) is None  # reusable
    assert len(eng.drain()) == 1


def test_cancel_during_decode_releases_pages_promptly(params):
    """Cancel of an ACTIVELY STREAMING request (tokens already
    committed, mid-decode — the SSE-stream cancellation path): the slot
    AND every reserved KV page release immediately at cancel(), not at
    the next step or at what would have been completion. Only
    refcount-0 radix-cached prefix pages may stay resident, and the
    freed capacity admits a page-hungry successor at once — with
    parity, without a recompile."""
    ecfg = EngineConfig(pool_size=2, max_queue=4, page_size=4, n_pages=8)
    eng = Engine(params, CFG, ecfg)
    # 6-token prompt + 20-token budget = ceil(25/4) = 7 of 8 pages
    doomed = _greedy("doomed", np.arange(1, 7, dtype=np.int32),
                     max_new=20)
    assert eng.submit(doomed) is None
    for _ in range(5):
        eng.step()
    n_streamed = len(eng.partial_tokens("doomed"))
    assert n_streamed >= 4                      # genuinely mid-stream
    assert eng.pool.alloc.pages_in_use == 7
    counts = compile_counts()
    assert eng.cancel("doomed")
    # released NOW: slot free, every slot-referenced page refcount 0
    assert eng.pool.n_free == eng.pool.n_slots
    assert (eng.pool.alloc.ref > 0).sum() == 0
    # resident pages are exactly the radix-cached prefix (refcount 0)
    assert (eng.pool.alloc.pages_in_use
            == len(eng.pool.alloc.page_node))
    # a successor needing most of the pool admits immediately
    succ = _greedy("succ", np.arange(2, 8, dtype=np.int32), max_new=18)
    want = _offline_greedy(params, [succ])
    assert eng.submit(succ) is None
    res = {r.id: r for r in eng.step()}   # surfaces doomed's terminal
    assert eng.pool.slot_of("succ") is not None     # admitted at once
    res.update({r.id: r for r in eng.drain()})
    assert res["doomed"].finish_reason == "cancelled"
    assert len(res["doomed"].tokens) == n_streamed  # partials preserved
    assert res["succ"].tokens == want["succ"]
    assert compile_counts() == counts               # cancel is host-only


def test_scheduler_fits_blocks_head_fifo():
    sch = Scheduler(max_queue=4, block_size=8, clock=lambda: 0.0)
    a = Request(id="a", prompt=np.array([1, 1, 1], np.int32))
    b = Request(id="b", prompt=np.array([2], np.int32))
    assert sch.submit(a) is None and sch.submit(b) is None
    # head does not fit: nothing admitted, ORDER preserved (no skip)
    admitted, dropped = sch.admit(2, fits=lambda r: r.prompt.size <= 2)
    assert admitted == [] and dropped == [] and sch.depth == 2
    admitted, _ = sch.admit(2, fits=lambda r: True)
    assert [r.id for r, _ in admitted] == ["a", "b"]


# ---------------------------------------------------------------------------
# paged device programs == contiguous programs (unit equivalence)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("layout", ["heads", "packed"])
def test_decode_step_paged_matches_multi(params, layout):
    from replicatinggpt_tpu.models.gpt import (decode_step_multi,
                                               decode_step_paged,
                                               init_kv_cache,
                                               init_paged_kv_pool)
    cfg = dataclasses.replace(CFG, decode_cache_layout=layout)
    B, psz = 3, 8
    mp = cfg.block_size // psz
    rng = np.random.default_rng(1)
    toks = rng.integers(0, cfg.vocab_size, (B, 6)).astype(np.int32)
    pos0 = np.array([0, 3, 5], np.int32)
    cache_m = init_kv_cache(cfg, B)
    pool = init_paged_kv_pool(cfg, B * mp, psz)
    # identity mapping: slot b's logical page g -> physical b*mp + g
    tables = (np.arange(B)[:, None] * mp
              + np.arange(mp)[None, :]).astype(np.int32)
    active = np.ones((B,), bool)
    for step in range(6):
        pos = (pos0 + step).astype(np.int32)
        lg_m, cache_m = decode_step_multi(
            params, jnp.asarray(toks[:, step]), jnp.asarray(pos),
            cache_m, cfg)
        lg_p, pool = decode_step_paged(
            params, jnp.asarray(toks[:, step]), jnp.asarray(pos),
            jnp.asarray(active), jnp.asarray(tables), pool, cfg)
        np.testing.assert_allclose(np.asarray(lg_m), np.asarray(lg_p),
                                   atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("layout", ["heads", "packed"])
def test_verify_step_paged_matches_multi(params, layout):
    from replicatinggpt_tpu.models.gpt import (init_kv_cache,
                                               init_paged_kv_pool,
                                               prefill, verify_step_multi,
                                               verify_step_paged)
    cfg = dataclasses.replace(CFG, decode_cache_layout=layout)
    B, W, psz = 2, 4, 8
    mp = cfg.block_size // psz
    rng = np.random.default_rng(2)
    warm = rng.integers(0, cfg.vocab_size, (B, 10)).astype(np.int32)
    cache_m = prefill(params, jnp.asarray(warm), init_kv_cache(cfg, B), cfg)
    pool = init_paged_kv_pool(cfg, B * mp, psz)
    tables = (np.arange(B)[:, None] * mp
              + np.arange(mp)[None, :]).astype(np.int32)
    # mirror the contiguous prefill into the paged pool page by page
    km, vm = np.asarray(cache_m["k"]), np.asarray(cache_m["v"])
    kp, vp = (np.array(pool["k"]), np.array(pool["v"]))  # writable copies
    for b in range(B):
        for g in range(mp):
            sl = slice(g * psz, (g + 1) * psz)
            if layout == "packed":
                kp[:, b * mp + g] = km[:, b, sl]
                vp[:, b * mp + g] = vm[:, b, sl]
            else:
                kp[:, b * mp + g] = km[:, b, :, sl]
                vp[:, b * mp + g] = vm[:, b, :, sl]
    pool = {"k": jnp.asarray(kp), "v": jnp.asarray(vp)}
    window = rng.integers(0, cfg.vocab_size, (B, W)).astype(np.int32)
    pos = np.array([9, 6], np.int32)
    m = np.array([3, 2], np.int32)
    active = np.ones((B,), bool)
    lg_m, _ = verify_step_multi(params, jnp.asarray(window),
                                jnp.asarray(pos), jnp.asarray(m),
                                cache_m, cfg)
    lg_p, _ = verify_step_paged(params, jnp.asarray(window),
                                jnp.asarray(pos), jnp.asarray(m),
                                jnp.asarray(active), jnp.asarray(tables),
                                pool, cfg)
    # compare only REAL window positions (padding logits are garbage on
    # both paths, but differently-garbage: the multi path scatters pads
    # to S, the paged path drops them)
    for b in range(B):
        np.testing.assert_allclose(np.asarray(lg_m)[b, :m[b] + 1],
                                   np.asarray(lg_p)[b, :m[b] + 1],
                                   atol=1e-5, rtol=1e-5)


# ---------------------------------------------------------------------------
# Pallas fast path (interpret mode on CPU)
# ---------------------------------------------------------------------------

def test_paged_pallas_kernel_matches_gather_reference():
    from replicatinggpt_tpu.ops import paged_pallas
    from replicatinggpt_tpu.ops.attention import cached_attention
    rng = np.random.default_rng(0)
    B, H, D, psz, mp, N = 3, 2, 32, 8, 4, 10
    C = H * D
    kp = jnp.asarray(rng.normal(size=(N, psz, C)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(N, psz, C)), jnp.float32)
    tables = np.zeros((B, mp), np.int32)
    perm = rng.permutation(N)
    tables[0, :4] = perm[:4]
    tables[1, :2] = perm[4:6]
    tables[2, :3] = perm[6:9]
    pos = np.array([17, 9, 0], np.int32)   # incl. the pos=0 fresh-only row
    q = jnp.asarray(rng.normal(size=(B, C)), jnp.float32)
    kn = jnp.asarray(rng.normal(size=(B, C)), jnp.float32)
    vn = jnp.asarray(rng.normal(size=(B, C)), jnp.float32)
    out = paged_pallas.paged_decode_attention(
        q, kn, vn, kp, vp, jnp.asarray(tables), jnp.asarray(pos), n_head=H)
    ka = np.asarray(kp)[tables].reshape(B, mp * psz, C).copy()
    va = np.asarray(vp)[tables].reshape(B, mp * psz, C).copy()
    for b in range(B):
        ka[b, pos[b]] = np.asarray(kn)[b]
        va[b, pos[b]] = np.asarray(vn)[b]

    def split(x):
        return jnp.asarray(x.reshape(B, -1, H, D).transpose(0, 2, 1, 3))

    ref = cached_attention(split(np.asarray(q)[:, None, :]), split(ka),
                           split(va), jnp.asarray(pos))
    ref = np.asarray(ref).transpose(0, 2, 1, 3).reshape(B, C)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5, rtol=1e-5)


def test_paged_kernel_engine_greedy_parity(params, monkeypatch):
    """The engine's opt-in Pallas paged decode routes must keep exact
    greedy parity with offline generate(). ``paged_kernel=True`` now
    prefers the FUSED all-layers kernel (one launch per decode step,
    ops/decode_pallas.fused_paged_decode_layers) and falls back to the
    per-layer kernel (ops/paged_pallas) when the fused envelope says
    no — both routes are pinned here."""
    from replicatinggpt_tpu.ops import decode_pallas, paged_pallas
    monkeypatch.setattr(paged_pallas, "_paged_attn_backend_ok",
                        lambda: True)
    cfg = ModelConfig(vocab_size=65, block_size=32, n_layer=2, n_head=2,
                      n_embd=64, dropout=0.0, attn_dropout=0.0,
                      dtype="float32", decode_cache_layout="packed")
    p64 = init_params(jax.random.PRNGKey(1), cfg)
    reqs = [_greedy("k0", np.array([3, 1, 4, 1, 5], np.int32), max_new=6),
            _greedy("k1", np.array([9, 2, 6], np.int32), max_new=5)]
    want = _offline_greedy(p64, reqs, cfg=cfg)

    ecfg = EngineConfig(pool_size=2, max_queue=4, page_size=8,
                        paged_kernel=True)
    eng = Engine(p64, cfg, ecfg)
    assert eng._use_fused, "fused kernel route should be on under the patch"
    assert not eng._use_pallas
    for r in reqs:
        assert eng.submit(r) is None
    got = {r.id: r.tokens for r in eng.drain()}
    assert got == want

    # per-layer fallback: force the fused envelope shut
    monkeypatch.setattr(decode_pallas, "fused_paged_decode_supported",
                        lambda *a, **kw: False)
    eng2 = Engine(p64, cfg, ecfg)
    assert eng2._use_pallas and not eng2._use_fused, \
        "per-layer kernel route should be the fallback"
    for r in reqs:
        assert eng2.submit(r) is None
    got2 = {r.id: r.tokens for r in eng2.drain()}
    assert got2 == want


def test_fused_paged_kernel_matches_xla_reference():
    """Interpret-mode parity of the fused all-layers paged kernel
    against the XLA gather path: logits and the post-write page pools
    must match on mixed active/inactive slots at ragged positions."""
    from replicatinggpt_tpu.models.gpt import (decode_step_paged,
                                               init_paged_kv_pool)
    from replicatinggpt_tpu.ops.decode_pallas import (
        fused_paged_decode_supported)
    cfg = ModelConfig(vocab_size=97, block_size=64, n_layer=3, n_head=2,
                      n_embd=64, dropout=0.0, attn_dropout=0.0,
                      dtype="float32", decode_cache_layout="packed")
    p = init_params(jax.random.PRNGKey(0), cfg)
    B, psz, N, mp = 4, 8, 32, 8
    assert fused_paged_decode_supported(cfg, B, psz, 4)
    rng = np.random.default_rng(0)
    cache = {"k": jnp.asarray(rng.normal(size=(cfg.n_layer, N, psz,
                                               cfg.n_embd)), jnp.float32),
             "v": jnp.asarray(rng.normal(size=(cfg.n_layer, N, psz,
                                               cfg.n_embd)), jnp.float32)}
    tables = jnp.asarray(rng.permutation(N)[:B * mp]
                         .reshape(B, mp).astype(np.int32))
    pos = jnp.asarray(np.array([5, 0, 17, 23], np.int32))
    active = jnp.asarray(np.array([True, False, True, True]))
    tok = jnp.asarray(np.array([3, 0, 9, 50], np.int32))
    ref_lg, ref_c = decode_step_paged(p, tok, pos, active, tables,
                                      cache, cfg)
    fus_lg, fus_c = decode_step_paged(p, tok, pos, active, tables,
                                      cache, cfg, use_fused=True)
    am = np.asarray(active)
    np.testing.assert_allclose(np.asarray(fus_lg)[am],
                               np.asarray(ref_lg)[am],
                               atol=1e-5, rtol=1e-5)
    for name in ("k", "v"):
        np.testing.assert_allclose(np.asarray(fus_c[name]),
                                   np.asarray(ref_c[name]),
                                   atol=1e-5, rtol=1e-5)


# ---------------------------------------------------------------------------
# replay + metrics schema
# ---------------------------------------------------------------------------

def test_shared_prefix_replay_hits_and_fewer_prefills(params):
    """The shared-prefix trace through run_replay: cache ON claims
    prefix tokens and dispatches less prefill than the SAME trace with
    the cache off, with identical greedy token streams."""
    rcfg = ReplayConfig(n_requests=12, rate=5000.0, seed=3,
                        prompt_len_min=10, prompt_len_max=16,
                        shared_prefix_len=8, max_new_tokens=4,
                        greedy=True, prompt_mode="shared_prefix")
    on = run_replay(params, CFG,
                    rcfg, EngineConfig(pool_size=4, max_queue=32,
                                       page_size=8))
    off = run_replay(params, CFG,
                     rcfg, EngineConfig(pool_size=4, max_queue=32,
                                        page_size=8, prefix_cache=False))
    assert on["n_completed"] == off["n_completed"] == 12
    assert on["recompiles_after_warmup"] == 0
    assert off["recompiles_after_warmup"] == 0
    assert on["pages"]["prefix_hit_tokens"] > 0
    assert off["pages"]["prefix_hit_tokens"] == 0
    assert (on["counters"]["prefill_tokens"]
            < off["counters"]["prefill_tokens"])


def test_metrics_summary_key_schema(params):
    """Pin the summary schema bench dashboards consume — a silently
    dropped field is a dashboard hole nobody notices until an incident
    (satellite)."""
    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=4))
    eng.submit(_greedy("m", np.array([1, 2, 3], np.int32), max_new=3))
    res = eng.drain()
    assert res[0].finish_reason == FINISH_MAX_TOKENS
    s = eng.metrics_summary()
    for key in ("counters", "gauges", "histograms", "step_latency",
                "n_steps", "compile_counts", "compile_guards", "recovery",
                "pages", "kernel_route"):
        assert key in s, key
    # kernel-route decision (ISSUE 20): static per engine; the bench
    # serve artifact carries this block verbatim
    assert set(s["kernel_route"]) == {
        "route", "decode", "window", "sharded", "mesh", "kv_quant",
        "weight_quant", "granularity", "act_quant", "reasons"}
    assert s["kernel_route"]["route"] in ("pallas", "xla")
    assert "kernel_route_pallas" in s["gauges"]
    assert set(s["compile_counts"]) == {
        "decode", "mixed", "prefill", "verify", "page_copy",
        "page_export", "page_install", "draft_decode", "draft_prefill"}
    assert set(s["compile_guards"]) == {"decode", "mixed", "prefill",
                                        "verify", "page_copy",
                                        "page_export", "page_install"}
    # continuous-window observability (ISSUE 13): the break counters
    # keyed by reason, and the k-autotune fields in the dispatch block
    assert set(s["window_breaks"]) == {"admit", "deadline", "cancel",
                                       "spec", "reprobe"}
    for key in ("window_k", "window_k_max", "autotune",
                "autotune_increases"):
        assert key in s["dispatch"], key
    assert set(s["recovery"]) == {
        "watchdog_stalls", "spec_disables", "spec_reprobes",
        "shed_requests", "spec_active", "events"}
    assert set(s["pages"]) == {
        "page_size", "max_pages_per_slot", "n_pages", "pages_in_use",
        "pages_free", "page_utilization", "radix_pages", "prefix_cache",
        "prefix_lookups", "prefix_hits", "prefix_hit_tokens",
        "prefix_hit_rate", "evictions", "cow_copies",
        # sharded-serving block (ISSUE 12): on 1x1 the per-chip numbers
        # degenerate to the aggregate ones but the SCHEMA is mesh-
        # independent — dashboards and the router gauges never branch
        "mesh_shape", "aggregate_pages", "pages_per_chip",
        "pages_in_use_by_chip", "page_utilization_by_chip",
        # quantization gauges (ISSUE 15): same schema quantized or not
        # (values differ — pinned for a quantized engine in
        # tests/test_quant.py); bytes_per_page is the fixed-HBM
        # capacity denominator, kv_quant_bits the numeric mode gauge
        "kv_quant", "quant_granularity", "bytes_per_page",
        "kv_quant_bits",
        # disaggregation gauges (ISSUE 16): page export/install traffic
        # and transfer-pinned pages; zero on a colocated engine but the
        # schema never branches on tier
        "pages_exported", "pages_installed", "transfer_pins"}
    assert s["pages"]["kv_quant"] == "none"
    assert s["pages"]["kv_quant_bits"] == 32      # f32 test pool
    assert s["pages"]["mesh_shape"] == [1, 1]
    assert s["pages"]["aggregate_pages"] == s["pages"]["n_pages"]
    assert s["pages"]["pages_per_chip"] == s["pages"]["n_pages"]
    assert s["pages"]["pages_in_use_by_chip"] == \
        [s["pages"]["pages_in_use"]]
    for guard in s["compile_guards"].values():
        assert set(guard) == {"calls", "compiles", "budget"}
    # every histogram summary carries the pinned hist_summary schema
    # (incl. min) — the telemetry exporters index these keys directly
    from replicatinggpt_tpu.utils.logging import Metrics
    assert s["histograms"], "expected at least one histogram"
    for name, h in s["histograms"].items():
        assert set(h) == set(Metrics.HIST_KEYS), name
