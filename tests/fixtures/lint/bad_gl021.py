"""GL021 bad: counter drift in both directions against the pins."""

PROM_PINNED_COUNTERS = (
    "fleet_requests_routed",
    "fleet_requeue_retries",      # nothing increments this
)


class Stepper:
    def step(self, metrics):
        metrics.inc("fleet_requests_routed")
        metrics.inc("fleet_replica_downs")      # incremented, not pinned
