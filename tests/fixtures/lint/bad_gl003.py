"""GL003 bad: one PRNG key feeding multiple consumers."""
import jax


def sample():
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (8,))
    b = jax.random.normal(key, (8,))      # identical to `a`
    return a, b


def loop_reuse(xs):
    key = jax.random.PRNGKey(1)
    out = []
    for x in xs:
        out.append(jax.random.normal(key, (4,)))   # same noise each iter
    return out
