"""GL018 good: every verb has a caller, keys agree in both directions."""


class WorkerStub:
    def dispatch(self, doc):
        op = doc.get("op")
        fn = getattr(self, "op_" + op, None)
        if fn is None:
            raise ValueError(op)
        return fn(doc)

    def op_submit(self, doc):
        req = doc["req"]
        if not req:
            return {"accepted": False, "rejection": "empty"}
        return {"accepted": True}


class ClientStub:
    def __init__(self, call):
        self.call = call

    def submit(self, req):
        resp = self.call("submit", req=req, timeout_s=1.0)
        if not resp["accepted"]:
            return resp["rejection"]
        return None
