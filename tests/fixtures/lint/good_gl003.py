"""GL003 good: split / fold_in before each consumer; exclusive branches."""
import jax


def sample():
    key = jax.random.PRNGKey(0)
    ka, kb = jax.random.split(key)
    a = jax.random.normal(ka, (8,))
    b = jax.random.normal(kb, (8,))
    return a, b


def loop_fresh(xs):
    key = jax.random.PRNGKey(1)
    out = []
    for i, _ in enumerate(xs):
        k = jax.random.fold_in(key, i)    # fresh stream per iteration
        out.append(jax.random.normal(k, (4,)))
    return out


def branchy(flag):
    key = jax.random.PRNGKey(2)
    if flag:                              # branches are exclusive:
        return jax.random.normal(key, (2,))
    return jax.random.uniform(key, (2,))  # only ONE consumer runs
