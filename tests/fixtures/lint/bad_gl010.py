"""GL010 bad: PartitionSpec names an axis the mesh doesn't have."""
import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_batch(devices, batch):
    mesh = Mesh(np.asarray(devices), ("data", "model"))
    sharding = NamedSharding(mesh, P("data", "seq"))   # 'seq': no such axis
    return jax.device_put(batch, sharding)


def shard_mapped(devices, fn, xs):
    from jax.experimental.shard_map import shard_map
    mesh = Mesh(np.asarray(devices), ("data",))
    return shard_map(fn, mesh, in_specs=P("model"),   # 'model': no such axis
                     out_specs=P("data"))(xs)
