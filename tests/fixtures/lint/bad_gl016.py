"""GL016 bad: router-side code reading per-worker files — a
shared-filesystem assumption the multi-host fleet cannot keep."""

import json


class Router:
    def reconcile(self, rep):
        # the worker's journal may live on ANOTHER MACHINE
        return RequestJournal.unfinished(rep.journal_path)

    def await_worker(self, spec):
        with open(spec.ready_file) as f:       # ready-file handshake
            return json.load(f)

    def requeue_from_disk(self, idx):
        return load_jsonl_if_exists(f_path("replica0.jsonl"))

    def requeue_from_worker_dir(self, base):
        # the per-worker-dir layout is just as shared-filesystem
        return load_jsonl_if_exists(base + "/worker0/journal.jsonl")


def f_path(name):
    return name
