"""GL012 bad: sharding-spec tuples whose arity disagrees with the
wrapped function."""
from functools import partial

import jax


@partial(jax.jit, in_shardings=(None, None))
def apply3(x, w, b):                  # 3 args, 2 specs
    return x @ w + b


def pair(x):
    return x, x


paired = jax.jit(pair, out_shardings=(None, None, None))   # 2-tuple return
