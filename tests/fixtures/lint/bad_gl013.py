"""GL013 bad: per-iteration Python scalars flow into shape/static
positions of a jitted function — one fresh XLA program per value."""
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("n",))
def window(x, n):
    return x[:n] * jnp.ones((n,))


def sweep(x, steps):
    outs = []
    for i in range(steps):
        outs.append(window(x, i))        # recompiles per i
    return outs


def drain(x, items):
    outs = []
    while items:
        items.pop()
        outs.append(window(x, len(items)))   # recompiles per length
    return outs
