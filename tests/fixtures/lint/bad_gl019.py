"""GL019 bad: event-loop blockers in and below async defs."""

import time


class Poller:
    def _backoff(self):
        time.sleep(0.5)

    async def tick(self):
        # reaches time.sleep through a sync helper
        self._backoff()

    async def drain(self, sock):
        # direct blocking socket read inside a coroutine
        return sock.recv(4096)

    async def probe(self, client):
        # RPC call with no explicit timeout_s budget
        return client.call("health")
