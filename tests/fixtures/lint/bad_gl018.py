"""GL018 bad: verb/key drift between a dispatch class and its caller."""


class WorkerStub:
    def dispatch(self, doc):
        op = doc.get("op")
        return getattr(self, "op_" + op)(doc)

    def op_submit(self, doc):
        req = doc["req"]
        return {"accepted": bool(req)}

    def op_orphan(self, doc):
        # no literal .call("orphan", ...) site anywhere: dead verb
        return {}


class ClientStub:
    def __init__(self, call):
        self.call = call

    def submit(self, req):
        # sends 'payload' (never read), omits required 'req', and reads
        # 'rejection' off a response that never returns it
        resp = self.call("submit", payload=req, timeout_s=1.0)
        return resp["rejection"]
