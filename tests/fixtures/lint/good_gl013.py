"""GL013 good: sizes padded to a fixed bucket — one program total."""
from functools import partial

import jax
import jax.numpy as jnp

BUCKET = 128


@partial(jax.jit, static_argnames=("n",))
def window(x, n):
    return x[:n] * jnp.ones((n,))


def sweep(x, steps):
    outs = []
    for _ in range(steps):
        outs.append(window(x, BUCKET))   # constant static: one program
    return outs
