"""GL017 good: every kernel-body ref load is bound with an explicit
cast before it meets other operands, and every pool write casts its
value to the target's dtype at the write site."""

import jax
import jax.numpy as jnp


def _explicit_kernel(q_ref, kp_ref, out_ref, *, scale):
    kc = kp_ref[...].astype(jnp.float32)      # precision visible here
    s = kc * q_ref[...].astype(jnp.float32)
    out_ref[...] = s.astype(out_ref.dtype)


def scatter_cast(ck, k_m, layer, phys, woff):
    return ck.at[layer, phys, woff, :].set(
        (k_m * 2.0).astype(ck.dtype), mode="drop")


def dus_cast(cv, v_m, start):
    assert start >= 0
    return jax.lax.dynamic_update_slice(cv, v_m.astype(cv.dtype)[None],
                                        start)


def page_copy(cache, page, dst):
    # a bare name re-write of the pool's own slice carries the dtype
    # by construction (the COW page copy shape)
    assert dst >= 0
    return jax.lax.dynamic_update_slice(cache, page, dst)
