"""GL024 good: the full idempotency contract — declared verbs tuple,
idem-keyed reply cache consulted in dispatch, explicit idem key at the
call site."""

IDEMPOTENT_VERBS = ("submit",)


class WorkerStub:
    def __init__(self):
        self._replies = {}

    def dispatch(self, doc):
        op = doc.get("op")
        fn = getattr(self, "op_" + op, None)
        if fn is None:
            raise ValueError(op)
        idem = doc.get("idem")
        if op in IDEMPOTENT_VERBS and idem is not None:
            cached = self._replies.get(idem)
            if cached is not None:
                return {**cached, "idem_hit": True}
        resp = fn(doc)
        if op in IDEMPOTENT_VERBS and idem is not None:
            self._replies[idem] = resp
        return resp

    def op_submit(self, doc):
        req = doc["req"]
        if not req:
            return {"accepted": False, "rejection": "empty"}
        return {"accepted": True}


class ClientStub:
    def __init__(self, call):
        self.call = call
        self._seq = 0

    def submit(self, req):
        self._seq += 1
        resp = self.call("submit", req=req, timeout_s=1.0,
                         idem="sub.%d" % self._seq)
        if not resp["accepted"]:
            return resp["rejection"]
        return None
