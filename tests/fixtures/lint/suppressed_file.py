# graftlint: disable-file=GL004
"""File-level pragma: every GL004 finding in this file is suppressed."""
import numpy as np


def loop(xs):
    out = []
    for x in xs:
        out.append(np.asarray(x))
    return out


def loop2(xs):
    return [float(np.sum(x)) for x in xs]
