"""GL024 bad: a mutating verb with no idempotency anywhere — no
declaration tuple, no reply cache in dispatch, no idem key at the call
site. (GL018-clean on purpose: keys agree in both directions, so only
the idempotency contract fires.)"""


class WorkerStub:
    def dispatch(self, doc):
        op = doc.get("op")
        fn = getattr(self, "op_" + op, None)
        if fn is None:
            raise ValueError(op)
        return fn(doc)          # no reply cache, no idem read

    def op_submit(self, doc):   # mutating: enqueues a request
        req = doc["req"]
        return {"accepted": bool(req)}


class ClientStub:
    def __init__(self, call):
        self.call = call

    def submit(self, req):
        # no idem key: a duplicated frame re-enqueues the request
        resp = self.call("submit", req=req, timeout_s=1.0)
        return resp["accepted"]
