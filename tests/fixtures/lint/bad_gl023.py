"""GL023 bad: the validator pins a span name nothing emits."""

TRACE_VALIDATED_NAMES = ("request", "token", "page_transfer")


def emit(t, track, rid):
    t.begin("request", track, id=rid)
    t.instant("token", track, index=0)
    t.end("request", track)
