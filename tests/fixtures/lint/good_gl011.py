"""GL011 good: the table arrives as an argument with its own spec."""
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, in_shardings=(None, None))
def embed(ids, table):
    return jnp.take(table, ids, axis=0)
