"""GL001 bad: Python control flow on traced jit arguments."""
import jax


@jax.jit
def step(x, n):
    if n > 0:                 # n is traced -> retrace/crash
        x = x * n
    while n > 0:              # traced while: same hazard
        n = n - 1
    return x
