"""GL020 bad: a finish path that bypasses the crash ledger."""


class MiniRouter:
    def __init__(self, journal):
        self.journal = journal
        self.results = {}

    def on_finish(self, res):
        # terminal store without record_finish: the next crash recovery
        # replays this request and double-delivers its stream
        self.results[res.id] = res
