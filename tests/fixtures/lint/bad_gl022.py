"""GL022 bad: all three forwarding-drift directions at once."""

ENGINE_FORWARD_FLAGS = (
    ("pool_size", "--pool-size"),
    ("stale_knob", "--stale-knob"),   # builder never reads it
)
ENGINE_FORWARD_SWITCHES = ()


class EngineConfig:
    pool_size: int = 8
    max_queue: int = 64
    page_size: int = 0                # never passed: inexpressible


def engine_config_from_args(args):
    return EngineConfig(pool_size=args.pool_size,
                        max_queue=args.max_queue)   # dest not whitelisted
