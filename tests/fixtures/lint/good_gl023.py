"""GL023 good: every pinned name has an emission site."""

TRACE_VALIDATED_NAMES = ("request", "token", "page_transfer")


def emit(t, track, rid, pages):
    t.begin("request", track, id=rid)
    t.instant("token", track, index=0)
    t.end("request", track)
    t.complete("page_transfer", track, 0.0, 1.0, pages=pages)
