"""GL004 good: accumulate on device, fetch once after the loop."""
import numpy as np


def eval_loop(step, params, batches):
    total = None
    for b in batches:
        loss = step(params, b)              # stays on device
        total = loss if total is None else total + loss
    return float(total) / len(batches)      # ONE sync


def fetch_once(decode, toks):
    outs = [decode(t) for t in toks]
    return np.asarray(outs)                 # one fetch outside any loop
