"""Innermost helper: the actual device->host sync (not in any loop, so
the per-file GL004 stays silent here — only the call graph sees it)."""


def fetch_loss(metrics):
    return metrics["loss"].item()
