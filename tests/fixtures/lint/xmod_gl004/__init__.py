"""Cross-module GL004 fixture package: the host sync lives two call
levels (and two files) below the step loop."""
