"""The step loop: calls a helper that (two levels down, in another
file) syncs to host — interprocedural GL004 must fire HERE."""
from .mid import log_metrics


def train(step, state, batches):
    losses = []
    for b in batches:
        state, metrics = step(state, b)
        losses.append(log_metrics(metrics))    # sync hidden two calls deep
    return state, losses
