"""Clean variants: cadence-guarded sync (the standard logging pattern)
and device-side accumulation with one sync after the loop."""
from .mid import log_metrics


def train_logged(step, state, batches, log_every):
    for i, b in enumerate(batches):
        state, metrics = step(state, b)
        if i % log_every == 0:          # intentional once-per-interval sync
            log_metrics(metrics)
    return state


def train_accumulated(step, state, batches):
    total = None
    for b in batches:
        state, metrics = step(state, b)
        loss = metrics["loss"]          # stays on device
        total = loss if total is None else total + loss
    return state, log_metrics({"loss": total})   # ONE sync, after the loop
