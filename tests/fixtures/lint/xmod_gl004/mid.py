"""Middle helper: one more call level between the loop and the sync."""
from .leaf import fetch_loss


def log_metrics(metrics):
    return fetch_loss(metrics)
