"""GL019 good: yield instead of blocking; budget or offload the rest."""

import asyncio


class Poller:
    async def tick(self):
        await asyncio.sleep(0.5)

    async def drain(self, reader):
        return await reader.read(4096)

    async def probe(self, client, loop):
        # blocking work offloaded to an executor, with a timeout budget
        return await loop.run_in_executor(
            None, lambda: client.call("health", timeout_s=1.0))
