"""GL017 bad: dtype drift — a raw ref load mixed with a cast operand
inside a kernel body (implicit upcast by the ref's storage dtype), and
uncast scatter/dynamic_update_slice writes into pool-shaped arrays."""

import jax
import jax.numpy as jnp


def _drifty_kernel(q_ref, kp_ref, out_ref, *, scale):
    # raw (possibly int8/bf16) ref load promoted by the OTHER side's
    # explicit f32 cast — the compute precision is invisible here
    s = kp_ref[...] * q_ref[...].astype(jnp.float32)
    out_ref[...] = s


def scatter_uncast(ck, k_m, layer, phys, woff):
    # quantized pools store int8 rows: an uncast write promotes the
    # buffer or rounds through the wrong dtype, silently
    return ck.at[layer, phys, woff, :].set(k_m * 2.0, mode="drop")


def scatter_uncast_bare_name(cv, v_m, layer, phys, woff):
    # the most common spelling — a bare-name fresh row — is just as
    # uncast (only dynamic_update_slice's page-copy idiom is exempt)
    return cv.at[layer, phys, woff, :].set(v_m, mode="drop")


def dus_uncast(cv, v_m, start):
    return jax.lax.dynamic_update_slice(cv, v_m[None], start)
