"""GL006 good: every start index guarded, clamped, or constant."""
import jax
import jax.numpy as jnp

from replicatinggpt_tpu.utils.sanitize import check_in_bounds


def write_guarded(buf, row, pos):
    check_in_bounds(pos, row.shape[0], buf.shape[0])
    return jax.lax.dynamic_update_slice(buf, row, (pos, 0))


def write_asserted(buf, row, pos):
    assert pos + row.shape[0] <= buf.shape[0]
    return jax.lax.dynamic_update_slice(buf, row, (pos, 0))


def write_clamped(buf, row, pos):
    p = jnp.minimum(pos, buf.shape[0] - row.shape[0])
    return jax.lax.dynamic_update_slice(buf, row, (p, 0))


def write_const(buf, row):
    zero = jnp.int32(0)
    return jax.lax.dynamic_update_slice(buf, row, (zero, 0))
