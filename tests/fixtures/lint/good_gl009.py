"""GL009 good: narrow the exception, or log / re-raise typed."""


class CorruptCheckpointError(RuntimeError):
    pass


def rng_shape(mngr, step):
    try:
        return mngr.item_metadata(step)["state"]["rng"].shape
    except (KeyError, TypeError, OSError) as e:
        raise CorruptCheckpointError(
            f"checkpoint step {step} is corrupt: {e}") from e


def fetch_loss(metrics, logger):
    import jax
    try:
        return jax.device_get(metrics["loss"])
    except Exception as e:       # broad, but the failure is logged
        logger.warning(f"loss fetch failed: {e!r}")
        return 0.0
