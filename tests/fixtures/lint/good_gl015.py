"""GL015 good: launch only enqueues; the one sync lives in the
drain-side function, after the next window is in flight."""

import numpy as np


class Engine:
    def _launch(self, k):
        out = self._dispatch(k)      # enqueue only; no device wait
        copy = getattr(out.toks, "copy_to_host_async", None)
        if copy is not None:
            copy()                   # overlap the transfer
        return out

    def _drain_window(self, w):
        return np.asarray(w.toks)    # the ONE sync, at the boundary
