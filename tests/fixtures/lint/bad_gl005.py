"""GL005 bad: jit over update-in-place pytrees without donation."""
import jax


@jax.jit
def update(state, batch):            # old state buffers stay live
    return state


def make_step():
    def inner(state, cache):
        return state, cache
    return jax.jit(inner)            # resolvable wrap site, no donation
