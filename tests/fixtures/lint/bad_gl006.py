"""GL006 bad: dynamic_update_slice with an unguarded start index."""
import jax


def write(buf, row, pos):
    # out-of-bounds pos CLAMPS and overwrites earlier rows
    return jax.lax.dynamic_update_slice(buf, row, (pos, 0))


def write_in_dim(buf, row, i):
    return jax.lax.dynamic_update_slice_in_dim(buf, row, i, axis=0)
