"""GL010 good: every PartitionSpec axis exists on the mesh it targets."""
import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_batch(devices, batch):
    mesh = Mesh(np.asarray(devices), ("data", "seq", "model"))
    sharding = NamedSharding(mesh, P("data", "seq"))
    return jax.device_put(batch, sharding)


def shard_mapped(devices, fn, xs):
    from jax.experimental.shard_map import shard_map
    mesh = Mesh(np.asarray(devices), ("data", "model"))
    return shard_map(fn, mesh, in_specs=P("model"),
                     out_specs=P("data"))(xs)
