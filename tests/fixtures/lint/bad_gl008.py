"""GL008 bad: pmap/shard_map bodies reading module globals."""
import jax
import numpy as np

table = np.zeros((16, 4))            # module global


def embed(ids):
    return table[ids]                # broadcast into every program


embed_p = jax.pmap(embed)


@jax.pmap
def lookup(ids):
    return table[ids] + 1
