"""GL022 good: whitelist, builder, and config fields all agree."""

ENGINE_FORWARD_FLAGS = (
    ("pool_size", "--pool-size"),
    ("max_queue", "--max-queue"),
    ("page_size", "--page-size"),
)
ENGINE_FORWARD_SWITCHES = (("no_prefix_cache", "--no-prefix-cache"),)


class EngineConfig:
    pool_size: int = 8
    max_queue: int = 64
    page_size: int = 0
    prefix_cache: bool = True


def engine_config_from_args(args):
    return EngineConfig(pool_size=args.pool_size,
                        max_queue=args.max_queue,
                        page_size=args.page_size,
                        prefix_cache=not args.no_prefix_cache)
