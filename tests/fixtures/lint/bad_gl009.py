"""GL009 bad: broad except swallowing checkpoint/device I/O failures."""


def rng_shape(mngr, step):
    try:
        return mngr.item_metadata(step)["state"]["rng"].shape
    except Exception:            # corrupt step vanishes here
        return None


def fetch_loss(metrics):
    import jax
    try:
        return jax.device_get(metrics["loss"])
    except:                      # noqa: E722 — bare except, no trace left
        return 0.0
