"""GL011 bad: sharding-annotated program captures an unsharded module
array."""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

table = np.zeros((1024, 64), np.float32)    # module array, no sharding


@partial(jax.jit, in_shardings=(None,))
def embed(ids):
    return jnp.take(table, ids, axis=0)     # baked in, fully replicated
