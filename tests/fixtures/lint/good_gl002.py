"""GL002 good: host constants at module scope, device work inside fns."""
import jax.numpy as jnp
import numpy as np

MASK = np.tril(np.ones((64, 64)))       # host constant


def f(x):
    return x + jnp.asarray(MASK)        # device work happens traced


def g(x, shape=(2,)):
    return x + jnp.zeros(shape)
