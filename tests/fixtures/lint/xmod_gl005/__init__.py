"""Cross-module GL005 fixture package: donated buffer read after the
jitted call, through a local alias."""
