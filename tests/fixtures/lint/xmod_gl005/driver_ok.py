"""Clean variant: only the returned value is read after the call."""
from .steps import train_step


def run(state, batch):
    new_state = train_step(state, batch)
    return new_state, new_state.mean()
