"""The jitted step: donates its state pytree (correct on its own)."""
from functools import partial

import jax


@partial(jax.jit, donate_argnames=("state",))
def train_step(state, batch):
    return state
