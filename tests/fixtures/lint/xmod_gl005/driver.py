"""Reads the donated buffer after the call, through an alias —
interprocedural GL005 must fire HERE."""
from .steps import train_step


def run(state, batch):
    snapshot = state                      # alias of the soon-donated buffer
    new_state = train_step(state, batch)
    return new_state, snapshot.mean()     # read-after-donate via alias
