"""GL015 bad: blocking fetches / window drains on the launch side of a
windowed dispatch path."""

import numpy as np


class Engine:
    def _launch(self, k):
        toks = np.asarray(self._inflight.toks)   # blocks mid-launch
        self._drain_pending()                    # breaks the window
        return self._dispatch(k), toks

    def _launch_mixed(self, k):
        w = self._dispatch(k)
        w.toks.block_until_ready()               # serializes every window
        return w
