"""GL005 good: donation declared, or no update-in-place parameter."""
from functools import partial

import jax


@partial(jax.jit, donate_argnames=("state",))
def update(state, batch):
    return state


@jax.jit
def evaluate(params, batch):         # read-only pytree: donation optional
    return params


def make_step():
    def inner(state, cache):
        return state, cache
    return jax.jit(inner, donate_argnums=(0, 1))
