"""GL004 bad: device->host syncs inside step loops."""
import numpy as np


def eval_loop(step, params, batches):
    total = 0.0
    for b in batches:
        total += float(step(params, b))     # sync per batch
    return total


def fetch_loop(decode, toks):
    outs = []
    while toks:
        t = decode(toks.pop())
        outs.append(np.asarray(t))          # sync per token
        outs[-1].item()                     # and again
    return outs
