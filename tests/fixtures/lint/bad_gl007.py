"""GL007 bad: non-hashable values for static jit parameters."""
from functools import partial

import jax


@partial(jax.jit, static_argnames=("dims",))
def pool(x, dims=[1, 2]):            # unhashable default
    return x.sum(tuple(dims))


@partial(jax.jit, static_argnames=("cfg",))
def run(x, cfg):
    return x


def caller(x):
    return run(x, cfg={"layers": 2})  # unhashable at the callsite
