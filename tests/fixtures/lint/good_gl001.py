"""GL001 good: static args, device-side select, identity checks."""
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("n",))
def step(x, n):
    if n > 0:                 # n is a static (hashable) Python value
        x = x * n
    return x


@jax.jit
def masked(x, n):
    return jnp.where(n > 0, x * n, x)   # branch ON DEVICE


@jax.jit
def optional(x, rng):
    if rng is None:           # identity check: static under tracing
        return x
    return x + 1
