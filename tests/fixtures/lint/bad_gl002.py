"""GL002 bad: device computation at module import time."""
import jax
import jax.numpy as jnp

MASK = jnp.tril(jnp.ones((64, 64)))     # device alloc at import
NOISE = jax.random.normal(jax.random.PRNGKey(0), (8,))


def f(x, m=jnp.zeros((2,))):            # default evaluated at import
    return x + m
