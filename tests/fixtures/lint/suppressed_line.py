"""Line-level pragma: the finding on the tagged line is suppressed."""
import numpy as np


def loop(xs):
    out = []
    for x in xs:
        out.append(np.asarray(x))  # graftlint: disable=GL004
    return out
