"""GL016 good: the local-mode backend (is_local = True) may read its
own journal — same filesystem by construction; the router reconciles
through the backend's journal_state() and its OWN ledger."""


class Replica:
    is_local = True                            # in-process backend

    def __init__(self, journal_path):
        self.journal_path = journal_path

    def journal_state(self):
        return RequestJournal.unfinished(self.journal_path)


class Router:
    def __init__(self, ledger_path):
        # the router's OWN crash journal is its own disk — fine
        self.recovered = RequestJournal.unfinished(ledger_path)

    def reconcile(self, rep):
        # the BACKEND owns journal access: local file or the
        # journal_drain RPC — the router never sees a worker path
        return rep.journal_state()
