"""GL014 good: everything the jitted body needs arrives as an argument;
the donated buffer is threaded, never captured."""
from functools import partial

import jax


@partial(jax.jit, donate_argnames=("s",))
def step(s, delta):
    return s + delta


def advance(state, delta):
    state = step(state, delta)          # rebound: no read-after-donate
    return state
