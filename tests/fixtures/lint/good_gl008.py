"""GL008 good: per-device data arrives as arguments; constants allowed."""
import jax
import numpy as np

TABLE = np.zeros((16, 4))            # ALL-CAPS constant: allowed


def embed(table, ids):               # explicit argument
    return table[ids]


embed_p = jax.pmap(embed, in_axes=(None, 0))


def local_ok(ids):
    table = ids * 2                  # local shadows nothing
    return table


local_p = jax.pmap(local_ok)
