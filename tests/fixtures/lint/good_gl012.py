"""GL012 good: one spec per argument / per returned element."""
from functools import partial

import jax


@partial(jax.jit, in_shardings=(None, None, None))
def apply3(x, w, b):
    return x @ w + b


def pair(x):
    return x, x


paired = jax.jit(pair, out_shardings=(None, None))
