"""GL007 good: hashable statics (tuples / frozen configs)."""
from functools import partial

import jax


@partial(jax.jit, static_argnames=("dims",))
def pool(x, dims=(1, 2)):
    return x.sum(dims)


def caller(x):
    return pool(x, dims=(1, 3))
