"""GL014 bad: the donated buffer is ALSO a closure constant of the
jitted body — donation frees memory the program holds baked in."""
from functools import partial

import jax
import jax.numpy as jnp

state = jnp.zeros((128,))     # graftlint: disable=GL002


@partial(jax.jit, donate_argnames=("s",))
def step(s):
    return s + state                    # captures `state` as a constant


def advance():
    return step(state)                  # ...and donates the same buffer
