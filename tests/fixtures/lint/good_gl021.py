"""GL021 good: every pin incremented, every family literal pinned."""

PROM_PINNED_COUNTERS = (
    "fleet_requests_routed",
    "fleet_replica_downs",
)


class Stepper:
    def step(self, metrics):
        metrics.inc("fleet_requests_routed")
        metrics.inc("fleet_replica_downs")
        metrics.inc("engine_steps")   # outside the pinned families: fine
