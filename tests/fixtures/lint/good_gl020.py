"""GL020 good: ledger first, then the delivery-map store."""


class MiniRouter:
    def __init__(self, journal):
        self.journal = journal
        self.results = {}

    def on_finish(self, res):
        if self.journal is not None:
            self.journal.record_finish(res.id, res.finish_reason)
        self.results[res.id] = res
