"""Cross-module GL002 fixture package: import-time device work hidden
behind a re-exported wrapper function."""
