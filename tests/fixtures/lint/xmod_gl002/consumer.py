"""Calls the wrapper at module scope: device alloc at import time, one
re-export away — interprocedural GL002 must fire HERE."""
from .maker import build_mask

MASK = build_mask(1024)
