"""Clean variant: the mask is built lazily, inside a function."""
from .maker import build_mask


def get_mask(n):
    return build_mask(n)
