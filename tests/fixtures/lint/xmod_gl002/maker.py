"""The wrapper: device computation inside a function — clean on its own
(nothing runs at maker.py import time)."""
import jax.numpy as jnp


def build_mask(n):
    return jnp.tril(jnp.ones((n, n)))
