"""Serving-engine tests (serve/): greedy parity with offline generate
regardless of arrival order, slot free/reuse, backpressure, deadlines,
cancellation, per-slot sampling params, and the steady-state
zero-recompile guarantee."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replicatinggpt_tpu.config import ModelConfig
from replicatinggpt_tpu.models.gpt import init_params
from replicatinggpt_tpu.sample import GenerateConfig, generate
from replicatinggpt_tpu.serve import (CachePool, Engine, EngineConfig,
                                      ReplayConfig, Request, RequestResult,
                                      SamplingParams, Scheduler,
                                      compile_counts, run_replay)
from replicatinggpt_tpu.serve.requests import (FINISH_CANCELLED,
                                               FINISH_DEADLINE,
                                               FINISH_LENGTH_CAP,
                                               FINISH_MAX_TOKENS,
                                               REJECT_PROMPT_TOO_LONG,
                                               REJECT_QUEUE_FULL)

CFG = ModelConfig(vocab_size=65, block_size=32, n_layer=2, n_head=2,
                  n_embd=32, dropout=0.0, attn_dropout=0.0, dtype="float32")


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _requests(n=6, greedy=True, seed=3, max_new=None):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        P = int(rng.integers(1, CFG.block_size // 2))
        prompt = rng.integers(0, CFG.vocab_size, (P,)).astype(np.int32)
        out.append(Request(
            id=f"r{i}", prompt=prompt,
            max_new_tokens=max_new or int(rng.integers(4, 14)),
            sampling=SamplingParams(greedy=greedy), rng_seed=i))
    return out


def _offline_greedy(params, reqs):
    return {r.id: np.asarray(generate(
        params, r.prompt[None, :], CFG,
        GenerateConfig(max_new_tokens=r.max_new_tokens, greedy=True))
    )[0].tolist() for r in reqs}


# ---------------------------------------------------------------------------
# parity
# ---------------------------------------------------------------------------

def test_greedy_parity_any_arrival_order(params):
    """Engine greedy output must be token-identical to offline
    generate() per request, for a pool smaller than the request count,
    under different submission orders (continuous batching must not
    leak anything between slots)."""
    reqs = _requests(6)
    want = _offline_greedy(params, reqs)
    for order in (list(range(6)), [5, 2, 0, 4, 1, 3]):
        eng = Engine(params, CFG, EngineConfig(pool_size=3, max_queue=16))
        for i in order:
            assert eng.submit(reqs[i]) is None
        got = {r.id: r.tokens for r in eng.drain()}
        assert got == want


def test_greedy_parity_packed_cache_layout(params):
    """The packed (L,B,S,C) pooled-cache layout must produce the same
    greedy tokens through the engine (decode_step_multi's packed write
    path + chunked-prefill packed path)."""
    pc = dataclasses.replace(CFG, decode_cache_layout="packed")
    reqs = _requests(4)
    want = _offline_greedy(params, reqs)
    eng = Engine(params, pc, EngineConfig(pool_size=2, max_queue=8))
    for r in reqs:
        assert eng.submit(r) is None
    got = {r.id: r.tokens for r in eng.drain()}
    assert got == want


def test_prefill_chunk_rounded_to_block_divisor(params):
    """A --prefill-chunk that does not divide block_size must be rounded
    down to a divisor (a non-divisor's padded final chunk would start
    past the cache buffer, where dynamic_update_slice silently CLAMPS
    and corrupts earlier K/V) — and parity must hold at the rounded
    chunk, including prompts whose final chunk is the last one in the
    buffer."""
    ecfg = EngineConfig(pool_size=2, max_queue=8, prefill_chunk=12)
    assert ecfg.chunk(CFG.block_size) == 8     # largest divisor of 32 <= 12
    assert EngineConfig(prefill_chunk=48).chunk(256) == 32
    assert EngineConfig().chunk(31) == 31      # degenerate: c | c always
    reqs = _requests(3) + [Request(
        id="edge", prompt=np.arange(CFG.block_size - 1, dtype=np.int32) % 17,
        max_new_tokens=2, sampling=SamplingParams(greedy=True))]
    want = _offline_greedy(params, reqs)
    eng = Engine(params, CFG, ecfg)
    for r in reqs:
        assert eng.submit(r) is None
    got = {r.id: r.tokens for r in eng.drain()}
    assert got == want


def test_decode_step_multi_matches_single_row(params):
    """decode_step_multi at staggered per-slot positions must equal
    independent single-row decode_step calls (per-row independence is
    what the parity guarantee rests on)."""
    from replicatinggpt_tpu.models.gpt import (decode_step,
                                               decode_step_multi,
                                               init_kv_cache)
    B = 3
    rng = np.random.default_rng(0)
    warm = [int(x) for x in rng.integers(2, 7, (B,))]  # per-row warm length
    toks = rng.integers(0, CFG.vocab_size, (B, 8)).astype(np.int32)

    # single-row references, each warmed to its own position
    singles = []
    for b in range(B):
        cache = init_kv_cache(CFG, 1)
        for pos in range(warm[b]):
            logits, cache = decode_step(params, toks[b:b + 1, pos],
                                        jnp.int32(pos), cache, CFG)
        singles.append((logits, cache))

    # multi-slot: warm each slot by stepping all slots with per-slot pos
    cache_m = init_kv_cache(CFG, B)
    pos = np.zeros((B,), np.int32)
    logits_m = None
    for step in range(max(warm)):
        cur = np.array([toks[b, min(step, warm[b] - 1)] for b in range(B)])
        step_pos = np.minimum(step, np.array(warm) - 1).astype(np.int32)
        out, cache_m = decode_step_multi(params, jnp.asarray(cur),
                                         jnp.asarray(step_pos), cache_m, CFG)
        if logits_m is None or step == max(warm) - 1:
            logits_m = out
    # rows that reached their final position on the last step must match
    for b in range(B):
        if warm[b] == max(warm):
            np.testing.assert_allclose(np.asarray(logits_m[b]),
                                       np.asarray(singles[b][0][0]),
                                       atol=1e-5, rtol=1e-5)


# ---------------------------------------------------------------------------
# slots: free / reuse / cancellation
# ---------------------------------------------------------------------------

def test_slot_free_and_reuse_after_completion(params):
    reqs = _requests(5, max_new=6)
    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=16))
    for r in reqs:
        assert eng.submit(r) is None
    max_used = 0
    results = []
    while not eng.idle:
        results.extend(eng.step())
        max_used = max(max_used, eng.pool.n_used)
    assert len(results) == 5
    assert all(r.finish_reason == FINISH_MAX_TOKENS for r in results)
    assert max_used == 2                      # pool bound respected
    assert eng.pool.n_free == 2               # everything released
    assert eng.metrics.counters["requests_admitted"] == 5


def test_cancellation_frees_slot_and_queue(params):
    eng = Engine(params, CFG, EngineConfig(pool_size=1, max_queue=4))
    long_req = Request(id="long", prompt=np.array([1], np.int32),
                       max_new_tokens=30,
                       sampling=SamplingParams(greedy=True))
    queued = Request(id="queued", prompt=np.array([2], np.int32),
                     max_new_tokens=3, sampling=SamplingParams(greedy=True))
    assert eng.submit(long_req) is None
    assert eng.submit(queued) is None
    for _ in range(3):
        eng.step()
    assert eng.pool.slot_of("long") is not None
    assert eng.cancel("long")
    assert eng.pool.n_free == 1               # slot freed immediately
    res = {r.id: r for r in eng.drain()}
    assert set(res) == {"long", "queued"}
    assert res["long"].finish_reason == FINISH_CANCELLED
    assert len(res["long"].tokens) == 3       # partial output preserved
    assert res["queued"].finish_reason == FINISH_MAX_TOKENS
    assert len(res["queued"].tokens) == 3
    # cancelling a queued request removes it before admission
    eng2 = Engine(params, CFG, EngineConfig(pool_size=1, max_queue=4))
    assert eng2.submit(long_req) is None
    assert eng2.submit(queued) is None
    assert eng2.cancel("queued")
    res2 = {r.id: r for r in eng2.drain()}
    assert set(res2) == {"long", "queued"}
    assert res2["queued"].finish_reason == FINISH_CANCELLED
    assert res2["queued"].tokens == []
    assert not eng2.cancel("nonexistent")


def test_cancel_admitted_request_mid_decode_and_slot_reuse(params):
    """Engine-side cancellation of an ALREADY-ADMITTED request: the slot
    frees immediately, the partial output is preserved on the terminal
    result, the freed slot serves the next request with exact greedy
    parity, and the surviving neighbor's stream is untouched — all
    without a recompile (the cancel only flips host-side state)."""
    from replicatinggpt_tpu.serve import compile_counts
    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=4))
    doomed = Request(id="doomed", prompt=np.array([5, 6, 7], np.int32),
                     max_new_tokens=25,
                     sampling=SamplingParams(greedy=True))
    neighbor = Request(id="neighbor", prompt=np.array([9, 10], np.int32),
                       max_new_tokens=8,
                       sampling=SamplingParams(greedy=True))
    assert eng.submit(doomed) is None
    assert eng.submit(neighbor) is None
    for _ in range(4):
        eng.step()
    assert eng.pool.slot_of("doomed") is not None
    counts = compile_counts()
    assert eng.cancel("doomed")
    assert eng.pool.slot_of("doomed") is None    # slot freed immediately
    assert eng.pool.n_free == 1
    assert not eng.cancel("doomed")              # already gone
    successor = Request(id="successor", prompt=np.array([3, 4], np.int32),
                        max_new_tokens=6,
                        sampling=SamplingParams(greedy=True))
    assert eng.submit(successor) is None
    res = {r.id: r for r in eng.drain()}
    assert res["doomed"].finish_reason == FINISH_CANCELLED
    assert len(res["doomed"].tokens) == 4        # partial output kept
    offline = _offline_greedy(params, [neighbor, successor])
    for rid in ("neighbor", "successor"):
        assert res[rid].finish_reason == FINISH_MAX_TOKENS
        assert res[rid].tokens == offline[rid]
    assert compile_counts() == counts            # cancel is host-only


def test_cancel_admitted_request_speculative_path(params):
    """The same engine-side cancel under speculative decoding: the
    drafter's slot lifecycle (on_release) stays in sync and the freed
    slot is reusable with a drafter attached."""
    from replicatinggpt_tpu.serve import NGramDrafter

    class TrackingDrafter(NGramDrafter):
        def __init__(self, k):
            super().__init__(k)
            self.released = []

        def on_release(self, slot):
            self.released.append(slot)
            super().on_release(slot)

    drafter = TrackingDrafter(k=2)
    eng = Engine(params, CFG, EngineConfig(pool_size=1, max_queue=4),
                 drafter=drafter)
    doomed = Request(id="doomed",
                     prompt=np.array([5, 6, 5, 6, 5, 6], np.int32),
                     max_new_tokens=20,
                     sampling=SamplingParams(greedy=True))
    assert eng.submit(doomed) is None
    for _ in range(3):
        eng.step()
    slot = eng.pool.slot_of("doomed")
    assert slot is not None
    n_before = len(eng._slots[slot].tokens)
    assert n_before > 0
    assert eng.cancel("doomed")
    assert drafter.released == [slot]            # drafter told exactly once
    nxt = Request(id="next", prompt=np.array([7, 8, 7, 8], np.int32),
                  max_new_tokens=6, sampling=SamplingParams(greedy=True))
    assert eng.submit(nxt) is None
    res = {r.id: r for r in eng.drain()}
    assert res["doomed"].finish_reason == FINISH_CANCELLED
    assert len(res["doomed"].tokens) == n_before
    assert res["next"].finish_reason == FINISH_MAX_TOKENS
    assert res["next"].tokens == _offline_greedy(params, [nxt])["next"]


# ---------------------------------------------------------------------------
# admission control: backpressure, validation, deadlines, length caps
# ---------------------------------------------------------------------------

def test_backpressure_rejects_when_queue_full(params):
    eng = Engine(params, CFG, EngineConfig(pool_size=1, max_queue=2))
    reqs = _requests(5, max_new=4)
    rejected = [r for r in (eng.submit(q) for q in reqs) if r is not None]
    # slot admission happens at step(), so submit #3..#5 hit a full queue
    assert len(rejected) == 3
    assert all(r.finish_reason == REJECT_QUEUE_FULL for r in rejected)
    assert eng.metrics.counters[REJECT_QUEUE_FULL] == 3
    accepted = eng.drain()
    assert len(accepted) == 2
    assert all(r.finish_reason == FINISH_MAX_TOKENS for r in accepted)


def test_prompt_too_long_rejected(params):
    eng = Engine(params, CFG, EngineConfig(pool_size=1, max_queue=2))
    r = eng.submit(Request(id="big",
                           prompt=np.zeros((CFG.block_size + 1,), np.int32)))
    assert r is not None and r.finish_reason == REJECT_PROMPT_TOO_LONG


def test_deadline_expiry_queued_and_active(params):
    t = [0.0]
    eng = Engine(params, CFG, EngineConfig(pool_size=1, max_queue=4),
                 clock=lambda: t[0])
    active = Request(id="active", prompt=np.array([1], np.int32),
                     max_new_tokens=30, deadline=5.0,
                     sampling=SamplingParams(greedy=True))
    queued = Request(id="queued", prompt=np.array([2], np.int32),
                     max_new_tokens=4, deadline=1.0,
                     sampling=SamplingParams(greedy=True))
    assert eng.submit(active) is None
    assert eng.submit(queued) is None
    eng.step()                                 # admits 'active' only
    t[0] = 2.0                                 # queued deadline passes
    finished = eng.step()
    assert [r.id for r in finished] == ["queued"]
    assert finished[0].finish_reason == FINISH_DEADLINE
    t[0] = 6.0                                 # active deadline passes
    finished = eng.step()
    assert [r.id for r in finished] == ["active"]
    assert finished[0].finish_reason == FINISH_DEADLINE
    assert eng.pool.n_free == 1
    assert 0 < len(finished[0].tokens) < 30    # partial output preserved


def test_max_new_tokens_and_context_length_cap(params):
    """A request whose budget exceeds the slot's cache room finishes
    with the length_cap reason and exactly room = S - P + 1 tokens."""
    P = CFG.block_size - 4
    room = CFG.block_size - P + 1
    eng = Engine(params, CFG, EngineConfig(pool_size=1, max_queue=2))
    res = eng.submit(Request(id="cap",
                             prompt=np.ones((P,), np.int32),
                             max_new_tokens=100,
                             sampling=SamplingParams(greedy=True)))
    assert res is None
    out = eng.drain()
    assert out[0].finish_reason == FINISH_LENGTH_CAP
    assert len(out[0].tokens) == room


# ---------------------------------------------------------------------------
# per-slot sampling params + batched filters
# ---------------------------------------------------------------------------

def test_mixed_batch_greedy_row_unaffected_by_stochastic_neighbors(params):
    reqs = _requests(4, greedy=True, max_new=8)
    want = _offline_greedy(params, reqs)
    # neighbors with aggressive stochastic settings share the batch
    noisy = [Request(id=f"n{i}", prompt=np.array([i + 1], np.int32),
                     max_new_tokens=8,
                     sampling=SamplingParams(temperature=1.7, top_k=5,
                                             top_p=0.9), rng_seed=100 + i)
             for i in range(3)]
    eng = Engine(params, CFG, EngineConfig(pool_size=4, max_queue=16))
    for r in (noisy[0], reqs[0], noisy[1], reqs[1], reqs[2], noisy[2],
              reqs[3]):
        assert eng.submit(r) is None
    got = {r.id: r.tokens for r in eng.drain()}
    for rid, toks in want.items():
        assert got[rid] == toks
    for n in noisy:                          # stochastic rows still valid
        assert len(got[n.id]) == 8
        assert all(0 <= t < CFG.vocab_size for t in got[n.id])


def test_stochastic_request_reproducible_by_seed(params):
    """A request's sampled stream is keyed by its own rng_seed — same
    seed twice gives the same tokens, independent of slot/batch."""
    def run(pool):
        eng = Engine(params, CFG, EngineConfig(pool_size=pool, max_queue=8))
        reqs = [Request(id=f"s{i}", prompt=np.array([7], np.int32),
                        max_new_tokens=10,
                        sampling=SamplingParams(temperature=0.9, top_k=12),
                        rng_seed=42 + i) for i in range(3)]
        for r in reqs:
            eng.submit(r)
        return {r.id: r.tokens for r in eng.drain()}

    a, b = run(pool=3), run(pool=1)          # different batching, same seeds
    assert a == b


def test_batched_filters_match_scalar_filters():
    from replicatinggpt_tpu.sample.generate import (batched_top_k_filter,
                                                    batched_top_p_filter,
                                                    _top_k_filter,
                                                    _top_p_filter)
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(4, 200)), jnp.float32)
    # per-row k: rows 0/1 filtered at different k, row 2 off (0), row 3 off (>=V)
    k = jnp.asarray([5, 50, 0, 200], jnp.int32)
    got = np.asarray(batched_top_k_filter(logits, k))
    np.testing.assert_array_equal(got[0], np.asarray(
        _top_k_filter(logits[:1], 5))[0])
    np.testing.assert_array_equal(got[1], np.asarray(
        _top_k_filter(logits[1:2], 50))[0])
    np.testing.assert_array_equal(got[2], np.asarray(logits[2]))  # passthrough
    np.testing.assert_array_equal(got[3], np.asarray(logits[3]))
    p = jnp.asarray([0.3, 0.9, 0.0, 1.0], jnp.float32)
    got = np.asarray(batched_top_p_filter(logits, p))
    np.testing.assert_array_equal(got[0], np.asarray(
        _top_p_filter(logits[:1], 0.3))[0])
    np.testing.assert_array_equal(got[1], np.asarray(
        _top_p_filter(logits[1:2], 0.9))[0])
    np.testing.assert_array_equal(got[2], np.asarray(logits[2]))
    np.testing.assert_array_equal(got[3], np.asarray(logits[3]))


# ---------------------------------------------------------------------------
# steady state: zero recompiles + metrics (acceptance criterion)
# ---------------------------------------------------------------------------

def test_steady_state_64_requests_zero_recompiles(params):
    """>= 64 requests through a pool of 8 (smaller than the request
    count): completes, reports TTFT/tok-s/occupancy, and compiles ZERO
    new programs after the warmup request."""
    ecfg = EngineConfig(pool_size=8, max_queue=64)
    warm = Engine(params, CFG, ecfg)
    warm.submit(Request(id="w", prompt=np.array([1], np.int32),
                        max_new_tokens=2,
                        sampling=SamplingParams(greedy=True)))
    warm.drain()
    baseline = compile_counts()

    eng = Engine(params, CFG, ecfg)
    reqs = _requests(64, greedy=False, seed=9, max_new=6)
    for r in reqs:
        assert eng.submit(r) is None
    results = eng.drain()
    assert compile_counts() == baseline       # zero recompiles at steady state
    assert len(results) == 64
    assert all(r.finish_reason == FINISH_MAX_TOKENS for r in results)
    s = eng.metrics_summary()
    assert s["histograms"]["ttft_s"]["n"] == 64
    assert s["histograms"]["ttft_s"]["p50"] > 0
    assert s["histograms"]["decode_tokens_per_s"]["p50"] > 0
    assert 0 < s["histograms"]["batch_fill_ratio"]["mean"] <= 1
    assert s["step_latency"]["p50_s"] > 0
    assert s["counters"]["decode_tokens"] == 64 * 6


# ---------------------------------------------------------------------------
# unit: scheduler + cache pool
# ---------------------------------------------------------------------------

def test_scheduler_bounds_and_fifo():
    sch = Scheduler(max_queue=2, block_size=8, clock=lambda: 0.0)
    a = Request(id="a", prompt=np.array([1], np.int32))
    b = Request(id="b", prompt=np.array([1], np.int32))
    c = Request(id="c", prompt=np.array([1], np.int32))
    assert sch.submit(a) is None and sch.submit(b) is None
    assert sch.submit(c) == REJECT_QUEUE_FULL
    admitted, dropped = sch.admit(n_free=1)
    assert [r.id for r, _ in admitted] == ["a"] and not dropped
    assert sch.depth == 1
    assert sch.cancel("b") and not sch.cancel("b")


def test_cache_pool_acquire_release():
    pool = CachePool(CFG, n_slots=2)
    s0, s1 = pool.acquire("a"), pool.acquire("b")
    assert {s0, s1} == {0, 1} and pool.acquire("c") is None
    assert pool.occupancy == 1.0 and pool.slot_of("b") == s1
    pool.release(s0)
    assert pool.n_free == 1 and pool.owner(s0) is None
    assert pool.acquire("c") == s0            # freed slot is reused
    pool.release(s1)
    with pytest.raises(AssertionError):
        pool.release(s1)                      # double free caught


# ---------------------------------------------------------------------------
# replay driver + CLI smoke (tier-1) and soak (slow)
# ---------------------------------------------------------------------------

def test_serve_replay_smoke(params):
    """Tiny replay through the public driver: everything completes,
    metrics summary is well-formed, zero recompiles after warmup."""
    s = run_replay(params, CFG,
                   ReplayConfig(n_requests=16, rate=2000.0, seed=0,
                                prompt_len_max=12, max_new_tokens=5,
                                greedy=True),
                   EngineConfig(pool_size=4, max_queue=32))
    assert s["n_completed"] == 16
    assert s["recompiles_after_warmup"] == 0
    assert s["generated_tokens"] == 16 * 5
    assert s["aggregate_tokens_per_s"] > 0


def test_serve_replay_cli_smoke(capsys):
    from replicatinggpt_tpu.cli import main
    rc = main(["serve-replay", "--preset", "test-tiny", "--n-requests",
               "16", "--pool-size", "4", "--rate", "2000",
               "--request-max-new-tokens", "4", "--greedy"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "16 completed" in out
    assert "recompiles after warmup: 0" in out
    assert "TTFT" in out


@pytest.mark.slow
def test_serve_replay_soak(params):
    """Longer mixed soak: 200 stochastic requests with deadlines through
    a small pool — no leaks (pool fully free), queue drained, every
    request resolved exactly once."""
    s = run_replay(params, CFG,
                   ReplayConfig(n_requests=200, rate=3000.0, seed=5,
                                prompt_len_max=16, max_new_tokens=10,
                                temperature=0.9, top_k=10),
                   EngineConfig(pool_size=6, max_queue=256))
    assert s["n_requests"] == 200
    # every request resolved exactly once (queue deep enough: no rejects)
    assert s["n_completed"] + s["n_rejected"] == 200
    assert s["recompiles_after_warmup"] == 0
    assert s["histograms"]["ttft_s"]["n"] == 200 - s["n_rejected"]
