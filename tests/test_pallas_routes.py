"""Unified Pallas kernel family (ISSUE 20): every engine route —
decode, mixed prefill+decode windows, speculative verify — through ONE
parameterized kernel (`ops/paged_pallas.paged_window_attention`), with
in-kernel dequant for int8/fp8 at page/head granularity, a shard_map
wrapper for >1 (data, model) meshes, and the route decision made once
per engine and exported (`metrics_summary()["kernel_route"]`).

Acceptance pinned here:
- route matrix: `kernel_route == "pallas"` (empty reasons) for every
  shipped configuration — quantized, weight-quantized, W8A8, sharded;
- interpret-mode parity of the windowed kernel vs the XLA gather
  reference for fp8 KV and head-granularity scales (the old
  documented fallback seams), unsharded and under shard_map;
- engine greedy-stream parity with the XLA route for mixed windows,
  speculative verify, and a sharded 2x2 engine;
- zero recompiles across a paged-kernel replay with admissions.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replicatinggpt_tpu.config import ModelConfig
from replicatinggpt_tpu.models.gpt import init_params
from replicatinggpt_tpu.serve import (Engine, EngineConfig, ReplayConfig,
                                      Request, SamplingParams, run_replay)

CFG = ModelConfig(vocab_size=65, block_size=32, n_layer=2, n_head=2,
                  n_embd=64, dropout=0.0, attn_dropout=0.0,
                  dtype="float32", decode_cache_layout="packed")


@pytest.fixture(scope="module")
def p64():
    return init_params(jax.random.PRNGKey(1), CFG)


@pytest.fixture
def kernel_backend(monkeypatch):
    """CPU runs the kernels in interpret mode; route predicates gate on
    the backend check, so parity tests force it open."""
    from replicatinggpt_tpu.ops import paged_pallas
    monkeypatch.setattr(paged_pallas, "_paged_attn_backend_ok",
                        lambda: True)


def _greedy(rid, prompt, max_new=6):
    return Request(id=rid, prompt=np.asarray(prompt, np.int32),
                   max_new_tokens=max_new,
                   sampling=SamplingParams(greedy=True))


def _run(params, ecfg, reqs, cfg=CFG, drafter=None):
    eng = Engine(params, cfg, ecfg, drafter=drafter)
    for r in reqs:
        assert eng.submit(r) is None
    return {r.id: r.tokens for r in eng.drain()}, eng


# ---------------------------------------------------------------------------
# the route matrix: Pallas everywhere (tier-1)
# ---------------------------------------------------------------------------

def test_kernel_route_matrix_every_shipped_config(kernel_backend):
    """THE ISSUE 20 acceptance: `decide_kernel_route` returns
    route == "pallas" with empty reasons for every shipped
    configuration — no silent XLA fallback is left in the matrix."""
    from replicatinggpt_tpu.parallel.mesh import make_serve_mesh
    from replicatinggpt_tpu.serve.engine import decide_kernel_route
    mesh22 = make_serve_mesh(2, 2)
    matrix = [
        (EngineConfig(paged_kernel=True), None),
        (EngineConfig(paged_kernel=True, kv_quant="int8"), None),
        (EngineConfig(paged_kernel=True, kv_quant="int8",
                      quant_granularity="head"), None),
        (EngineConfig(paged_kernel=True, kv_quant="fp8"), None),
        (EngineConfig(paged_kernel=True, kv_quant="fp8",
                      quant_granularity="head"), None),
        (EngineConfig(paged_kernel=True, weight_quant="int8"), None),
        (EngineConfig(paged_kernel=True, weight_quant="fp8"), None),
        (EngineConfig(paged_kernel=True, weight_quant="int8",
                      act_quant="int8"), None),
        (EngineConfig(paged_kernel=True, decode_window=8), None),
        (EngineConfig(paged_kernel=True, mesh_data=2, mesh_model=2,
                      kv_quant="int8"), mesh22),
        (EngineConfig(paged_kernel=True, mesh_data=2, mesh_model=2,
                      kv_quant="fp8", quant_granularity="head"), mesh22),
    ]
    for ecfg, mesh in matrix:
        route = decide_kernel_route(CFG, ecfg, ecfg.quant(),
                                    page_size=8, n_pages=16, itemsize=4,
                                    n_slots=ecfg.pool_size, mesh=mesh)
        assert route.route == "pallas", (ecfg, route)
        assert route.reasons == (), (ecfg, route)
        assert route.window == "pallas", (ecfg, route)
        assert route.decode in ("fused", "pallas"), (ecfg, route)
        # the fused all-layers kernel keeps its documented gates:
        # unquantized weights + 1x1 mesh only
        if ecfg.quant().weight_enabled or mesh is not None:
            assert route.decode == "pallas", (ecfg, route)
        assert route.sharded == (mesh is not None), (ecfg, route)
    # the knob still exists, and an off-route is attributable
    off = decide_kernel_route(CFG, EngineConfig(), EngineConfig().quant(),
                              page_size=8, n_pages=16, itemsize=4,
                              n_slots=8, mesh=None)
    assert off.route == "xla"
    assert "paged_kernel_off" in off.reasons
    # indivisible mesh geometry names itself
    odd = decide_kernel_route(
        CFG, EngineConfig(paged_kernel=True, mesh_data=2, mesh_model=2),
        EngineConfig().quant(), page_size=8, n_pages=15, itemsize=4,
        n_slots=8, mesh=mesh22)
    assert odd.route == "xla" and "mesh_indivisible" in odd.reasons


# ---------------------------------------------------------------------------
# interpret-mode kernel parity: the old fallback seams, in-kernel now
# ---------------------------------------------------------------------------

def _window_ref(q, kn, vn, kp, vp, tables, pos, n_head):
    """XLA-free reference: gather the logical view, append the fresh
    window rows causally, softmax per head in f64-free numpy."""
    B, W, C = q.shape
    D = C // n_head
    mp = tables.shape[1]
    psz = kp.shape[1]
    out = np.zeros((B, W, C), np.float32)
    for b in range(B):
        hk = kp[tables[b]].reshape(mp * psz, C)[: pos[b]]
        hv = vp[tables[b]].reshape(mp * psz, C)[: pos[b]]
        for j in range(W):
            kk = np.concatenate([hk, kn[b, : j + 1]], 0)
            vv = np.concatenate([hv, vn[b, : j + 1]], 0)
            for h in range(n_head):
                sl = slice(h * D, (h + 1) * D)
                s = kk[:, sl] @ q[b, j, sl] * D ** -0.5
                p = np.exp(s - s.max())
                p /= p.sum()
                out[b, j, sl] = p @ vv[:, sl]
    return out


def _window_inputs(seed=0):
    rng = np.random.default_rng(seed)
    B, W, psz, mp, N, C = 3, 4, 8, 4, 12, 64
    pos = np.array([17, 9, 0], np.int32)   # incl. the fresh-only row
    tables = rng.permutation(N)[: B * mp].reshape(B, mp).astype(np.int32)
    mk = lambda *s: rng.standard_normal(s).astype(np.float32)  # noqa: E731
    return (mk(B, W, C), mk(B, W, C), mk(B, W, C), mk(N, psz, C),
            mk(N, psz, C), tables, pos)


@pytest.mark.parametrize("kv_dtype,gran", [
    ("int8", "head"), ("fp8", "page"), ("fp8", "head")])
def test_windowed_kernel_quantized_parity(kv_dtype, gran):
    """fp8 KV and head-granularity scales were the documented XLA
    seams — the per-head scale-lane selection and the saturating e4m3
    fake-quant now run inside the accumulation loop, parity-pinned
    against the dequantized gather reference."""
    from replicatinggpt_tpu.ops import paged_pallas as pp
    from replicatinggpt_tpu.quant.kv import (fake_quantize_rows,
                                             quantize_rows)
    q, kn, vn, kp, vp, tables, pos = _window_inputs()
    H, D = 2, 32
    kq, ks = quantize_rows(jnp.array(kp), kv_dtype, H, gran)
    vq, vs = quantize_rows(jnp.array(vp), kv_dtype, H, gran)
    expand = (lambda s: np.asarray(s)[..., None] if gran == "page"
              else np.asarray(jnp.repeat(s, D, -1)))
    kpf = np.asarray(kq, np.float32).astype(np.float32) * expand(ks)
    vpf = np.asarray(vq, np.float32).astype(np.float32) * expand(vs)
    knf = np.asarray(fake_quantize_rows(jnp.array(kn), kv_dtype, H, gran))
    vnf = np.asarray(fake_quantize_rows(jnp.array(vn), kv_dtype, H, gran))
    ref = _window_ref(q, knf, vnf, kpf, vpf, tables, pos, H)
    out = pp.paged_window_attention(
        jnp.array(q), jnp.array(knf), jnp.array(vnf), kq, vq,
        jnp.array(tables), jnp.array(pos), n_head=H,
        k_scales=ks, v_scales=vs)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4,
                               rtol=1e-4)


def test_sharded_window_kernel_matches_reference():
    """The shard_map wrapper on a 2x2 (data, model) mesh: per-shard
    table localization + cross-shard online-softmax merge must match
    the unsharded reference bit-for-float — plain AND fp8/head pools
    (forced 8-device CPU mesh from conftest)."""
    from replicatinggpt_tpu.ops import paged_pallas as pp
    from replicatinggpt_tpu.parallel.mesh import make_serve_mesh
    from replicatinggpt_tpu.quant.kv import (fake_quantize_rows,
                                             quantize_rows)
    mesh = make_serve_mesh(2, 2)
    q, kn, vn, kp, vp, tables, pos = _window_inputs(seed=3)
    H, D = 2, 32
    ref = _window_ref(q, kn, vn, kp, vp, tables, pos, H)
    out = pp.sharded_paged_window_attention(
        jnp.array(q), jnp.array(kn), jnp.array(vn), jnp.array(kp),
        jnp.array(vp), jnp.array(tables), jnp.array(pos), n_head=H,
        mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5,
                               rtol=1e-5)
    kq, ks = quantize_rows(jnp.array(kp), "fp8", H, "head")
    vq, vs = quantize_rows(jnp.array(vp), "fp8", H, "head")
    rep = lambda s: np.asarray(jnp.repeat(s, D, -1))  # noqa: E731
    kpf = np.asarray(kq, np.float32) * rep(ks)
    vpf = np.asarray(vq, np.float32) * rep(vs)
    knf = np.asarray(fake_quantize_rows(jnp.array(kn), "fp8", H, "head"))
    vnf = np.asarray(fake_quantize_rows(jnp.array(vn), "fp8", H, "head"))
    ref_q = _window_ref(q, knf, vnf, kpf, vpf, tables, pos, H)
    out_q = pp.sharded_paged_window_attention(
        jnp.array(q), jnp.array(knf), jnp.array(vnf), kq, vq,
        jnp.array(tables), jnp.array(pos), n_head=H, mesh=mesh,
        k_scales=ks, v_scales=vs)
    np.testing.assert_allclose(np.asarray(out_q), ref_q, atol=1e-4,
                               rtol=1e-4)


# ---------------------------------------------------------------------------
# engine greedy parity: mixed windows, verify, sharded — Pallas vs XLA
# ---------------------------------------------------------------------------

def test_mixed_window_kernel_engine_parity(p64, kernel_backend):
    """Mixed prefill+decode windows through the windowed kernel:
    admissions ride mixed dispatches (pool smaller than the request
    set, window > 1), and greedy streams must match the XLA route
    token-for-token."""
    reqs = lambda: [_greedy(f"m{i}", [3 + i, 1, 4, 1, 5 + i][: 3 + i % 3],  # noqa: E731
                            max_new=5) for i in range(5)]
    ecfg = EngineConfig(pool_size=2, max_queue=8, page_size=8,
                        decode_window=4)
    want, _ = _run(p64, ecfg, reqs())
    got, eng = _run(p64, dataclasses.replace(ecfg, paged_kernel=True),
                    reqs())
    assert eng._use_window_kernel
    assert eng.kernel_route.route == "pallas"
    assert eng.kernel_route.window == "pallas"
    assert got == want


def test_verify_kernel_engine_parity(p64, kernel_backend):
    """Speculative verify through the windowed kernel: the drafted
    (k+1)-window scores in-kernel (scatter AFTER attention — the
    write-then-attend equivalence), streams identical to the XLA
    verify on a repetitive greedy trace."""
    from replicatinggpt_tpu.serve.speculative import make_drafter
    reqs = lambda: [_greedy("v0", [5, 6, 5, 6, 5, 6], max_new=8),  # noqa: E731
                    _greedy("v1", [2, 3, 2, 3], max_new=6)]
    ecfg = EngineConfig(pool_size=2, max_queue=4, page_size=8)
    mk = lambda: make_drafter("ngram", 3, 3, ecfg.pool_size)  # noqa: E731
    want, _ = _run(p64, ecfg, reqs(), drafter=mk())
    got, eng = _run(p64, dataclasses.replace(ecfg, paged_kernel=True),
                    reqs(), drafter=mk())
    assert eng._use_window_kernel
    assert got == want


def test_sharded_engine_kernel_greedy_parity(p64, kernel_backend):
    """A 2x2-mesh engine on the Pallas route (shard_map wrapper for
    decode AND windows) streams identically to the unsharded XLA
    engine — the route reads sharded=True, pallas everywhere."""
    reqs = lambda: [_greedy("s0", [3, 1, 4, 1, 5], max_new=6),  # noqa: E731
                    _greedy("s1", [9, 2, 6], max_new=5)]
    want, _ = _run(p64, EngineConfig(pool_size=2, max_queue=4,
                                     page_size=8), reqs())
    got, eng = _run(p64, EngineConfig(pool_size=2, max_queue=4,
                                      page_size=8, paged_kernel=True,
                                      mesh_data=2, mesh_model=2),
                    reqs())
    assert eng.kernel_route.route == "pallas"
    assert eng.kernel_route.sharded
    assert eng.kernel_route.decode == "pallas"   # fused is 1x1-only
    assert got == want


def test_paged_kernel_replay_zero_recompiles(p64, kernel_backend):
    """The unified route holds compile discipline: a replay with
    admissions on the Pallas route recompiles nothing after warmup,
    and the summary/artifact carry the route block + gauge."""
    s = run_replay(p64, CFG,
                   ReplayConfig(n_requests=8, rate=2000.0, seed=0,
                                prompt_len_max=10, max_new_tokens=4,
                                greedy=True),
                   EngineConfig(pool_size=2, max_queue=16, page_size=8,
                                paged_kernel=True, decode_window=2))
    assert s["n_completed"] == 8
    assert s["recompiles_after_warmup"] == 0
    assert s["kernel_route"]["route"] == "pallas"
    assert s["kernel_route"]["reasons"] == []
    assert s["gauges"]["kernel_route_pallas"] == 1.0


# ---------------------------------------------------------------------------
# W8A8 rides along
# ---------------------------------------------------------------------------

def test_w8a8_divergence_and_threading(p64, kernel_backend):
    """--act-quant int8 (W8A8): activation rows quantize per-row into
    the int8 weight matmuls. The engine threads it into ModelConfig
    (a different jit key), the route block reports it, streams
    complete, and the numerics actually move (it is not a no-op)
    while staying inside the int8 divergence budget on the first
    decode logits."""
    from replicatinggpt_tpu.quant import DIVERGENCE_BUDGET
    reqs = lambda: [_greedy("w0", [3, 1, 4, 1, 5], max_new=5),  # noqa: E731
                    _greedy("w1", [9, 2, 6], max_new=4)]
    ecfg = EngineConfig(pool_size=2, max_queue=4, page_size=8,
                        paged_kernel=True, weight_quant="int8",
                        act_quant="int8")
    got, eng = _run(p64, ecfg, reqs())
    assert eng.cfg.act_quant == "int8"     # threaded via replace()
    assert eng.kernel_route.act_quant == "int8"
    assert eng.kernel_route.route == "pallas"
    assert all(len(t) > 0 for t in got.values())
    # teacher-forced divergence of the W8A8 matmuls vs weight-only
    # int8: nonzero (the activation quant is real) and far under the
    # int8 budget at this scale
    from replicatinggpt_tpu.models.gpt import (decode_step_paged,
                                               init_paged_kv_pool)
    from replicatinggpt_tpu.quant.weights import quantize_params
    qp = quantize_params(p64, "int8")
    pool = init_paged_kv_pool(CFG, 8, 8)
    tables = jnp.arange(8, dtype=jnp.int32).reshape(2, 4)
    tok = jnp.array([3, 9], jnp.int32)
    pos = jnp.array([0, 0], jnp.int32)
    active = jnp.array([True, True])
    cfg_w8 = dataclasses.replace(CFG, act_quant="int8")
    lg_a, _ = decode_step_paged(qp, tok, pos, active, tables,
                                dict(pool), cfg_w8)
    lg_w, _ = decode_step_paged(qp, tok, pos, active, tables,
                                dict(pool), CFG)
    div = float(jnp.max(jnp.abs(lg_a - lg_w)))
    assert 0.0 < div < DIVERGENCE_BUDGET["int8"]


def test_act_quant_requires_int8_weights():
    from replicatinggpt_tpu.quant import QuantConfig
    with pytest.raises(ValueError):
        QuantConfig(act_dtype="int8").validate()
    with pytest.raises(ValueError):
        QuantConfig(act_dtype="int8", weight_dtype="fp8").validate()
    QuantConfig(act_dtype="int8", weight_dtype="int8").validate()
