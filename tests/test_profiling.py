"""Profiling subsystem tests (SURVEY.md §5 row 1 — absent in reference;
supplied as jax.profiler traces + blocking step-latency statistics)."""

import glob
import os

import pytest

import jax
import jax.numpy as jnp

from replicatinggpt_tpu.utils.profiling import (StepTimer, annotate, trace,
                                                trace_window)


def test_trace_writes_artifacts(tmp_path):
    logdir = str(tmp_path / "trace")
    f = jax.jit(lambda x: (x * 2.0).sum())
    with trace(logdir):
        with annotate("hot-region"):
            jax.block_until_ready(f(jnp.ones((64, 64))))
    hits = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                     recursive=True)
    assert hits, f"no trace artifacts under {logdir}"


def test_trace_window_covers_requested_steps(tmp_path):
    logdir = str(tmp_path / "win")
    win = trace_window(logdir, start=2, n_steps=2)
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros(8)
    for it in range(6):
        win.step(it)
        assert win._active == (2 <= it < 4)
        x = f(x)
    win.close()
    assert not win._active
    assert glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                     recursive=True)


def test_trace_window_disabled_without_logdir():
    win = trace_window(None, start=0, n_steps=100)
    for it in range(5):
        win.step(it)
        assert not win._active
    win.close()


def test_step_timer_stats():
    t = StepTimer()
    t.start()
    t.laps = [0.1, 0.2, 0.3, 0.4, 1.0]  # inject deterministic laps
    s = t.summary(tokens_per_step=1000, n_chips=2, skip=1)
    assert s["n"] == 4
    assert abs(s["mean_s"] - (0.2 + 0.3 + 0.4 + 1.0) / 4) < 1e-9
    assert s["p50_s"] in (0.3, 0.4)
    assert s["tokens_per_sec_per_chip"] == 1000 / s["p50_s"] / 2


def test_step_timer_laps_block():
    t = StepTimer()
    t.start()
    y = jax.jit(lambda x: x @ x)(jnp.ones((128, 128)))
    dt = t.lap(y)
    assert dt > 0 and len(t.laps) == 1
    assert t.summary()["n"] == 1


@pytest.mark.slow
def test_runner_profile_dir(tmp_path):
    from replicatinggpt_tpu.config import get_config
    from replicatinggpt_tpu.train.runner import train
    from replicatinggpt_tpu.utils.logging import StepLogger
    import dataclasses as dc

    cfg = get_config("test-tiny")
    cfg = cfg.replace(train=dc.replace(cfg.train, max_iters=4,
                                       eval_interval=0, log_interval=0))
    logdir = str(tmp_path / "prof")
    train(cfg, logger=StepLogger(stream=open(os.devnull, "w")),
          profile_dir=logdir, profile_start=1, profile_steps=2)
    assert glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                     recursive=True)


def test_trace_window_close_mid_window(tmp_path):
    """A loop that ends while the window is still open must still get a
    trace from close(): the profiler stops, marks itself done, and a
    late step() can never reopen it (double-start would raise inside
    jax.profiler)."""
    logdir = str(tmp_path / "midwin")
    win = trace_window(logdir, start=0, n_steps=100)
    f = jax.jit(lambda x: x + 1)
    win.step(0)
    assert win._active
    jax.block_until_ready(f(jnp.zeros(8)))
    win.close()                     # loop ended at step 1 of 100
    assert not win._active and win._done
    win.step(1)                     # a straggler call must not reopen
    assert not win._active
    win.close()                     # idempotent
    assert glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                     recursive=True)


def test_trace_window_with_strided_steps(tmp_path):
    # multi-step dispatch loops advance it by K; a window jumped over must
    # still open (and close on the next call), producing a trace
    logdir = str(tmp_path / "stride")
    win = trace_window(logdir, start=10, n_steps=5)
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros(8)
    for it in (0, 25, 50, 75):
        win.step(it)
        assert win._active == (it == 25)
        x = f(x)
    win.close()
    assert glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                     recursive=True)
