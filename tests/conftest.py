"""Test harness config: force an 8-device virtual CPU mesh.

Sharding/collective tests run against CPU XLA with 8 virtual devices
(SURVEY.md §4 implication) — no TPU hardware needed. Env must be set before
jax first imports.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ.setdefault("HF_HUB_OFFLINE", "1")
os.environ.setdefault("TRANSFORMERS_OFFLINE", "1")
os.environ.setdefault("TOKENIZERS_PARALLELISM", "false")

# Some PJRT plugin environments (e.g. tunneled TPU backends) override
# JAX_PLATFORMS at plugin-registration time; the config API wins over both.
import jax

jax.config.update("jax_platforms", "cpu")

import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import numpy as np
import pytest


@pytest.fixture(scope="session")
def corpus_text():
    return (REPO / "datasets" / "shakespeare.txt").read_text()


@pytest.fixture(scope="session")
def tiny_corpus(corpus_text):
    return corpus_text[:50_000]
