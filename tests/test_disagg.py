"""Disaggregated prefill/decode tier tests (serve/disagg.py + the
router's two-tier placement): greedy token identity vs a colocated
fleet for unquantized and int8-KV pools with zero steady-state
recompiles on either tier, the prefix-hot short-circuit, host loss on
the prefill tier mid-transfer and on the decode tier post-transfer
(exactly-once streams, token parity via re-prefill), page_transfer
span validation in the Perfetto trace, and the HTTP front door's
per-client token-bucket rate limiter."""

import asyncio
import importlib.util
import json
import pathlib

import jax
import numpy as np
import pytest

from replicatinggpt_tpu.config import ModelConfig
from replicatinggpt_tpu.faults import Fault, FaultPlan, installed
from replicatinggpt_tpu.faults.fleet import (FLEET_STEP, FLEET_TRANSFER,
                                             KIND_REPLICA_KILL,
                                             KIND_TRANSFER_KILL)
from replicatinggpt_tpu.models.gpt import init_params
from replicatinggpt_tpu.serve import (EngineConfig, Request, Router,
                                      RouterConfig, SamplingParams)
from replicatinggpt_tpu.serve.engine import compile_counts
from replicatinggpt_tpu.serve.http import RateLimitConfig, ServeApp

REPO = pathlib.Path(__file__).resolve().parents[1]

pytestmark = pytest.mark.fleet

CFG = ModelConfig(vocab_size=65, block_size=64, n_layer=2, n_head=2,
                  n_embd=32, dropout=0.0, attn_dropout=0.0,
                  dtype="float32")

#: 20 tokens @ page_size 4 — five flushed pages, so the radix holds 4
#: full pages for prompt[:-1] and the transfer ships a real multi-page
#: payload while the tail re-prefills on the decode tier
PROMPT_LEN = 20


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _long_req(rid, seed=3, max_new=8):
    rng = np.random.default_rng(seed)
    return Request(id=rid,
                   prompt=rng.integers(1, CFG.vocab_size - 1,
                                       (PROMPT_LEN,)).astype(np.int32),
                   max_new_tokens=max_new,
                   sampling=SamplingParams(greedy=True), rng_seed=0)


def _ecfg(**kw):
    return EngineConfig(**{"pool_size": 2, "max_queue": 8,
                           "page_size": 4, **kw})


def _colocated_tokens(params, ecfg, rid="base", seed=3, max_new=8):
    """The baseline arm: the same request through a colocated fleet of
    the same engine config (int8 KV perturbs logits, so parity must be
    measured against the same pool storage, not offline float)."""
    r = Router(params, CFG, RouterConfig(n_replicas=2), ecfg)
    assert r.submit(_long_req(rid, seed, max_new)) is None
    tokens = {res.id: res.tokens for res in r.drain()}[rid]
    r.close()
    return tokens


def _drain_streaming(router, ids):
    results, streams = {}, {i: [] for i in ids}
    while not router.idle:
        for res in router.step():
            results[res.id] = res
        for rid in streams:
            streams[rid].extend(router.take_new_tokens(rid))
    return results, streams


def _trace_check():
    spec = importlib.util.spec_from_file_location(
        "trace_check", REPO / "tools" / "trace_check.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# token identity + transfer counters + zero recompiles
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kv_quant", ["none", "int8"])
def test_disagg_token_identity_and_short_circuit(params, kv_quant):
    """A request through the prefill tier + page transfer + decode tier
    produces the exact greedy stream a colocated fleet produces, for
    the raw and the int8-quantized page pool (the wire format carries
    the quantized page bytes AND the per-row scales); the transfer
    installs through warmed programs (zero compiles during traffic);
    and a second identical prompt short-circuits the prefill tier —
    its pages are already radix-hot on the decode worker."""
    ecfg = _ecfg(kv_quant=kv_quant)
    base = _colocated_tokens(params, ecfg)

    r = Router(params, CFG,
               RouterConfig(n_replicas=2, tiers=("prefill", "decode"),
                            disagg_min_tail=1), ecfg)
    warm = sum(compile_counts().values())
    assert r.submit(_long_req("d1")) is None
    out = {res.id: res for res in r.drain()}
    assert out["d1"].tokens == base
    c = r.metrics.counters
    assert c.get("fleet_disagg_prefills", 0) == 1
    assert c.get("fleet_transfers", 0) == 1
    assert c.get("fleet_transfer_pages", 0) >= 4
    assert c.get("fleet_transfer_bytes", 0) > 0
    assert c.get("fleet_transfer_failures", 0) == 0

    # same prompt again: the decode tier already holds its prefix —
    # no second diversion, no second transfer
    assert r.submit(_long_req("d2")) is None
    out2 = {res.id: res for res in r.drain()}
    assert out2["d2"].tokens == base
    c = r.metrics.counters
    assert c.get("fleet_disagg_shortcircuits", 0) == 1
    assert c.get("fleet_transfers", 0) == 1

    assert sum(compile_counts().values()) == warm
    s = r.fleet_summary()
    assert s["tiers"] == {"prefill": 1, "decode": 1}
    r.close()


# ---------------------------------------------------------------------------
# chaos: tier loss
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_prefill_tier_loss_mid_transfer(params, tmp_path):
    """The prefill worker dies mid-transfer (chunk 0 of the page
    stream): the transfer aborts, the request falls back to a full
    decode-tier prefill through the retry ladder, and the client
    stream is exactly-once and token-identical."""
    ecfg = _ecfg()
    base = _colocated_tokens(params, ecfg)
    with installed(FaultPlan(Fault(site=FLEET_TRANSFER,
                                   kind=KIND_TRANSFER_KILL, at=0,
                                   arg=0))):
        r = Router(params, CFG,
                   RouterConfig(n_replicas=2,
                                tiers=("prefill", "decode"),
                                disagg_min_tail=1,
                                journal_dir=str(tmp_path)), ecfg)
        assert r.submit(_long_req("x")) is None
        results, streams = _drain_streaming(r, ["x"])
        c = dict(r.metrics.counters)
        prefill_alive = r.replicas[0].alive
        r.close()
    assert results["x"].tokens == base
    assert streams["x"] == base
    assert c.get("fleet_transfer_failures", 0) == 1
    assert c.get("fleet_transfer_pages", 0) == 0
    assert not prefill_alive


@pytest.mark.chaos
def test_decode_tier_loss_post_transfer(params, tmp_path):
    """The decode worker holding the transferred pages dies mid-decode
    (after the transfer landed): the journal requeue re-places the
    request from scratch — the pages died with the host, so the prompt
    re-prefills (via the still-alive prefill tier, a second diversion
    + transfer to the surviving decode worker) — token-identical,
    exactly-once stream."""
    ecfg = _ecfg()
    base = _colocated_tokens(params, ecfg, max_new=12)
    with installed(FaultPlan(Fault(site=FLEET_STEP,
                                   kind=KIND_REPLICA_KILL, at=6,
                                   arg=1))):
        r = Router(params, CFG,
                   RouterConfig(n_replicas=3,
                                tiers=("prefill", "decode", "decode"),
                                disagg_min_tail=1,
                                journal_dir=str(tmp_path)), ecfg)
        assert r.submit(_long_req("x", max_new=12)) is None
        results, streams = _drain_streaming(r, ["x"])
        c = dict(r.metrics.counters)
        r.close()
    assert results["x"].tokens == base
    assert streams["x"] == base
    # the first transfer landed on the doomed worker; the requeue
    # re-prefilled via a fresh diversion (so >= 1 transfer, none failed)
    assert c.get("fleet_transfers", 0) >= 1
    assert c.get("fleet_transfer_failures", 0) == 0
    assert c.get("fleet_requeued_requests", 0) >= 1


# ---------------------------------------------------------------------------
# telemetry: page_transfer spans
# ---------------------------------------------------------------------------

def test_page_transfer_span_validates(params, tmp_path):
    """A disaggregated run's trace carries a router-track
    page_transfer X span inside the request's fleet-wide envelope
    hull, and the request's envelope closes exactly once fleet-wide
    (prefill segment migrated, decode segment terminal) — all enforced
    by tools/trace_check.py."""
    from replicatinggpt_tpu.utils.telemetry import Telemetry
    tel = Telemetry()
    r = Router(params, CFG,
               RouterConfig(n_replicas=2, tiers=("prefill", "decode"),
                            disagg_min_tail=1), _ecfg(),
               telemetry=tel)
    assert r.submit(_long_req("t1")) is None
    r.drain()
    r.close()
    out = tmp_path / "disagg_trace.json"
    tel.export_chrome_trace(str(out))
    tel.close()
    doc = json.loads(out.read_text())
    xfer = [e for e in doc["traceEvents"]
            if e.get("ph") == "X" and e.get("name") == "page_transfer"]
    assert len(xfer) == 1
    assert xfer[0]["args"]["request"] == "t1"
    assert xfer[0]["args"]["pages"] >= 4
    assert xfer[0]["args"]["bytes"] > 0
    tc = _trace_check()
    assert tc.check_trace(str(out), min_requests=1) == []


def test_trace_check_flags_bad_transfers(tmp_path):
    """The validator actually rejects: a transfer dangling past the
    terminal envelope close, a transfer for a request with no
    envelope, and a transfer with no preceding migrated (prefill)
    segment."""
    tc = _trace_check()

    def trace(events):
        p = tmp_path / "t.json"
        p.write_text(json.dumps({"traceEvents": events}))
        return str(p)

    meta = {"ph": "M", "name": "thread_name", "pid": 0, "tid": 9,
            "args": {"name": "router"}}

    def envelope(rid, tid, b, e, migrated=False):
        args = {"request": rid}
        return [{"ph": "B", "name": "request", "pid": 0, "tid": tid,
                 "ts": b, "args": dict(args)},
                {"ph": "E", "name": "request", "pid": 0, "tid": tid,
                 "ts": e,
                 "args": {**args, **({"migrated": True}
                                     if migrated else {})}}]

    def xfer(rid, ts, dur):
        return {"ph": "X", "name": "page_transfer", "pid": 0, "tid": 9,
                "ts": ts, "dur": dur, "args": {"request": rid}}

    good = [meta] + envelope("r1", 1, 100.0, 200.0, migrated=True) \
        + envelope("r1", 2, 260.0, 300.0) + [xfer("r1", 210.0, 20.0)]
    assert tc.check_trace(trace(good)) == []

    dangling = [meta] + envelope("r1", 1, 100.0, 200.0, migrated=True) \
        + envelope("r1", 2, 260.0, 300.0) + [xfer("r1", 290.0, 40.0)]
    errs = tc.check_trace(trace(dangling))
    assert any("outside its fleet-wide envelope hull" in e for e in errs)

    orphan = [meta] + envelope("r1", 1, 100.0, 200.0) \
        + [xfer("r2", 110.0, 10.0)]
    errs = tc.check_trace(trace(orphan))
    assert any("no complete envelope" in e for e in errs)

    unmigrated = [meta] + envelope("r1", 1, 100.0, 200.0) \
        + [xfer("r1", 110.0, 10.0)]
    errs = tc.check_trace(trace(unmigrated))
    assert any("no migrated" in e for e in errs)


# ---------------------------------------------------------------------------
# HTTP front door: per-client rate limiting
# ---------------------------------------------------------------------------

async def _post(host, port, path, body, headers=None):
    """One POST; returns (status, response-headers-lowercased, body)."""
    r, w = await asyncio.open_connection(host, port)
    payload = json.dumps(body).encode()
    extra = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
    w.write(f"POST {path} HTTP/1.1\r\nHost: t\r\n{extra}"
            f"Content-Length: {len(payload)}\r\n\r\n".encode() + payload)
    await w.drain()
    data = await r.read()
    w.close()
    await w.wait_closed()
    head, _, rest = data.partition(b"\r\n\r\n")
    lines = head.decode().split("\r\n")
    status = int(lines[0].split(" ")[1])
    hdrs = {}
    for ln in lines[1:]:
        k, _, v = ln.partition(":")
        hdrs[k.strip().lower()] = v.strip()
    return status, hdrs, json.loads(rest)


def test_http_rate_limit_per_client(params):
    """The submit paths meter a token bucket per x-client-id: a client
    past its burst gets 429 with a Retry-After header and a metrics
    increment; other clients (and the anonymous bucket) are
    unaffected; the router never sees the over-rate submit."""
    ecfg = _ecfg()

    async def main():
        router = Router(params, CFG, RouterConfig(n_replicas=1), ecfg)
        app = ServeApp(router,
                       rate_limit=RateLimitConfig(rps=0.001, burst=2.0))
        host, port = await app.start()
        try:
            body = {"prompt": [1, 2], "max_new_tokens": 1,
                    "greedy": True}
            for i in range(2):
                st, _, doc = await _post(
                    host, port, "/v1/submit", {**body, "id": f"a{i}"},
                    {"x-client-id": "tenant-a"})
                assert st == 200, doc
            st, hdrs, doc = await _post(
                host, port, "/v1/submit", {**body, "id": "a2"},
                {"x-client-id": "tenant-a"})
            assert st == 429
            assert doc["error"] == "rate limited"
            assert int(hdrs["retry-after"]) >= 1
            # a different tenant still has its full burst
            st, _, doc = await _post(
                host, port, "/v1/submit", {**body, "id": "b0"},
                {"x-client-id": "tenant-b"})
            assert st == 200, doc
            # no header = the shared anonymous bucket, also fresh
            st, _, doc = await _post(host, port, "/v1/submit",
                                     {**body, "id": "anon0"})
            assert st == 200, doc
            assert router.metrics.counters["http_rate_limited"] == 1
            # the rejected id never reached the router
            assert not router.knows("a2")
        finally:
            await app.stop()

    asyncio.run(main())
