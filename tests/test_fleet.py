"""Fleet-tier tests (serve/router.py + serve/loadgen.py +
faults/fleet.py): prefix-affinity routing, journal requeue across a
replica kill with greedy token parity and exactly-once delivery,
wedge detection + hedged re-route + rejoin, fleet-wide duplicate-id
dedupe, the bounded retry ladder, trace validity through envelope
migration, and the chaos soak (slow tier)."""

import asyncio
import importlib.util
import pathlib
import subprocess
import sys
import threading
import time

import jax
import numpy as np
import pytest

from replicatinggpt_tpu.config import ModelConfig
from replicatinggpt_tpu.faults import Fault, FaultPlan, installed
from replicatinggpt_tpu.faults.fleet import (FLEET_SESSION, FLEET_STEP,
                                             KIND_HOT_KEY_SKEW,
                                             KIND_REPLICA_KILL,
                                             KIND_REPLICA_WEDGE)
from replicatinggpt_tpu.faults.netchaos import (KIND_NET_CORRUPT,
                                                KIND_NET_DELAY,
                                                KIND_NET_DROP,
                                                KIND_NET_DUP,
                                                KIND_NET_PARTITION,
                                                KIND_NET_REORDER,
                                                KIND_NET_TRICKLE,
                                                net_site)
from replicatinggpt_tpu.models.gpt import init_params
from replicatinggpt_tpu.sample import GenerateConfig, generate
from replicatinggpt_tpu.serve import (EngineConfig, REJECT_FLEET_CAPACITY,
                                      Request, Router, RouterConfig,
                                      SamplingParams, SessionLoadConfig,
                                      make_sessions, run_fleet_replay)
from replicatinggpt_tpu.serve.requests import (FINISH_MAX_TOKENS,
                                               REJECT_BAD_REQUEST)

REPO = pathlib.Path(__file__).resolve().parents[1]

CFG = ModelConfig(vocab_size=65, block_size=64, n_layer=2, n_head=2,
                  n_embd=32, dropout=0.0, attn_dropout=0.0,
                  dtype="float32")


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _reqs(n, seed=7, max_new=10):
    rng = np.random.default_rng(seed)
    return [Request(
        id=f"r{i}",
        prompt=rng.integers(1, CFG.vocab_size - 1,
                            (int(rng.integers(2, 12)),)).astype(np.int32),
        max_new_tokens=max_new, sampling=SamplingParams(greedy=True),
        rng_seed=i) for i in range(n)]


def _offline(params, reqs):
    return {r.id: np.asarray(generate(
        params, r.prompt[None, :], CFG,
        GenerateConfig(max_new_tokens=r.max_new_tokens, greedy=True))
    )[0].tolist() for r in reqs}


def _drain_streaming(router, ids):
    """Drain the fleet while consuming the delivery ledger every step;
    returns (results, per-id streamed tokens)."""
    results, streams = {}, {i: [] for i in ids}
    while not router.idle:
        for res in router.step():
            results[res.id] = res
        for rid in streams:
            streams[rid].extend(router.take_new_tokens(rid))
    return results, streams


def _trace_check():
    spec = importlib.util.spec_from_file_location(
        "trace_check", REPO / "tools" / "trace_check.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------

@pytest.mark.fleet
def test_router_parity_across_replicas(params):
    """Greedy output through a 2-replica fleet is token-identical to
    offline generate per request — routing must not change results."""
    reqs = _reqs(6)
    want = _offline(params, reqs)
    r = Router(params, CFG, RouterConfig(n_replicas=2),
               EngineConfig(pool_size=2, max_queue=8))
    for q in reqs:
        assert r.submit(q) is None
    out = {res.id: res for res in r.drain()}
    assert {k: v.tokens for k, v in out.items()} == want
    s = r.fleet_summary()
    # both replicas actually served (least-loaded spread)
    served = [rep["finished"].get("finished_max_tokens", 0)
              for rep in s["replicas"]]
    assert all(n > 0 for n in served), served
    r.close()


@pytest.mark.fleet
def test_duplicate_inflight_id_rejected_fleet_wide(params):
    r = Router(params, CFG, RouterConfig(n_replicas=2),
               EngineConfig(pool_size=1, max_queue=8))
    q = _reqs(1)[0]
    assert r.submit(q) is None
    dup = r.submit(q)
    assert dup is not None and dup.finish_reason == REJECT_BAD_REQUEST
    assert r.metrics.counters["fleet_dedup_rejects"] == 1
    out = r.drain()
    assert [res.id for res in out] == [q.id]     # decoded exactly once
    r.close()


@pytest.mark.fleet
def test_fleet_ttft_includes_same_step_finishers(params):
    """Regression: a request that finishes in the same router step its
    first token commits (max_new_tokens=1) was invisible to the
    fleet_ttft_s histogram — _observe_ttft runs after the per-replica
    loop and only iterates ids still in flight — so the bench TTFT
    p50/p99 silently excluded exactly the fastest requests."""
    reqs = _reqs(3, max_new=1)
    r = Router(params, CFG, RouterConfig(n_replicas=1),
               EngineConfig(pool_size=4, max_queue=8))
    for q in reqs:
        assert r.submit(q) is None
    results = {res.id: res for res in r.drain(max_steps=200)}
    assert all(res.finish_reason == FINISH_MAX_TOKENS
               and len(res.tokens) == 1 for res in results.values())
    assert r.metrics.hist_summary("fleet_ttft_s")["n"] == len(reqs)
    assert all(res.ttft_s > 0 for res in results.values())
    r.close()


@pytest.mark.fleet
def test_prefix_affinity_keeps_fleet_hit_rate(params):
    """The acceptance bar: the 2-replica fleet's aggregate prefix-hit
    rate on session traffic stays within 10% of the single-replica
    baseline (affinity routes each session to the replica owning its
    history), and beats the same fleet with affinity off."""
    lcfg = SessionLoadConfig(n_sessions=8, turns=3, prefix_len=12,
                             n_prefix_groups=2, max_new_tokens=4,
                             user_len_min=2, user_len_max=3, seed=3)
    ecfg = EngineConfig(pool_size=2, max_queue=32, page_size=4)

    def run(n_replicas, affinity):
        s = run_fleet_replay(params, CFG, lcfg,
                             RouterConfig(n_replicas=n_replicas,
                                          affinity=affinity),
                             ecfg, virtual_dt=0.01)
        assert s["n_completed"] == lcfg.n_sessions * lcfg.turns
        return s["aggregate_prefix_hit_rate"]

    single = run(1, True)
    fleet = run(2, True)
    blind = run(2, False)
    assert single > 0.3, single          # the workload is prefix-heavy
    assert fleet >= 0.9 * single, (fleet, single)
    assert fleet >= blind, (fleet, blind)


@pytest.mark.fleet
def test_hot_key_skew_collapses_sessions():
    """The fleet/session chaos seam: with hot_key_skew planned, most
    sessions collapse onto prefix group 0 (deterministically per
    seed)."""
    lcfg = SessionLoadConfig(n_sessions=16, turns=1, n_prefix_groups=4,
                             prefix_len=8, seed=5)
    base = make_sessions(CFG, lcfg)
    with installed(FaultPlan(Fault(site=FLEET_SESSION,
                                   kind=KIND_HOT_KEY_SKEW, at=0,
                                   times=16, arg=1.0))) as plan:
        skewed = make_sessions(CFG, lcfg)
        assert plan.count(FLEET_SESSION) == 16
    assert len({s.group for s in base}) > 1
    assert all(s.group == 0 for s in skewed)


# ---------------------------------------------------------------------------
# replica death: journal requeue, parity, exactly-once delivery
# ---------------------------------------------------------------------------

@pytest.mark.fleet
@pytest.mark.chaos
def test_replica_kill_requeues_with_parity_and_streams(params, tmp_path):
    """THE fleet invariant: replica_kill mid-decode -> every in-flight
    request requeues via the dead replica's journal and completes with
    greedy output token-identical to an uninterrupted run, and the
    router's delivery ledger hands every token exactly once (no drops,
    no duplicates across the migration)."""
    reqs = _reqs(8)
    want = _offline(params, reqs)
    with installed(FaultPlan(Fault(site=FLEET_STEP,
                                   kind=KIND_REPLICA_KILL, at=3,
                                   arg=0))) as plan:
        r = Router(params, CFG,
                   RouterConfig(n_replicas=2,
                                journal_dir=str(tmp_path)),
                   EngineConfig(pool_size=2, max_queue=16))
        for q in reqs:
            assert r.submit(q) is None
        results, streams = _drain_streaming(r, [q.id for q in reqs])
        assert plan.count(FLEET_STEP, KIND_REPLICA_KILL) == 1
    c = r.metrics.counters
    assert c["fleet_replica_kills"] == 1
    assert c["fleet_requeued_requests"] > 0         # work WAS in flight
    assert r.n_alive == 1
    for q in reqs:
        assert results[q.id].finish_reason == FINISH_MAX_TOKENS
        assert results[q.id].tokens == want[q.id], q.id
        assert streams[q.id] == want[q.id], q.id    # exactly-once
    r.close()


@pytest.mark.fleet
@pytest.mark.chaos
def test_duplicate_id_after_kill_never_double_decoded(params, tmp_path):
    """The PR-5 in-flight-id invariant, fleet edition: after a kill
    requeues r onto the surviving replica, a duplicate submit of r
    (a stale client retry racing the recovery) is rejected with
    rejected_bad_request — never decoded twice."""
    reqs = _reqs(4, max_new=12)
    want = _offline(params, reqs)
    with installed(FaultPlan(Fault(site=FLEET_STEP,
                                   kind=KIND_REPLICA_KILL, at=3,
                                   arg=0))):
        r = Router(params, CFG,
                   RouterConfig(n_replicas=2,
                                journal_dir=str(tmp_path)),
                   EngineConfig(pool_size=2, max_queue=16))
        for q in reqs:
            assert r.submit(q) is None
        results = {}
        retried = False
        while not r.idle:
            for res in r.step():
                results[res.id] = res
            if (r.metrics.counters.get("fleet_replica_kills", 0)
                    and not retried):
                retried = True
                for q in reqs:
                    if q.id not in results:
                        dup = r.submit(q)     # the stale client retry
                        assert dup is not None
                        assert (dup.finish_reason
                                == REJECT_BAD_REQUEST), q.id
        assert retried
    # every request decoded exactly once, with parity
    for q in reqs:
        assert results[q.id].tokens == want[q.id]
    assert (r.metrics.counters["fleet_requests_finished"]
            == len(reqs))
    r.close()


@pytest.mark.fleet
@pytest.mark.chaos
def test_kill_with_no_survivors_exhausts_retry_ladder(params, tmp_path):
    """Bounded retry-with-backoff: killing the ONLY replica leaves
    nowhere to requeue — after retry_max backoff attempts each request
    surfaces as rejected_fleet_capacity instead of hanging the fleet.
    The trace still forms one complete span tree per request: the
    router itself emits the terminal envelope close for requests that
    die router-side (their engine segments all ended migrated)."""
    from replicatinggpt_tpu.utils.telemetry import Telemetry
    reqs = _reqs(3, max_new=12)
    tel = Telemetry()
    with installed(FaultPlan(Fault(site=FLEET_STEP,
                                   kind=KIND_REPLICA_KILL, at=2,
                                   arg=0))):
        r = Router(params, CFG,
                   RouterConfig(n_replicas=1, journal_dir=str(tmp_path),
                                retry_max=2, retry_backoff_steps=1),
                   EngineConfig(pool_size=2, max_queue=8),
                   telemetry=tel)
        for q in reqs:
            assert r.submit(q) is None
        results = {res.id: res for res in r.drain(max_steps=200)}
    assert r.n_alive == 0
    assert len(results) == len(reqs)
    assert all(res.finish_reason == REJECT_FLEET_CAPACITY
               for res in results.values())
    assert r.metrics.counters["fleet_requeue_exhausted"] == len(reqs)
    out = tmp_path / "exhausted_trace.json"
    tel.export_chrome_trace(str(out))
    tc = _trace_check()
    assert tc.check_trace(str(out), min_requests=len(reqs)) == []
    r.close()


@pytest.mark.fleet
@pytest.mark.chaos
def test_kill_without_journals_surfaces_cancelled_via_step(params):
    """``journal_dir=None`` is a documented configuration: a kill
    cannot requeue, so the dead replica's in-flight requests terminate
    router-side as cancelled — and those router-recorded results must
    come back from step()/drain() like any engine finish. Regression:
    they used to land only in ``router.results``, so a driver consuming
    step() output (the fleet replay, the SSE driver) waited forever on
    ids that had already terminated."""
    lcfg = SessionLoadConfig(n_sessions=4, turns=2, rate=1000.0,
                             max_new_tokens=4)
    with installed(FaultPlan(Fault(site=FLEET_STEP,
                                   kind=KIND_REPLICA_KILL, at=6,
                                   arg=0))):
        s = run_fleet_replay(params, CFG, lcfg,
                             RouterConfig(n_replicas=2,
                                          journal_dir=None),
                             EngineConfig(pool_size=2, max_queue=16),
                             virtual_dt=0.01, max_steps=2000)
    assert s["n_alive"] == 1
    assert s["router"]["fleet_replica_kills"] == 1
    # every submitted request surfaced a terminal result through the
    # step() return — completed, rejected at submit, or cancelled with
    # the kill; none vanished (the replay would have hit max_steps)
    assert s["turns_finished"] + s["n_rejected"] >= s["n_requests"]


@pytest.mark.fleet
def test_cancel_of_requeued_request_surfaces_from_step(params, tmp_path):
    """Cancelling a request while it sits BETWEEN replicas (in the
    retry-backoff queue after its replica died) records the terminal
    result router-side; the next step() must return it — the
    router-finished ledger, not just the results map."""
    from replicatinggpt_tpu.serve.requests import FINISH_CANCELLED
    reqs = _reqs(2, max_new=12)
    with installed(FaultPlan(Fault(site=FLEET_STEP,
                                   kind=KIND_REPLICA_KILL, at=2,
                                   arg=0))):
        r = Router(params, CFG,
                   RouterConfig(n_replicas=1, journal_dir=str(tmp_path),
                                retry_max=5, retry_backoff_steps=8),
                   EngineConfig(pool_size=2, max_queue=8))
        for q in reqs:
            assert r.submit(q) is None
        for _ in range(4):       # past the kill; work is backing off
            r.step()
        assert r._requeue, "expected requests between replicas"
        target = r._requeue[0].req.id
        assert r.cancel(target)
        assert not r.idle        # the undelivered terminal keeps it live
        surfaced = r.step()
    assert any(res.id == target
               and res.finish_reason == FINISH_CANCELLED
               for res in surfaced)
    assert r.result(target).finish_reason == FINISH_CANCELLED
    r.close()


@pytest.mark.fleet
def test_loadgen_runaway_guard_counts_idle_iterations(params):
    """Regression: the idle branch used to ``continue`` without
    counting, so a stall with pending turns but an idle router spun
    forever instead of raising the promised RuntimeError — max_steps
    now bounds every loop iteration, idle ticks included."""
    # the only session's arrival is ~1/rate seconds out: at rate=1e-4
    # the virtual clock needs millions of idle ticks to reach it — the
    # runaway guard must trip first
    lcfg = SessionLoadConfig(n_sessions=1, turns=1, rate=1e-4,
                             max_new_tokens=2)
    with pytest.raises(RuntimeError, match="did not finish"):
        run_fleet_replay(params, CFG, lcfg,
                         RouterConfig(n_replicas=1, journal_dir=None),
                         EngineConfig(pool_size=2),
                         warmup=False, virtual_dt=0.001, max_steps=50)


@pytest.mark.fleet
@pytest.mark.chaos
def test_stale_journal_ghosts_never_resurrected(params, tmp_path):
    """A journal dir reused across runs holds permanently-unfinished
    entries (requests that migrated off a killed replica finish in the
    SURVIVOR's journal). A later kill must not resurrect those ghosts —
    and above all must not double-decode a live request whose id
    collides with one. The router's in-memory ledger gates the
    replay."""
    import json as jsonmod
    reqs = _reqs(3, max_new=8)
    want = _offline(params, reqs)
    # "previous run" residue in replica0's journal: one id a live
    # request reuses — and (deterministic least-loaded routing) that
    # request lives on replica 1, so resurrecting the stale entry off
    # replica 0's journal would put the id live on two replicas — plus
    # one id nothing reuses
    stale = tmp_path / "replica0.jsonl"
    recs = []
    for rid in (reqs[1].id, "ghost-from-run-1"):
        recs.append({"ev": "submit", "id": rid, "prompt": [1, 2, 3],
                     "max_new_tokens": 8, "rng_seed": 0,
                     "temperature": 1.0, "top_k": 0, "top_p": 0.0,
                     "greedy": True})
    stale.write_text("".join(jsonmod.dumps(x) + "\n" for x in recs))
    with installed(FaultPlan(Fault(site=FLEET_STEP,
                                   kind=KIND_REPLICA_KILL, at=3,
                                   arg=0))):
        r = Router(params, CFG,
                   RouterConfig(n_replicas=2,
                                journal_dir=str(tmp_path)),
                   EngineConfig(pool_size=2, max_queue=16))
        for q in reqs:
            assert r.submit(q) is None
        results = {res.id: res for res in r.drain(max_steps=300)}
    # every live request decoded exactly once, parity intact
    assert sorted(results) == sorted(q.id for q in reqs)
    for q in reqs:
        assert results[q.id].tokens == want[q.id], q.id
    assert "ghost-from-run-1" not in results
    assert (r.metrics.counters["fleet_requests_finished"]
            == len(reqs))
    r.close()


@pytest.mark.fleet
@pytest.mark.chaos
def test_replica_wedge_reroutes_then_rejoins(params, tmp_path):
    """Wedge probe: injected step stalls past the budget quarantine the
    replica, its in-flight work re-routes (hedged: cancelled-with-
    migrated on the suspect, so no id is ever live twice), results stay
    token-identical, and the replica rejoins after quarantine."""
    reqs = _reqs(4, max_new=12)
    want = _offline(params, reqs)
    with installed(FaultPlan(Fault(site=FLEET_STEP,
                                   kind=KIND_REPLICA_WEDGE, at=4,
                                   times=4, arg=0.05, arg2=0))):
        r = Router(params, CFG,
                   RouterConfig(n_replicas=2, journal_dir=str(tmp_path),
                                wedge_budget_s=0.02, wedge_patience=2,
                                quarantine_steps=5),
                   EngineConfig(pool_size=2, max_queue=16))
        for q in reqs:
            assert r.submit(q) is None
        results, streams = _drain_streaming(r, [q.id for q in reqs])
    c = r.metrics.counters
    assert c["fleet_replica_wedges"] >= 1
    assert c["fleet_replica_rejoins"] >= 1
    assert all(rep.alive for rep in r.replicas)      # wedged != dead
    for q in reqs:
        assert results[q.id].tokens == want[q.id]
        assert streams[q.id] == want[q.id]
    r.close()


@pytest.mark.fleet
def test_journal_unfinished_dedupes_reused_ids(tmp_path):
    """An id can legally reappear in one journal (finished, popped by
    the client, then a fresh request reused the id): unfinished() must
    return the reused id exactly ONCE — a duplicate would requeue and
    decode it twice."""
    import json as jsonmod

    from replicatinggpt_tpu.serve import RequestJournal
    p = tmp_path / "j.jsonl"
    sub = {"ev": "submit", "id": "x", "prompt": [1, 2],
           "max_new_tokens": 4, "rng_seed": 0, "temperature": 1.0,
           "top_k": 0, "top_p": 0.0, "greedy": True}
    p.write_text(jsonmod.dumps(sub) + "\n"
                 + jsonmod.dumps({"ev": "finish", "id": "x",
                                  "reason": "max_tokens"}) + "\n"
                 + jsonmod.dumps({**sub, "prompt": [3, 4]}) + "\n")
    out = RequestJournal.unfinished(str(p))
    assert [r.id for r in out] == ["x"]          # exactly once
    assert out[0].prompt.tolist() == [3, 4]      # the LIVE submission


# ---------------------------------------------------------------------------
# telemetry: migrated envelopes + router track
# ---------------------------------------------------------------------------

@pytest.mark.fleet
@pytest.mark.chaos
def test_fleet_trace_validates_through_migration(params, tmp_path):
    """A kill replay's Perfetto trace still forms exactly one complete
    span tree per request id: dead-replica segments close tagged
    'migrated', the terminal envelope lives on the surviving replica,
    and router-track instants are envelope-exempt."""
    out = tmp_path / "fleet_trace.json"
    with installed(FaultPlan(Fault(site=FLEET_STEP,
                                   kind=KIND_REPLICA_KILL, at=6,
                                   arg=0))):
        s = run_fleet_replay(
            params, CFG,
            SessionLoadConfig(n_sessions=5, turns=2, prefix_len=8,
                              max_new_tokens=5, user_len_max=3, seed=2),
            RouterConfig(n_replicas=2, journal_dir=str(tmp_path)),
            EngineConfig(pool_size=2, max_queue=16, page_size=4),
            virtual_dt=0.01, trace_out=str(out))
    assert s["n_completed"] == s["n_requests"] == 10
    assert s["router"]["fleet_requeued_requests"] > 0
    tc = _trace_check()
    assert tc.check_trace(str(out), min_requests=10) == []
    # CLI contract too (stdlib-only invocation)
    rc = subprocess.run([sys.executable,
                         str(REPO / "tools" / "trace_check.py"),
                         str(out), "--min-requests", "10"],
                        capture_output=True, text=True)
    assert rc.returncode == 0, rc.stderr


@pytest.mark.fleet
@pytest.mark.chaos
def test_cancel_then_kill_emits_one_terminal_envelope(params, tmp_path):
    """The cancel-then-kill race: a client cancels an active request
    (the engine closes its envelope terminally and journals the
    finish; the result sits in engine._pending), then the replica dies
    before its next step. The router's journaled-finish path must NOT
    close the envelope a second time — exactly one terminal segment
    per id (regression: trace_check flagged 2)."""
    from replicatinggpt_tpu.serve.requests import FINISH_CANCELLED
    from replicatinggpt_tpu.utils.telemetry import Telemetry
    reqs = _reqs(4, max_new=12)
    tel = Telemetry()
    r = Router(params, CFG,
               RouterConfig(n_replicas=2, journal_dir=str(tmp_path)),
               EngineConfig(pool_size=2, max_queue=8), telemetry=tel)
    for q in reqs:
        assert r.submit(q) is None
    for _ in range(3):             # admit + decode a few tokens
        r.step()
    victim = next(rid for rid, fi in r._inflight.items()
                  if r.replicas[fi.replica].engine.pool.slot_of(rid)
                  is not None)
    victim_replica = r._inflight[victim].replica
    assert r.cancel(victim)        # envelope closed + finish journaled;
    #                                the result dies undelivered with:
    with installed(FaultPlan(Fault(site=FLEET_STEP,
                                   kind=KIND_REPLICA_KILL,
                                   at=r.n_steps,
                                   arg=victim_replica))):
        results = {res.id: res for res in r.drain(max_steps=300)}
    assert results[victim].finish_reason == FINISH_CANCELLED
    assert results[victim].tokens == []     # lost with the process
    out = tmp_path / "cancel_kill_trace.json"
    tel.export_chrome_trace(str(out))
    tc = _trace_check()
    assert tc.check_trace(str(out)) == []
    r.close()


@pytest.mark.fleet
def test_jsonl_sink_trace_assembles_and_validates(params, tmp_path):
    """The crash-tolerant sink path: a fleet trace assembled OFFLINE
    from the JSONL event sink (chrome_trace_from_jsonl — the artifact
    of a run that died mid-flight) must carry the router track's
    thread_name metadata, or trace_check treats router instants as
    ordinary tagged events and fails a valid trace (regression)."""
    from replicatinggpt_tpu.utils.telemetry import (
        Telemetry, chrome_trace_from_jsonl)
    sink = tmp_path / "events.jsonl"
    tel = Telemetry(jsonl_path=str(sink))
    reqs = _reqs(4, max_new=6)
    with installed(FaultPlan(Fault(site=FLEET_STEP,
                                   kind=KIND_REPLICA_KILL, at=3,
                                   arg=0))):
        r = Router(params, CFG,
                   RouterConfig(n_replicas=2, journal_dir=str(tmp_path)),
                   EngineConfig(pool_size=2, max_queue=8), telemetry=tel)
        for q in reqs:
            assert r.submit(q) is None
        r.drain(max_steps=300)
    tel.close()
    r.close()
    out = tmp_path / "assembled.json"
    n = chrome_trace_from_jsonl(str(sink), str(out))
    assert n > 0
    tc = _trace_check()
    assert tc.check_trace(str(out), min_requests=len(reqs)) == []


@pytest.mark.fleet
def test_deterministic_rejects_skip_route_fallback(params):
    """prompt_too_long / dead-on-arrival deadline are the same verdict
    on every replica — the router must not try the others (and must not
    count the identical rejections as routing fallbacks, a capacity-
    pressure signal)."""
    r = Router(params, CFG, RouterConfig(n_replicas=3, journal_dir=None),
               EngineConfig(pool_size=2, max_queue=8))
    too_long = Request(
        id="huge",
        prompt=np.ones((CFG.block_size + 8,), np.int32),
        max_new_tokens=4, sampling=SamplingParams(greedy=True))
    rej = r.submit(too_long)
    assert rej is not None and "too_long" in rej.finish_reason
    assert r.metrics.counters.get("fleet_route_fallbacks", 0) == 0
    r.close()


@pytest.mark.fleet
def test_trace_check_rejects_double_terminal_and_unclosed(tmp_path):
    """Adversarial traces: two unmigrated envelope closes for one id,
    or a migrated segment never followed by a terminal one, must fail
    validation."""
    tc = _trace_check()
    import json

    def write(events, name):
        p = tmp_path / name
        p.write_text(json.dumps({"traceEvents": events}))
        return str(p)

    env = lambda ph, tid, ts, **a: {  # noqa: E731
        "ph": ph, "name": "request", "pid": 0, "tid": tid, "ts": ts,
        "args": {"request": "r0", **a}}
    # two terminal segments
    p = write([env("B", 1, 0), env("E", 1, 10),
               env("B", 101, 20), env("E", 101, 30)], "double.json")
    assert any("terminal" in e for e in tc.check_trace(p))
    # migrated segment with no terminal close at all
    p = write([env("B", 1, 0), env("E", 1, 10, migrated=True)],
              "no_terminal.json")
    assert any("terminal" in e for e in tc.check_trace(p))
    # the valid migration shape passes
    p = write([env("B", 1, 0), env("E", 1, 10, migrated=True),
               env("B", 101, 20), env("E", 101, 30)], "ok.json")
    assert tc.check_trace(p, min_requests=1) == []
    # router-track instants are envelope-exempt (by thread name)
    p = write([{"ph": "M", "name": "thread_name", "pid": 0, "tid": 9000,
                "args": {"name": "router"}},
               {"ph": "i", "name": "route", "pid": 0, "tid": 9000,
                "ts": 5, "s": "t", "args": {"request": "r0"}},
               env("B", 1, 10), env("E", 1, 20)], "router_ok.json")
    assert tc.check_trace(p, min_requests=1) == []


# ---------------------------------------------------------------------------
# the chaos soak (slow tier): loadgen + kill + wedge, everything holds
# ---------------------------------------------------------------------------

@pytest.mark.fleet
@pytest.mark.chaos
@pytest.mark.slow
def test_fleet_chaos_soak(params, tmp_path):
    """The full acceptance scenario in one run: multi-turn session
    traffic over 3 replicas with a replica kill AND a wedge injected
    mid-soak — every turn completes, every delivered stream equals the
    final token list (exactly-once across migrations), the aggregate
    prefix-hit rate stays within 10% of the single-replica baseline on
    the same workload, the trace validates, and steady state stays
    zero-recompile."""
    lcfg = SessionLoadConfig(n_sessions=16, turns=3, prefix_len=12,
                             n_prefix_groups=3, max_new_tokens=4,
                             user_len_min=2, user_len_max=3, seed=11)
    ecfg = EngineConfig(pool_size=2, max_queue=64, page_size=4)
    baseline = run_fleet_replay(params, CFG, lcfg,
                                RouterConfig(n_replicas=1), ecfg,
                                virtual_dt=0.01)
    assert baseline["n_completed"] == lcfg.n_sessions * lcfg.turns

    out = tmp_path / "soak_trace.json"
    with installed(FaultPlan(
            Fault(site=FLEET_STEP, kind=KIND_REPLICA_KILL, at=20, arg=0),
            Fault(site=FLEET_STEP, kind=KIND_REPLICA_WEDGE, at=40,
                  times=4, arg=0.05, arg2=1))) as plan:
        s = run_fleet_replay(
            params, CFG, lcfg,
            RouterConfig(n_replicas=3, journal_dir=str(tmp_path),
                         wedge_budget_s=0.02, wedge_patience=2,
                         quarantine_steps=6),
            ecfg, virtual_dt=0.01, collect_streams=True,
            trace_out=str(out))
        assert plan.count(FLEET_STEP, KIND_REPLICA_KILL) == 1
        assert plan.count(FLEET_STEP, KIND_REPLICA_WEDGE) >= 1
    n_turns = lcfg.n_sessions * lcfg.turns
    assert s["n_completed"] == s["n_requests"] == n_turns
    assert s["router"]["fleet_replica_kills"] == 1
    assert s["router"]["fleet_requeued_requests"] > 0
    assert s["n_alive"] == 2
    # exactly-once delivery through every migration
    for rid, res in s["results"].items():
        assert s["streams"][rid] == res.tokens, rid
    # fleet affinity holds under chaos: within 10% of single-replica
    assert (s["aggregate_prefix_hit_rate"]
            >= 0.9 * baseline["aggregate_prefix_hit_rate"]), (
        s["aggregate_prefix_hit_rate"],
        baseline["aggregate_prefix_hit_rate"])
    assert s["recompiles_after_warmup"] == 0
    tc = _trace_check()
    assert tc.check_trace(str(out), min_requests=n_turns) == []


@pytest.mark.fleet
@pytest.mark.slow
def test_bench_fleet_mode_emits_artifact(tmp_path, capsys, monkeypatch):
    """bench.py --mode fleet end to end (in-process): the artifact
    carries per-replica occupancy, requeue counts, and the fleet TTFT
    distribution — the acceptance criteria's dashboard keys."""
    import json
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)
    args = bench.main.__globals__["argparse"].Namespace(
        preset="test-tiny", serve_pool=2, serve_rate=200.0,
        serve_max_new_tokens=6, serve_page_size=4, serve_n_pages=0,
        fleet_replicas=2, fleet_sessions=5, fleet_turns=2,
        fleet_prefix_groups=2, fleet_prefix_len=8, fleet_kill_at=6,
        fleet_journal_dir=str(tmp_path), trace_out=None,
        metrics_timeline=None, metrics_out=None, multiproc=False,
        fleet_load_step=False, fleet_host_loss=False, net_chaos=False)
    bench.bench_fleet(args)
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("{")]
    assert lines, "bench_fleet emitted no artifact JSON"
    doc = json.loads(lines[-1])
    assert doc["metric"] == "fleet_replay_aggregate_tokens_per_sec"
    assert doc["value"] > 0
    assert doc["chaos"] == "replica_kill"
    assert doc["n_completed"] == doc["n_requests"]
    assert doc["router"]["fleet_replica_kills"] == 1
    assert len(doc["replicas"]) == 2
    for rep in doc["replicas"]:
        assert {"occupancy_mean", "pages_in_use",
                "prefix_hit_rate"} <= set(rep)
    assert "fleet_ttft_p50_ms" in doc and "fleet_ttft_p99_ms" in doc


# ---------------------------------------------------------------------------
# the wire fleet: real sockets between router and in-process workers —
# netchaos faults land on genuine checksummed frames
# ---------------------------------------------------------------------------

# five flushed pages at page_size 4 — long enough that disagg prefill
# hands off real multi-page transfers for the chaos plan to hurt
WIRE_PROMPT_LEN = 20


def _long_reqs(n, seed=29, max_new=8):
    rng = np.random.default_rng(seed)
    return [Request(
        id=f"L{i}",
        prompt=rng.integers(1, CFG.vocab_size - 1,
                            (WIRE_PROMPT_LEN,)).astype(np.int32),
        max_new_tokens=max_new, sampling=SamplingParams(greedy=True),
        rng_seed=100 + i) for i in range(n)]


class _WireFleet:
    """N real WorkerServers (real engines, this process), each behind a
    real TCP socket on a shared daemon asyncio thread: the router talks
    to them through the genuine RPC wire — framing, checksums, reply
    caches, generation fences — so netchaos faults hit actual frames.
    The closest in-process analogue of a multi-host fleet, minus the
    subprocess spawn cost of the multiproc tier."""

    def __init__(self, params, n, ecfg=None, gens=None):
        from replicatinggpt_tpu.serve.engine import Engine
        from replicatinggpt_tpu.serve.worker import WorkerServer
        ecfg = ecfg or EngineConfig(pool_size=2, max_queue=16,
                                    page_size=4)
        self.workers = []
        for i in range(n):
            w = WorkerServer(Engine(params, CFG, ecfg), journal=None)
            if gens is not None:
                w.gen = gens[i]
            self.workers.append(w)
        self.ports = []
        self._ready = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        assert self._ready.wait(30), "wire fleet never started listening"

    def _serve(self):
        from replicatinggpt_tpu.serve.rpc import serve_connection

        async def main():
            self._loop = asyncio.get_running_loop()
            self._stop = asyncio.Event()
            servers = []
            for w in self.workers:
                s = await asyncio.start_server(
                    lambda r, wr, w=w: serve_connection(
                        r, wr, w.dispatch),
                    "127.0.0.1", 0)
                servers.append(s)
                self.ports.append(s.sockets[0].getsockname()[1])
            self._ready.set()
            await self._stop.wait()
            for s in servers:
                s.close()
                await s.wait_closed()

        asyncio.run(main())

    def router(self, rcfg, tiers=None, page_size=0):
        from replicatinggpt_tpu.serve.router import RemoteReplica
        backends = []
        for i, port in enumerate(self.ports):
            rep = RemoteReplica(i, None,
                                rpc_timeout_s=rcfg.step_timeout_s,
                                step_timeout_s=rcfg.step_timeout_s)
            rep.connect(port, gen=(self.workers[i].gen
                                   if self.workers[i].gen >= 0
                                   else None))
            if tiers is not None:
                rep.tier = tiers[i]
            if page_size:
                rep.page_size = page_size
            backends.append(rep)
        return Router(rcfg=rcfg, backends=backends)

    def close(self):
        self._loop.call_soon_threadsafe(self._stop.set)
        self._thread.join(10)


def _drive_wire(r, fleet, ids, budget_s=240.0, on_down=None):
    """Step the wire fleet to idle, consuming the delivery ledger every
    step. Finishes are collected as a LIST (duplicates must show up,
    not be collapsed — exactly-once is the thing under test). A replica
    the router marked down is re-attached to its still-running worker;
    ``on_down(rep)`` supplies extra attach kwargs (e.g. the new gen)."""
    deadline = time.monotonic() + budget_s
    emitted, streams = [], {i: [] for i in ids}
    while not r.idle:
        assert time.monotonic() < deadline, (
            f"wire drain stuck; recent events: {r.events[-8:]}")
        emitted.extend(r.step())
        for rid in streams:
            streams[rid].extend(r.take_new_tokens(rid))
        for rep in r.replicas:
            if not rep.alive:
                extra = on_down(rep) if on_down else {}
                r.attach_replica(rep.idx, fleet.ports[rep.idx], **extra)
    return emitted, streams


@pytest.mark.fleet
def test_wire_fleet_clean_run_parity(params):
    """Protocol hardening must cost nothing on a clean wire: with no
    FaultPlan installed the FaultyTransport-wrapped path is a straight
    delegate — greedy parity and exactly-once hold, no chaos counter
    moves, and the per-verb fault ordinals are never even counted
    (proof the fast path really is untouched)."""
    fleet = _WireFleet(params, 2)
    try:
        reqs = _reqs(6, max_new=8)
        want = _offline(params, reqs)
        r = fleet.router(RouterConfig(n_replicas=2, journal_dir=None,
                                      step_timeout_s=5.0))
        for q in reqs:
            assert r.submit(q) is None
        emitted, streams = _drive_wire(r, fleet, [q.id for q in reqs])
        ids = [res.id for res in emitted]
        assert sorted(ids) == sorted(q.id for q in reqs)
        for res in emitted:
            assert res.tokens == want[res.id], res.id
            assert streams[res.id] == want[res.id], res.id
        c = r.metrics.counters
        assert c.get("rpc_dup_suppressed", 0) == 0
        assert c.get("rpc_corrupt_frames", 0) == 0
        assert c.get("rpc_partitions_active", 0) == 0
        for rep in r.replicas:
            assert rep.client.dups_injected == 0
            assert rep.client._counts == {}
        r.close()
    finally:
        fleet.close()


@pytest.mark.fleet
@pytest.mark.chaos
@pytest.mark.slow
def test_netchaos_soak_exactly_once(params):
    """The tentpole soak: the full wire-fault ladder — duplicated and
    reordered submits, a corrupt request frame, dropped and delayed
    steps, a one-way partition mid-decode, duplicated / trickled /
    reordered page-transfer frames mid-handoff on a disaggregated
    fleet — and the greedy token streams stay byte-identical to the
    clean offline run, every id finishes exactly once, and suppressed
    duplicates exactly equal injected duplicates."""
    fleet = _WireFleet(params, 3)
    try:
        reqs = _reqs(6) + _long_reqs(2)
        want = _offline(params, reqs)
        r = fleet.router(
            RouterConfig(n_replicas=3, journal_dir=None,
                         step_timeout_s=5.0,
                         tiers=("prefill", "decode", "decode"),
                         disagg_min_tail=1),
            tiers=("prefill", "decode", "decode"), page_size=4)
        plan = FaultPlan(
            Fault(site=net_site("router", "worker1", "submit"),
                  kind=KIND_NET_DUP, at=0, times=2),
            Fault(site=net_site("router", "worker2", "submit"),
                  kind=KIND_NET_CORRUPT, at=0),
            Fault(site=net_site("router", "worker1", "step"),
                  kind=KIND_NET_DROP, at=4),
            Fault(site=net_site("router", "worker2", "step"),
                  kind=KIND_NET_PARTITION, at=6, times=3, arg2=1),
            Fault(site=net_site("router", "worker1", "step"),
                  kind=KIND_NET_DELAY, at=8, arg=0.01),
            Fault(site=net_site("router", "worker0", "page_transfer"),
                  kind=KIND_NET_DUP, at=1, times=2),
            Fault(site=net_site("router", "worker0", "page_transfer"),
                  kind=KIND_NET_TRICKLE, at=4, arg=5, arg2=0.001),
            Fault(site=net_site("router", "worker1", "page_transfer"),
                  kind=KIND_NET_REORDER, at=2),
            Fault(site=net_site("router", "worker2", "page_transfer"),
                  kind=KIND_NET_REORDER, at=2),
        )
        with installed(plan):
            for q in reqs:
                assert r.submit(q) is None
            emitted, streams = _drive_wire(
                r, fleet, [q.id for q in reqs], budget_s=300.0)
        # nothing in the ladder is fatal: every replica must have
        # survived on its ORIGINAL transport — the dup-accounting
        # equality below is only meaningful over un-replaced clients
        assert all(rep.alive for rep in r.replicas)
        ids = [res.id for res in emitted]
        assert sorted(ids) == sorted(q.id for q in reqs), (
            "double/missing finish: %r" % ids)
        for res in emitted:
            assert res.tokens == want[res.id], res.id
            assert streams[res.id] == want[res.id], res.id
        c = r.metrics.counters
        injected = sum(rep.client.dups_injected for rep in r.replicas)
        assert injected >= 3
        assert c.get("rpc_dup_suppressed", 0) == injected
        assert c.get("rpc_corrupt_frames", 0) == 1
        assert c.get("rpc_partitions_active", 0) == 1
        assert c.get("fleet_disagg_prefills", 0) >= 1
        assert c.get("fleet_transfers", 0) >= 1
        r.close()
    finally:
        fleet.close()


@pytest.mark.fleet
@pytest.mark.chaos
def test_netchaos_two_way_partition_mark_down_and_reattach(params):
    """A two-way partition mid-decode: the step RPC dies as RpcDown,
    the router marks the replica down KEEPING its in-flight ledger, and
    re-attaching to the (still running, state intact) worker resumes
    the kept requests — token parity and exactly-once hold across the
    down/attach cycle."""
    fleet = _WireFleet(params, 2)
    try:
        reqs = _reqs(4)
        want = _offline(params, reqs)
        r = fleet.router(RouterConfig(n_replicas=2, journal_dir=None,
                                      step_timeout_s=5.0))
        plan = FaultPlan(
            Fault(site=net_site("router", "worker1", "step"),
                  kind=KIND_NET_PARTITION, at=2, times=1, arg2=0))
        with installed(plan):
            for q in reqs:
                assert r.submit(q) is None
            emitted, streams = _drive_wire(r, fleet,
                                           [q.id for q in reqs])
        ids = [res.id for res in emitted]
        assert sorted(ids) == sorted(q.id for q in reqs)
        for res in emitted:
            assert res.tokens == want[res.id], res.id
            assert streams[res.id] == want[res.id], res.id
        c = r.metrics.counters
        assert c.get("rpc_partitions_active", 0) == 1
        assert c.get("fleet_replica_downs", 0) >= 1
        assert c.get("fleet_replica_attaches", 0) >= 1
        assert any("attached" in e for e in r.events)
        r.close()
    finally:
        fleet.close()


@pytest.mark.fleet
def test_heartbeat_deadline_forces_reconnect(params):
    """Half-open detection: once no RPC has round-tripped within the
    heartbeat deadline the router closes the socket so the next call
    reconnects from scratch. With the deadline forced to zero EVERY
    step blows it — decode must still finish with parity through the
    constant reconnect churn (nothing rides on connection identity)."""
    fleet = _WireFleet(params, 1)
    try:
        reqs = _reqs(2, max_new=8)
        want = _offline(params, reqs)
        r = fleet.router(RouterConfig(n_replicas=1, journal_dir=None,
                                      step_timeout_s=5.0))
        rep = r.replicas[0]
        assert rep.heartbeat_deadline_s == pytest.approx(15.0)
        for q in reqs:
            assert r.submit(q) is None
        rep.heartbeat_deadline_s = 0.0
        emitted, streams = _drive_wire(r, fleet, [q.id for q in reqs])
        ids = [res.id for res in emitted]
        assert sorted(ids) == sorted(q.id for q in reqs)
        for res in emitted:
            assert res.tokens == want[res.id], res.id
            assert streams[res.id] == want[res.id], res.id
        assert any("heartbeat deadline blown" in e for e in r.events)
        r.close()
    finally:
        fleet.close()


@pytest.mark.fleet
@pytest.mark.chaos
def test_stale_generation_fenced_then_reattach(params):
    """Generation fencing over the real wire: the worker is replaced by
    a newer incarnation the router never heard about (supervisor
    restart during a partition). Frames stamped with the old gen must
    be REJECTED by the fence — a typed protocol error, never a quiet
    wrong-incarnation mutation — and re-attaching at the new gen
    resumes to full parity."""
    fleet = _WireFleet(params, 1, gens=[0])
    try:
        reqs = _reqs(3)
        want = _offline(params, reqs)
        r = fleet.router(RouterConfig(n_replicas=1, journal_dir=None,
                                      step_timeout_s=5.0))
        assert r.replicas[0].gen == 0
        for q in reqs:
            assert r.submit(q) is None
        for _ in range(2):
            r.step()
        # the worker's incarnation moves on without the router knowing
        fleet.workers[0].gen = 4
        emitted, streams = _drive_wire(
            r, fleet, [q.id for q in reqs],
            on_down=lambda rep: {"gen": 4})
        ids = [res.id for res in emitted]
        assert sorted(ids) == sorted(q.id for q in reqs)
        for res in emitted:
            assert res.tokens == want[res.id], res.id
            assert streams[res.id] == want[res.id], res.id
        c = r.metrics.counters
        assert c.get("rpc_stale_generation_rejects", 0) >= 1
        assert c.get("fleet_replica_downs", 0) >= 1
        assert c.get("fleet_replica_attaches", 0) >= 1
        assert any("attached" in e for e in r.events)
        r.close()
    finally:
        fleet.close()
