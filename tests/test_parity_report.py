"""Smoke test for the parity-report tool (SURVEY.md §7 item 7)."""

import os

import pytest


@pytest.mark.slow
def test_parity_report_runs(tmp_path):
    from replicatinggpt_tpu.parity_report import main
    out = str(tmp_path / "report.md")
    assert main(["--out", out, "--steps", "4", "--platform", ""]) == 0
    text = open(out).read()
    assert "Forward / gradient parity" in text
    assert "Training-curve parity" in text
    assert "deviations" in text
