"""CLI tests: the L6 surface (SURVEY.md §1 — the reference's 'CLI' is
running a script that trains at import time; here every pipeline is a
subcommand). Mirrors the verify-skill recipe as regression tests."""

import dataclasses
import io
import json
import os
from contextlib import redirect_stdout

import pytest

from replicatinggpt_tpu.cli import main


@pytest.fixture(scope="module")
def ckdir(tmp_path_factory):
    """A trained tiny checkpoint + its log, shared across the module."""
    d = tmp_path_factory.mktemp("cli")
    ck = str(d / "ck")
    log = str(d / "log.jsonl")
    rc = main(["train", "--preset", "test-tiny",
               "--dataset", "datasets/shakespeare.txt",
               "--max-iters", "30", "--eval-interval", "15",
               "--eval-iters", "2", "--checkpoint-dir", ck,
               "--log-jsonl", log])
    assert rc == 0
    return ck, log


@pytest.mark.slow
def test_train_writes_checkpoint_and_jsonl(ckdir):
    ck, log = ckdir
    assert os.path.isdir(os.path.join(ck, "30"))
    events = [json.loads(l) for l in open(log)]
    kinds = {e["event"] for e in events}
    assert {"eval", "step"} <= kinds
    evals = [e for e in events if e["event"] == "eval"]
    assert evals[0]["val_loss"] > evals[-1]["val_loss"]


def test_eval_from_checkpoint(ckdir, capsys):
    ck, _ = ckdir
    rc = main(["eval", "--preset", "test-tiny",
               "--dataset", "datasets/shakespeare.txt",
               "--eval-iters", "2", "--checkpoint-dir", ck])
    assert rc == 0
    out = capsys.readouterr().out
    # reference line format (GPT1.py:225) with a trained (not ln65) loss
    assert "train loss" in out and "val loss = " in out
    val = float(out.rsplit("= ", 1)[1])
    assert val < 4.0


def test_generate_from_checkpoint(ckdir, capsys):
    ck, _ = ckdir
    rc = main(["generate", "--preset", "test-tiny",
               "--dataset", "datasets/shakespeare.txt",
               "--checkpoint-dir", ck, "--prompt", "ROMEO:",
               "--sample-tokens", "40", "--top-k", "20"])
    assert rc == 0
    out = capsys.readouterr().out
    assert len(out.strip()) >= 40  # 40 chars sampled (char tokenizer)


def test_unknown_preset_rejected():
    with pytest.raises(SystemExit):
        main(["train", "--preset", "nope"])


def test_config_overrides_applied(capsys):
    # overrides reach the model: 1-layer run logs a 1L param count line
    rc = main(["eval", "--preset", "test-tiny",
               "--dataset", "datasets/shakespeare.txt",
               "--n_layer", "1", "--eval-iters", "1"])
    assert rc == 0
