"""CLI tests: the L6 surface (SURVEY.md §1 — the reference's 'CLI' is
running a script that trains at import time; here every pipeline is a
subcommand). Mirrors the verify-skill recipe as regression tests."""

import dataclasses
import io
import json
import os
from contextlib import redirect_stdout

import pytest

from replicatinggpt_tpu.cli import main


@pytest.fixture(scope="module")
def ckdir(tmp_path_factory):
    """A trained tiny checkpoint + its log, shared across the module."""
    d = tmp_path_factory.mktemp("cli")
    ck = str(d / "ck")
    log = str(d / "log.jsonl")
    rc = main(["train", "--preset", "test-tiny",
               "--dataset", "datasets/shakespeare.txt",
               "--max-iters", "30", "--eval-interval", "15",
               "--eval-iters", "2", "--checkpoint-dir", ck,
               "--log-jsonl", log])
    assert rc == 0
    return ck, log


@pytest.mark.slow
def test_train_writes_checkpoint_and_jsonl(ckdir):
    ck, log = ckdir
    assert os.path.isdir(os.path.join(ck, "30"))
    events = [json.loads(l) for l in open(log)]
    kinds = {e["event"] for e in events}
    assert {"eval", "step"} <= kinds
    evals = [e for e in events if e["event"] == "eval"]
    assert evals[0]["val_loss"] > evals[-1]["val_loss"]


def test_eval_from_checkpoint(ckdir, capsys):
    ck, _ = ckdir
    rc = main(["eval", "--preset", "test-tiny",
               "--dataset", "datasets/shakespeare.txt",
               "--eval-iters", "2", "--checkpoint-dir", ck])
    assert rc == 0
    out = capsys.readouterr().out
    # reference line format (GPT1.py:225) with a trained (not ln65) loss
    assert "train loss" in out and "val loss = " in out
    val = float(out.rsplit("= ", 1)[1])
    assert val < 4.0


def test_generate_from_checkpoint(ckdir, capsys):
    ck, _ = ckdir
    rc = main(["generate", "--preset", "test-tiny",
               "--dataset", "datasets/shakespeare.txt",
               "--checkpoint-dir", ck, "--prompt", "ROMEO:",
               "--sample-tokens", "40", "--top-k", "20"])
    assert rc == 0
    out = capsys.readouterr().out
    assert len(out.strip()) >= 40  # 40 chars sampled (char tokenizer)


def test_export_torch_round_trip(ckdir, tmp_path, capsys):
    """train -> export-torch -> torch.load into RefGPT: the state_dict
    reproduces the checkpointed params exactly, and RefGPT's logits on a
    real batch match the framework forward (the reference's artifact is
    exactly this file, GPT1.py:239-241)."""
    import jax
    import numpy as np
    import torch

    from replicatinggpt_tpu.config import get_config
    from replicatinggpt_tpu.models.gpt import forward
    from replicatinggpt_tpu.reference_torch import RefGPT, torch_to_params
    from replicatinggpt_tpu.train.checkpoint import CheckpointManager
    from replicatinggpt_tpu.train.runner import _resolve_vocab
    from replicatinggpt_tpu.train.state import create_train_state
    from replicatinggpt_tpu.tokenizers import get_tokenizer

    ck, _ = ckdir
    out = str(tmp_path / "model.pth")
    rc = main(["export-torch", "--preset", "test-tiny",
               "--dataset", "datasets/shakespeare.txt",
               "--checkpoint-dir", ck, "--out", out])
    assert rc == 0
    assert "exported" in capsys.readouterr().out

    cfg = get_config("test-tiny")
    text = open("datasets/shakespeare.txt").read()
    cfg = _resolve_vocab(cfg, get_tokenizer(cfg.tokenizer,
                                            corpus_text=text))
    model = RefGPT(cfg.model)
    model.load_state_dict(torch.load(out))
    model.eval()

    # the exported tensors ARE the checkpointed params (float32 copies)
    state = create_train_state(jax.random.PRNGKey(cfg.train.seed),
                               cfg.model, cfg.train)
    state = CheckpointManager(ck).restore_latest(state)
    back = torch_to_params(model)
    np.testing.assert_array_equal(
        back["wte"], np.asarray(state.params["wte"], np.float32))
    np.testing.assert_array_equal(
        back["blocks"]["qkv_kernel"],
        np.asarray(state.params["blocks"]["qkv_kernel"], np.float32))

    # and the torch model computes the same function
    x = np.array([[1, 5, 9, 2, 0, 3, 7, 4]], np.int32)
    jl, _ = forward(state.params, jax.numpy.asarray(x), cfg.model)
    with torch.no_grad():
        tl, _ = model(torch.from_numpy(x).long())
    np.testing.assert_allclose(np.asarray(jl, np.float32), tl.numpy(),
                               atol=1e-5, rtol=1e-5)


def test_unknown_preset_rejected():
    with pytest.raises(SystemExit):
        main(["train", "--preset", "nope"])


def test_config_overrides_applied(capsys):
    # overrides reach the model: 1-layer run logs a 1L param count line
    rc = main(["eval", "--preset", "test-tiny",
               "--dataset", "datasets/shakespeare.txt",
               "--n_layer", "1", "--eval-iters", "1"])
    assert rc == 0


def test_lint_changed_wrapper_smoke():
    """tools/lint_changed.sh (the pre-push hook wrapper) runs the
    diff-aware lint against a real ref and exits clean on a tree whose
    changed files carry no unbaselined findings."""
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "tools", "lint_changed.sh")
    assert os.access(script, os.X_OK), "lint_changed.sh must be executable"
    proc = subprocess.run([script, "HEAD"], cwd=repo, capture_output=True,
                          text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "graftlint" in proc.stderr
    # the equivalent direct invocation agrees
    proc2 = subprocess.run(
        [sys.executable, "-m", "replicatinggpt_tpu", "lint", "--baseline",
         "--changed", "HEAD"], cwd=repo, capture_output=True, text=True,
        timeout=120)
    assert proc2.returncode == 0, proc2.stdout + proc2.stderr


def test_lint_github_format_annotations(capsys):
    """`--format github` prints one workflow-command annotation per
    finding (`::error file=...,line=...`) — what a GitHub Actions step
    pipes to stdout to get inline PR-diff annotations."""
    from replicatinggpt_tpu.cli import main
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bad = os.path.join(repo, "tests", "fixtures", "lint", "bad_gl019.py")
    rc = main(["lint", "--format", "github", "--no-baseline",
               "--severity", "tests/=error", "--rules", "GL019", bad])
    out = capsys.readouterr().out
    assert rc == 1
    lines = [l for l in out.splitlines() if l]
    assert lines and all(l.startswith("::error file=") for l in lines)
    assert any("GL019" in l and ",line=" in l and ",col=" in l
               for l in lines)
    # clean run under the baseline: zero annotation lines, exit 0
    rc = main(["lint", "--format", "github", "--baseline",
               "--rules", "GL019"])
    out = capsys.readouterr().out
    assert rc == 0
    assert [l for l in out.splitlines()
            if l.startswith("::error")] == []
