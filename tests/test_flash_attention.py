"""Flash-attention kernel parity vs the einsum reference path (interpret
mode on CPU; the same kernel compiles via Mosaic on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replicatinggpt_tpu.ops.attention import full_causal_attention
from replicatinggpt_tpu.ops.flash_pallas import pallas_flash_attention


def _qkv(B=2, H=2, T=256, D=64, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, H, T, D)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


def test_fwd_matches_einsum_causal():
    q, k, v = _qkv()
    ref = full_causal_attention(q, k, v)
    got = pallas_flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


def test_fwd_noncausal():
    q, k, v = _qkv(T=128)
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(logits, axis=-1), v)
    got = pallas_flash_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


def test_grads_match_einsum():
    q, k, v = _qkv(B=1, H=2, T=128, D=32)

    def loss_flash(q, k, v):
        return jnp.sum(pallas_flash_attention(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(full_causal_attention(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5,
                                   rtol=5e-5)


def test_custom_scale():
    q, k, v = _qkv(T=128)
    ref = full_causal_attention(q, k, v, scale=0.5)
    got = pallas_flash_attention(q, k, v, scale=0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


def test_bf16_inputs():
    q, k, v = _qkv(T=128, dtype=jnp.bfloat16)
    ref = full_causal_attention(q, k, v)
    got = pallas_flash_attention(q, k, v)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(ref, np.float32), atol=3e-2)


def test_uneven_T_rejected():
    # T <= block clamps the block to T, so T=96 is fine...
    q, k, v = _qkv(T=96)
    pallas_flash_attention(q, k, v)
    # ...but T=160 > block=128 and 160 % 128 != 0 must be rejected
    q2, k2, v2 = _qkv(T=160)
    with pytest.raises(AssertionError):
        pallas_flash_attention(q2, k2, v2)


def test_auto_impl_picks_flash_at_long_T(monkeypatch):
    """'auto' routes to the flash core once the dense (T,T) weight
    materialization stops being the right trade (measured crossover), and
    stays dense at short T / when attention-weight dropout must apply."""
    from replicatinggpt_tpu.config import ModelConfig
    from replicatinggpt_tpu.models.gpt import forward, init_params
    from replicatinggpt_tpu.ops import attention as attn_mod

    calls = []
    real = attn_mod.full_causal_attention

    def spy(q, k, v, **kw):
        calls.append(kw.get("impl"))
        return real(q, k, v, **kw)

    import replicatinggpt_tpu.models.gpt as gpt_mod
    monkeypatch.setattr(gpt_mod, "full_causal_attention", spy)

    def route_for(T, attn_dropout=0.0, train=False):
        cfg = ModelConfig(vocab_size=65, block_size=T, n_layer=1, n_head=2,
                          n_embd=64, dropout=0.0, attn_dropout=attn_dropout,
                          attention_impl="auto", dtype="float32")
        params = jax.eval_shape(lambda k: init_params(k, cfg),
                                jax.random.PRNGKey(0))
        rng = jax.random.PRNGKey(0) if train else None
        calls.clear()
        jax.make_jaxpr(
            lambda p, x: forward(p, x, cfg, rng=rng, train=train)[0]
        )(params, jnp.zeros((1, T), jnp.int32))
        assert calls, "attention core was not invoked"
        return calls[0]

    assert route_for(128) == "einsum"
    assert route_for(256) == "flash"  # measured crossover, v5e auto-tiles
    assert route_for(1024) == "flash"
    # dropout training still routes to flash: the kernel applies
    # attention-weight dropout in-kernel on TPU, and full_causal_attention
    # degrades to einsum elsewhere (one source of truth, no warning)
    assert route_for(1024, attn_dropout=0.2, train=True) == "flash"


# ---------------------------------------------------------------------------
# in-kernel attention-weight dropout (counter-based mask; interpret mode)
# ---------------------------------------------------------------------------

def test_dropout_keep_rate_statistics():
    """q=0 makes attention weights uniform over the causal prefix; with
    v=1 each output entry is (#kept / #allowed) / (1-rate), so the global
    mean estimates 1 and recovers the empirical keep rate."""
    B, H, T, D = 2, 2, 256, 32
    rate = 0.5
    q = jnp.zeros((B, H, T, D), jnp.float32)
    k = jnp.zeros((B, H, T, D), jnp.float32)  # s=0 -> uniform weights
    v = jnp.ones((B, H, T, D), jnp.float32)
    out = pallas_flash_attention(q, k, v, causal=True,
                                 dropout_rate=rate,
                                 dropout_rng=jax.random.PRNGKey(42))
    rows = np.asarray(out)[..., 0]                     # (B, H, T)
    n_allowed = np.arange(1, T + 1, dtype=np.float64)  # causal prefix sizes
    keeps = rows * n_allowed * (1.0 - rate)            # #kept per row
    keep_frac = keeps.sum() / (B * H * n_allowed.sum())
    assert abs(keep_frac - (1.0 - rate)) < 0.01, keep_frac
    # inverted dropout is unbiased: mean output ~ dropout-off output (=1)
    assert abs(rows.mean() - 1.0) < 0.02, rows.mean()


def test_dropout_deterministic_in_rng():
    q, k, v = _qkv(B=1, H=2, T=128, D=32)
    kw = dict(causal=True, dropout_rate=0.3)
    a = pallas_flash_attention(q, k, v, dropout_rng=jax.random.PRNGKey(7),
                               **kw)
    b = pallas_flash_attention(q, k, v, dropout_rng=jax.random.PRNGKey(7),
                               **kw)
    c = pallas_flash_attention(q, k, v, dropout_rng=jax.random.PRNGKey(8),
                               **kw)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.abs(np.asarray(a) - np.asarray(c)).max() > 1e-3


def test_dropout_bwd_matches_finite_difference():
    """The backward kernels regenerate the forward mask exactly: the
    custom VJP of the (deterministic, fixed-seed) dropout kernel must
    match finite differences."""
    B, H, T, D = 1, 1, 128, 32
    q, k, v = _qkv(B=B, H=H, T=T, D=D, seed=3)
    w = jax.random.normal(jax.random.PRNGKey(9), (B, H, T, D))
    rng = jax.random.PRNGKey(11)

    def loss(q, k, v):
        out = pallas_flash_attention(q, k, v, causal=True, dropout_rate=0.25,
                                     dropout_rng=rng)
        return jnp.sum(out * w)

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    rng_dir = jax.random.split(jax.random.PRNGKey(13), 3)
    eps = 1e-2
    for arg, (g, rd) in enumerate(zip(grads, rng_dir)):
        d = jax.random.normal(rd, g.shape)
        d = d / jnp.linalg.norm(d)
        args = [q, k, v]
        ap = list(args); ap[arg] = args[arg] + eps * d
        am = list(args); am[arg] = args[arg] - eps * d
        fd = (loss(*ap) - loss(*am)) / (2 * eps)
        ad = jnp.sum(g * d)
        np.testing.assert_allclose(float(ad), float(fd), rtol=2e-2,
                                   atol=2e-3)


def test_dropout_training_routes_to_einsum_off_tpu():
    """full_causal_attention(impl='flash') while training with dropout on a
    backend without the Pallas kernel must silently use the einsum path
    with identical semantics (same rng -> same mask)."""
    if jax.default_backend() == "tpu":
        pytest.skip("on TPU the flash path applies in-kernel dropout "
                    "(different mask stream than the einsum path)")
    q, k, v = _qkv(B=1, H=2, T=128, D=32)
    rng = jax.random.PRNGKey(5)
    a = full_causal_attention(q, k, v, dropout_rate=0.2, rng=rng,
                              train=True, impl="flash")
    b = full_causal_attention(q, k, v, dropout_rate=0.2, rng=rng,
                              train=True, impl="einsum")
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# K/V-streaming kernels (VMEM-unbounded T): parity vs the resident kernels
# ---------------------------------------------------------------------------

def test_stream_causal_matches_einsum():
    """Causal stream uses the triangular scalar-prefetch grid; block 128 at
    T=512 exercises multi-tile rows and the init/finalize carry."""
    q, k, v = _qkv(B=1, H=2, T=512, D=32)
    ref = full_causal_attention(q, k, v)
    got = pallas_flash_attention(q, k, v, causal=True, stream=True,
                                 block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


def test_stream_noncausal_matches_einsum():
    q, k, v = _qkv(B=1, H=2, T=256, D=32)
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(logits, axis=-1), v)
    got = pallas_flash_attention(q, k, v, causal=False, stream=True,
                                 block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


def test_stream_rectangular_causal_unequal_blocks():
    """causal + block_q != block_k routes to the rectangular streamed grid
    (triangular needs square tiles); its pl.when skip/finalize logic must
    hold."""
    q, k, v = _qkv(B=1, H=1, T=512, D=32)
    ref = full_causal_attention(q, k, v)
    got = pallas_flash_attention(q, k, v, causal=True, stream=True,
                                 block_q=256, block_k=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


def test_stream_grads_match_einsum():
    q, k, v = _qkv(B=1, H=2, T=256, D=32)

    def loss_stream(q, k, v):
        return jnp.sum(pallas_flash_attention(q, k, v, stream=True,
                                              block_q=128, block_k=128) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(full_causal_attention(q, k, v) ** 2)

    gf = jax.grad(loss_stream, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5,
                                   rtol=5e-5)


def test_stream_dropout_matches_resident():
    """The kernel families share their tile math and the counter-based
    dropout mask keys off absolute positions, so streamed output must be
    BIT-identical to the resident kernels' — fwd and grads (the module
    docstring's bit-identity claim is asserted here)."""
    q, k, v = _qkv(B=1, H=2, T=256, D=32)
    rng = jax.random.PRNGKey(7)
    kw = dict(dropout_rate=0.3, dropout_rng=rng, block_q=128, block_k=128)
    a = pallas_flash_attention(q, k, v, stream=True, **kw)
    b = pallas_flash_attention(q, k, v, stream=False, **kw)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    ga = jax.grad(lambda q: jnp.sum(
        pallas_flash_attention(q, k, v, stream=True, **kw) ** 2))(q)
    gb = jax.grad(lambda q: jnp.sum(
        pallas_flash_attention(q, k, v, stream=False, **kw) ** 2))(q)
    np.testing.assert_array_equal(np.asarray(ga), np.asarray(gb))


def test_stream_auto_threshold():
    from replicatinggpt_tpu.ops.flash_pallas import (STREAM_KV_BYTES,
                                                     _should_stream)
    # D=64 bf16: K+V bytes = 2*T*64*2 = 256*T -> threshold at T=16384
    assert not _should_stream(16384, 64, 2)
    assert _should_stream(16384 + 128, 64, 2)
    assert _should_stream(STREAM_KV_BYTES, 1, 1)


def test_tri_tile_map():
    from replicatinggpt_tpu.ops.flash_pallas import _tri_tile_map
    qm = _tri_tile_map(3, kv_major=False)
    assert qm.tolist() == [[0, 1, 1, 2, 2, 2], [0, 0, 1, 0, 1, 2]]
    km = _tri_tile_map(3, kv_major=True)
    assert km.tolist() == [[0, 0, 0, 1, 1, 2], [0, 1, 2, 1, 2, 2]]


@pytest.mark.slow
def test_auto_tile_512_parity_and_grads():
    """T=1024 auto-selects 512-wide tiles (_auto_block); the causal
    n_kv bound, the dkv first_q skip, and the dropout tiling must hold
    at that size, not just the 128/256 tiles the other tests use."""
    from replicatinggpt_tpu.ops.flash_pallas import _auto_block
    assert _auto_block(1024) == 512
    q, k, v = _qkv(B=1, H=1, T=1024, D=64, seed=5)
    ref = full_causal_attention(q, k, v)
    got = pallas_flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)
    gf = jax.grad(lambda q: jnp.sum(pallas_flash_attention(q, k, v) ** 2))(q)
    gr = jax.grad(lambda q: jnp.sum(full_causal_attention(q, k, v) ** 2))(q)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), atol=5e-5,
                               rtol=5e-5)
    # dropout mask is position-keyed, so tile size must not change it
    rng = jax.random.PRNGKey(3)
    a = pallas_flash_attention(q, k, v, dropout_rate=0.3, dropout_rng=rng)
    b = pallas_flash_attention(q, k, v, dropout_rate=0.3, dropout_rng=rng,
                               block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5,
                               rtol=2e-5)


@pytest.mark.slow
def test_fused_single_tile_bwd_matches_split_kernels():
    """T == block triggers the fused dq/dk/dv backward; forcing smaller
    blocks runs the split dq + dkv kernels. Gradients must agree (same
    tile math, different launch structure), with and without dropout."""
    B, H, T, D = 2, 3, 256, 64
    q = jax.random.normal(jax.random.PRNGKey(0), (B, H, T, D), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, H, T, D), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, H, T, D), jnp.float32)

    def grads(block, rate):
        def loss(q, k, v):
            kw = dict(causal=True, block_q=block, block_k=block)
            if rate > 0:
                kw.update(dropout_rate=rate,
                          dropout_rng=jax.random.PRNGKey(7))
            return jnp.sum(pallas_flash_attention(q, k, v, **kw) ** 2)
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    for rate in (0.0, 0.2):
        fused = grads(T, rate)        # single tile -> fused kernel
        split = grads(T // 2, rate)   # 2x2 tiles -> split dq + dkv kernels
        for a, b in zip(fused, split):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_fused_multi_tile_bwd_matches_split_kernels():
    """The kv-major fully-fused backward (1 < n_tiles, dq in VMEM
    scratch) must match the split dq + dkv kernels; forcing tiny blocks
    at T big enough to exceed the scratch bound runs the split path."""
    from replicatinggpt_tpu.ops import flash_pallas as fp

    B, H, T, D = 2, 2, 512, 64
    q = jax.random.normal(jax.random.PRNGKey(3), (B, H, T, D), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(4), (B, H, T, D), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(5), (B, H, T, D), jnp.float32)

    def grads(rate, scratch_bytes):
        old = fp.FUSED_DQ_SCRATCH_BYTES
        fp.FUSED_DQ_SCRATCH_BYTES = scratch_bytes
        try:
            def loss(q, k, v):
                kw = dict(causal=True, block_q=128, block_k=128)
                if rate > 0:
                    kw.update(dropout_rate=rate,
                              dropout_rng=jax.random.PRNGKey(11))
                return jnp.sum(pallas_flash_attention(q, k, v, **kw) ** 2)
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        finally:
            fp.FUSED_DQ_SCRATCH_BYTES = old
    for rate in (0.0, 0.2):
        fused = grads(rate, fp.FUSED_DQ_SCRATCH_BYTES)  # multi-tile fused
        split = grads(rate, 0)                           # forced split
        for a, b in zip(fused, split):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)


def test_bf16_grads_match_einsum():
    """bf16 inputs run the native-bf16 matmul tiles (p/ds cast to operand
    dtype, f32 accumulation); gradients must track the einsum reference
    within bf16 tolerance on BOTH backward families — fused (block == T)
    and split (forced smaller blocks). Pins the bf16-specific precision
    envelope the f32 parity tests can't see (ADVICE r2)."""
    B, H, T, D = 2, 2, 256, 64
    ks = jax.random.split(jax.random.PRNGKey(9), 3)
    q, k, v = (jax.random.normal(kk, (B, H, T, D), jnp.bfloat16)
               for kk in ks)

    def flash_grads(block):
        def loss(q, k, v):
            out = pallas_flash_attention(q, k, v, causal=True,
                                         block_q=block, block_k=block)
            return jnp.sum(out.astype(jnp.float32) ** 2)
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    def ref_grads():
        def loss(q, k, v):
            out = full_causal_attention(q, k, v, impl="einsum")
            return jnp.sum(out.astype(jnp.float32) ** 2)
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    gr = ref_grads()
    for block in (T, T // 2):  # fused single-tile, then split kernels
        gf = flash_grads(block)
        for a, b in zip(gf, gr):
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32)
            # bf16 has ~8 mantissa bits; grads here are O(1-30), so the
            # elementwise band is dominated by the final bf16 rounding
            np.testing.assert_allclose(a, b, rtol=6e-2, atol=0.25)


# ---------------------------------------------------------------------------
# packed-heads family: attention straight off the fused (B, T, 3C) QKV
# projection (no head transposes) — must match the unpacked family
# bit-for-bit on the same logical q/k/v
# ---------------------------------------------------------------------------

def _packed_inputs(B=2, T=256, H=6, D=64, seed=0, dtype=jnp.float32):
    C = H * D
    qkv = jax.random.normal(jax.random.PRNGKey(seed), (B, T, 3 * C), dtype)
    return qkv, C


def _heads(x, H):
    B, T, C = x.shape
    return x.reshape(B, T, H, C // H).transpose(0, 2, 1, 3)


def test_packed_fwd_bit_identical_to_unpacked():
    from replicatinggpt_tpu.ops.flash_pallas import \
        pallas_flash_attention_packed
    H = 6
    qkv, C = _packed_inputs(H=H)
    B, T = qkv.shape[:2]
    q, k, v = jnp.split(qkv, 3, -1)
    ref = pallas_flash_attention(_heads(q, H), _heads(k, H), _heads(v, H))
    ref = ref.transpose(0, 2, 1, 3).reshape(B, T, C)
    got = pallas_flash_attention_packed(qkv, H)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_packed_dropout_bit_identical_to_unpacked():
    """The packed kernel derives its dropout stream from bh = b*H + h —
    the same counter the unpacked kernels use — so masks must be exactly
    equal, not just statistically alike."""
    from replicatinggpt_tpu.ops.flash_pallas import \
        pallas_flash_attention_packed
    H = 4
    qkv, C = _packed_inputs(B=2, T=128, H=H, D=32, seed=3)
    B, T = qkv.shape[:2]
    rng = jax.random.PRNGKey(7)
    got = pallas_flash_attention_packed(qkv, H, dropout_rate=0.2,
                                        dropout_rng=rng)
    q, k, v = (_heads(t, H) for t in jnp.split(qkv, 3, -1))
    ref = pallas_flash_attention(q, k, v, dropout_rate=0.2, dropout_rng=rng)
    ref = ref.transpose(0, 2, 1, 3).reshape(B, T, C)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_packed_grads_match_unpacked():
    from replicatinggpt_tpu.ops.flash_pallas import \
        pallas_flash_attention_packed
    H = 4
    qkv, C = _packed_inputs(B=1, T=256, H=H, D=32, seed=11)
    B, T = qkv.shape[:2]

    def loss_packed(qkv):
        return jnp.sum(pallas_flash_attention_packed(qkv, H) ** 2)

    def loss_unpacked(qkv):
        q, k, v = (_heads(t, H) for t in jnp.split(qkv, 3, -1))
        o = pallas_flash_attention(q, k, v)
        return jnp.sum(o.transpose(0, 2, 1, 3).reshape(B, T, C) ** 2)

    gp = jax.grad(loss_packed)(qkv)
    gu = jax.grad(loss_unpacked)(qkv)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gu), atol=2e-4,
                               rtol=2e-4)


def test_packed_grads_with_dropout_match_unpacked():
    from replicatinggpt_tpu.ops.flash_pallas import \
        pallas_flash_attention_packed
    H = 2
    qkv, C = _packed_inputs(B=1, T=128, H=H, D=32, seed=13)
    B, T = qkv.shape[:2]
    rng = jax.random.PRNGKey(5)

    def loss_packed(qkv):
        o = pallas_flash_attention_packed(qkv, H, dropout_rate=0.25,
                                          dropout_rng=rng)
        return jnp.sum(o ** 2)

    def loss_unpacked(qkv):
        q, k, v = (_heads(t, H) for t in jnp.split(qkv, 3, -1))
        o = pallas_flash_attention(q, k, v, dropout_rate=0.25,
                                   dropout_rng=rng)
        return jnp.sum(o.transpose(0, 2, 1, 3).reshape(B, T, C) ** 2)

    gp = jax.grad(loss_packed)(qkv)
    gu = jax.grad(loss_unpacked)(qkv)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gu), atol=2e-4,
                               rtol=2e-4)


def test_packed_supported_envelope():
    from replicatinggpt_tpu.ops.flash_pallas import (PACKED_QKV_BYTES,
                                                     packed_supported)
    assert packed_supported(256, 384, 6, 2)        # char-GPT bf16
    assert not packed_supported(1024, 768, 12, 2)  # 124M: 4.7MB > bound
    assert not packed_supported(256, 384, 5, 2)    # C % H != 0
    assert not packed_supported(192, 384, 6, 2)    # T % 128 != 0
    assert not packed_supported(256, 96, 6, 2)     # D=16 not sliceable
    t_max = PACKED_QKV_BYTES // (3 * 384 * 2) // 128 * 128
    assert packed_supported(t_max, 384, 6, 2)
    assert not packed_supported(t_max + 128, 384, 6, 2)


def test_model_block_routes_packed(monkeypatch):
    """forward() with attention_impl resolving to flash must produce the
    same logits through the packed path (backend check monkeypatched so
    the interpret-mode kernel engages on CPU) as through the split-heads
    path."""
    import replicatinggpt_tpu.ops.flash_attention as fa
    from replicatinggpt_tpu.config import ModelConfig
    from replicatinggpt_tpu.models.gpt import forward, init_params

    mcfg = ModelConfig(vocab_size=64, block_size=256, n_layer=2, n_head=4,
                       n_embd=128, dropout=0.0, attn_dropout=0.0,
                       dtype="float32", attention_impl="flash")
    params = init_params(jax.random.PRNGKey(0), mcfg)
    x = jax.random.randint(jax.random.PRNGKey(1), (2, 256), 0, 64)

    ref, _ = forward(params, x, mcfg)  # CPU backend -> split path (SDPA)

    calls = []

    def force_packed(qkv, n_head, **kw):
        from replicatinggpt_tpu.ops.flash_pallas import \
            pallas_flash_attention_packed
        calls.append(qkv.shape)
        rng, train = kw.get("rng"), kw.get("train", False)
        rate = kw.get("dropout_rate", 0.0)
        on = train and rate > 0.0 and rng is not None
        return pallas_flash_attention_packed(
            qkv, n_head, scale=kw.get("scale"),
            dropout_rate=rate if on else 0.0,
            dropout_rng=rng if on else None)

    monkeypatch.setattr(fa, "packed_qkv_attention", force_packed)
    got, _ = forward(params, x, mcfg)
    assert calls, "packed path was not routed"
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-4,
                               rtol=2e-4)


# --- packed head-group family (GPT-2-scale shapes past the resident bound) --


def test_group_fwd_bit_identical_to_unpacked():
    """hpg=4 (D=32): four sub-heads lane-sliced per 128-wide strip."""
    from replicatinggpt_tpu.ops.flash_pallas import \
        pallas_flash_attention_packed
    H, D = 4, 32
    qkv, C = _packed_inputs(B=2, T=128, H=H, D=D, seed=21)
    B, T = qkv.shape[:2]
    q, k, v = jnp.split(qkv, 3, -1)
    ref = pallas_flash_attention(_heads(q, H), _heads(k, H), _heads(v, H))
    ref = ref.transpose(0, 2, 1, 3).reshape(B, T, C)
    got = pallas_flash_attention_packed(qkv, H, family="group")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_group_fwd_single_head_groups():
    """hpg=1 (D=128): strip == head, no in-kernel sub-head loop."""
    from replicatinggpt_tpu.ops.flash_pallas import \
        pallas_flash_attention_packed
    H, D = 2, 128
    qkv, C = _packed_inputs(B=1, T=128, H=H, D=D, seed=22)
    B, T = qkv.shape[:2]
    q, k, v = jnp.split(qkv, 3, -1)
    ref = pallas_flash_attention(_heads(q, H), _heads(k, H), _heads(v, H))
    ref = ref.transpose(0, 2, 1, 3).reshape(B, T, C)
    got = pallas_flash_attention_packed(qkv, H, family="group")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_group_matches_resident_packed():
    """Both packed families on the same in-envelope shape must agree
    exactly (same tile math, same bh counter stream)."""
    from replicatinggpt_tpu.ops.flash_pallas import \
        pallas_flash_attention_packed
    H = 6
    qkv, _ = _packed_inputs(B=2, T=256, H=H, D=64, seed=23)
    res = pallas_flash_attention_packed(qkv, H, family="resident")
    grp = pallas_flash_attention_packed(qkv, H, family="group")
    np.testing.assert_array_equal(np.asarray(grp), np.asarray(res))


def test_group_dropout_bit_identical_to_unpacked():
    """Sub-head s of group g keys dropout off bh = b*H + g*hpg + s — the
    global head counter — so masks must equal the unpacked family's."""
    from replicatinggpt_tpu.ops.flash_pallas import \
        pallas_flash_attention_packed
    H, D = 4, 32
    qkv, C = _packed_inputs(B=2, T=128, H=H, D=D, seed=24)
    B, T = qkv.shape[:2]
    rng = jax.random.PRNGKey(9)
    got = pallas_flash_attention_packed(qkv, H, family="group",
                                        dropout_rate=0.2, dropout_rng=rng)
    q, k, v = (_heads(t, H) for t in jnp.split(qkv, 3, -1))
    ref = pallas_flash_attention(q, k, v, dropout_rate=0.2, dropout_rng=rng)
    ref = ref.transpose(0, 2, 1, 3).reshape(B, T, C)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_group_grads_match_unpacked():
    from replicatinggpt_tpu.ops.flash_pallas import \
        pallas_flash_attention_packed
    H, D = 4, 32
    qkv, C = _packed_inputs(B=1, T=256, H=H, D=D, seed=25)
    B, T = qkv.shape[:2]

    def loss_group(qkv):
        o = pallas_flash_attention_packed(qkv, H, family="group")
        return jnp.sum(o ** 2)

    def loss_unpacked(qkv):
        q, k, v = (_heads(t, H) for t in jnp.split(qkv, 3, -1))
        o = pallas_flash_attention(q, k, v)
        return jnp.sum(o.transpose(0, 2, 1, 3).reshape(B, T, C) ** 2)

    gp = jax.grad(loss_group)(qkv)
    gu = jax.grad(loss_unpacked)(qkv)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gu), atol=2e-4,
                               rtol=2e-4)


def test_group_grads_with_dropout_match_unpacked():
    from replicatinggpt_tpu.ops.flash_pallas import \
        pallas_flash_attention_packed
    H, D = 2, 64
    qkv, C = _packed_inputs(B=1, T=128, H=H, D=D, seed=26)
    B, T = qkv.shape[:2]
    rng = jax.random.PRNGKey(15)

    def loss_group(qkv):
        o = pallas_flash_attention_packed(qkv, H, family="group",
                                          dropout_rate=0.25, dropout_rng=rng)
        return jnp.sum(o ** 2)

    def loss_unpacked(qkv):
        q, k, v = (_heads(t, H) for t in jnp.split(qkv, 3, -1))
        o = pallas_flash_attention(q, k, v, dropout_rate=0.25,
                                   dropout_rng=rng)
        return jnp.sum(o.transpose(0, 2, 1, 3).reshape(B, T, C) ** 2)

    gp = jax.grad(loss_group)(qkv)
    gu = jax.grad(loss_unpacked)(qkv)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gu), atol=2e-4,
                               rtol=2e-4)


def test_group_supported_envelope():
    from replicatinggpt_tpu.ops.flash_pallas import (GROUP_STRIP_BYTES,
                                                     packed_group_supported)
    assert packed_group_supported(1024, 768, 12, 2)    # GPT-2 124M bf16
    assert packed_group_supported(1024, 1024, 16, 2)   # GPT-2 350M bf16
    assert packed_group_supported(2048, 768, 12, 2)    # T at the W=128 cap
    assert not packed_group_supported(4096, 768, 12, 2)   # past the cap
    assert not packed_group_supported(1024, 1600, 25, 2)  # H=25 % hpg=2
    assert not packed_group_supported(1024, 768, 7, 2)    # C % H != 0
    assert not packed_group_supported(192, 768, 12, 2)    # T % 128 != 0
    t_max = GROUP_STRIP_BYTES // (128 * 2) // 128 * 128
    assert packed_group_supported(t_max, 768, 12, 2)
    assert not packed_group_supported(t_max + 128, 768, 12, 2)


# --- streamed head-group family (packed long-T past GROUP_STRIP_BYTES) -----


def test_group_stream_fwd_bit_identical_to_group():
    """Same strips, kv axis moved to the grid with scratch state: must
    reproduce the resident group family exactly (shared tile math,
    shared bh counter stream)."""
    from replicatinggpt_tpu.ops.flash_pallas import \
        pallas_flash_attention_packed
    H, D = 4, 32
    qkv, C = _packed_inputs(B=2, T=256, H=H, D=D, seed=31)
    grp = pallas_flash_attention_packed(qkv, H, family="group")
    strm = pallas_flash_attention_packed(qkv, H, family="group_stream")
    np.testing.assert_array_equal(np.asarray(strm), np.asarray(grp))


def test_group_stream_fwd_matches_unpacked():
    from replicatinggpt_tpu.ops.flash_pallas import \
        pallas_flash_attention_packed
    H, D = 2, 64
    qkv, C = _packed_inputs(B=1, T=128, H=H, D=D, seed=32)
    B, T = qkv.shape[:2]
    q, k, v = jnp.split(qkv, 3, -1)
    ref = pallas_flash_attention(_heads(q, H), _heads(k, H), _heads(v, H))
    ref = ref.transpose(0, 2, 1, 3).reshape(B, T, C)
    got = pallas_flash_attention_packed(qkv, H, family="group_stream")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_group_stream_dropout_bit_identical_to_unpacked():
    from replicatinggpt_tpu.ops.flash_pallas import \
        pallas_flash_attention_packed
    H, D = 4, 32
    qkv, C = _packed_inputs(B=2, T=128, H=H, D=D, seed=33)
    B, T = qkv.shape[:2]
    rng = jax.random.PRNGKey(29)
    got = pallas_flash_attention_packed(qkv, H, family="group_stream",
                                        dropout_rate=0.2, dropout_rng=rng)
    q, k, v = (_heads(t, H) for t in jnp.split(qkv, 3, -1))
    ref = pallas_flash_attention(q, k, v, dropout_rate=0.2, dropout_rng=rng)
    ref = ref.transpose(0, 2, 1, 3).reshape(B, T, C)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


@pytest.mark.slow
def test_group_stream_grads_match_unpacked():
    from replicatinggpt_tpu.ops.flash_pallas import \
        pallas_flash_attention_packed
    H, D = 4, 32
    qkv, C = _packed_inputs(B=1, T=256, H=H, D=D, seed=34)
    B, T = qkv.shape[:2]

    def loss_stream(qkv):
        o = pallas_flash_attention_packed(qkv, H, family="group_stream")
        return jnp.sum(o ** 2)

    def loss_unpacked(qkv):
        q, k, v = (_heads(t, H) for t in jnp.split(qkv, 3, -1))
        o = pallas_flash_attention(q, k, v)
        return jnp.sum(o.transpose(0, 2, 1, 3).reshape(B, T, C) ** 2)

    gs = jax.grad(loss_stream)(qkv)
    gu = jax.grad(loss_unpacked)(qkv)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gu), atol=2e-4,
                               rtol=2e-4)


def test_group_stream_grads_with_dropout_match_group():
    """The two group families' backwards recompute the same dropout
    masks from the same counters — grads must agree exactly."""
    from replicatinggpt_tpu.ops.flash_pallas import \
        pallas_flash_attention_packed
    H, D = 2, 64
    qkv, C = _packed_inputs(B=1, T=128, H=H, D=D, seed=35)
    rng = jax.random.PRNGKey(41)

    def loss(qkv, family):
        o = pallas_flash_attention_packed(qkv, H, family=family,
                                          dropout_rate=0.25,
                                          dropout_rng=rng)
        return jnp.sum(o ** 2)

    gs = jax.grad(lambda x: loss(x, "group_stream"))(qkv)
    gg = jax.grad(lambda x: loss(x, "group"))(qkv)
    np.testing.assert_array_equal(np.asarray(gs), np.asarray(gg))


def test_group_stream_tri_multiblock_matches_unpacked():
    """Explicit block=128 at T=512 -> a 4x4 lower triangle (10 tiles) on
    the scalar-prefetched tile map; auto blocks would pick 512 and
    collapse the map to one tile, leaving the carried-state path
    untested. Bit-parity vs the unpacked kernel at the same tiles."""
    from replicatinggpt_tpu.ops.flash_pallas import \
        pallas_flash_attention_packed
    H, D = 4, 32
    qkv, C = _packed_inputs(B=1, T=512, H=H, D=D, seed=38)
    B, T = qkv.shape[:2]
    got = pallas_flash_attention_packed(qkv, H, family="group_stream",
                                        block_q=128, block_k=128)
    q, k, v = (_heads(t, H) for t in jnp.split(qkv, 3, -1))
    ref = pallas_flash_attention(q, k, v, block_q=128, block_k=128)
    ref = ref.transpose(0, 2, 1, 3).reshape(B, T, C)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


@pytest.mark.slow
def test_group_stream_tri_multiblock_grads_with_dropout():
    """Multi-block triangular backward (dq carried over kv steps, dk/dv
    over q steps) with the in-kernel dropout stream, vs the unpacked
    kernel at the same tiles."""
    from replicatinggpt_tpu.ops.flash_pallas import \
        pallas_flash_attention_packed
    H, D = 2, 64
    qkv, C = _packed_inputs(B=1, T=384, H=H, D=D, seed=39)
    B, T = qkv.shape[:2]
    rng = jax.random.PRNGKey(53)

    def loss_tri(qkv):
        o = pallas_flash_attention_packed(qkv, H, family="group_stream",
                                          block_q=128, block_k=128,
                                          dropout_rate=0.25,
                                          dropout_rng=rng)
        return jnp.sum(o ** 2)

    def loss_unpacked(qkv):
        q, k, v = (_heads(t, H) for t in jnp.split(qkv, 3, -1))
        o = pallas_flash_attention(q, k, v, block_q=128, block_k=128,
                                   dropout_rate=0.25, dropout_rng=rng)
        return jnp.sum(o.transpose(0, 2, 1, 3).reshape(B, T, C) ** 2)

    gt = jax.grad(loss_tri)(qkv)
    gu = jax.grad(loss_unpacked)(qkv)
    np.testing.assert_allclose(np.asarray(gt), np.asarray(gu), atol=2e-4,
                               rtol=2e-4)


def test_group_stream_rect_unequal_blocks():
    """block_q != block_k keeps the rectangular grid (the triangular
    tile map needs equal blocks); with identical tile sizes the unpacked
    kernel runs the same update sequence, so outputs are bit-equal."""
    from replicatinggpt_tpu.ops.flash_pallas import \
        pallas_flash_attention_packed
    H, D = 4, 32
    qkv, C = _packed_inputs(B=1, T=256, H=H, D=D, seed=36)
    B, T = qkv.shape[:2]
    got = pallas_flash_attention_packed(qkv, H, family="group_stream",
                                        block_q=128, block_k=64)
    q, k, v = (_heads(t, H) for t in jnp.split(qkv, 3, -1))
    ref = pallas_flash_attention(q, k, v, block_q=128, block_k=64)
    ref = ref.transpose(0, 2, 1, 3).reshape(B, T, C)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_group_stream_rect_grads_match_unpacked():
    """Backward through the rectangular streamed-group grid (forced via
    unequal blocks) against the unpacked kernel at the same tile
    sizes."""
    from replicatinggpt_tpu.ops.flash_pallas import \
        pallas_flash_attention_packed
    H, D = 2, 64
    qkv, C = _packed_inputs(B=1, T=128, H=H, D=D, seed=37)
    B, T = qkv.shape[:2]

    def loss_rect(qkv):
        o = pallas_flash_attention_packed(qkv, H, family="group_stream",
                                          block_q=128, block_k=64)
        return jnp.sum(o ** 2)

    def loss_unpacked(qkv):
        q, k, v = (_heads(t, H) for t in jnp.split(qkv, 3, -1))
        o = pallas_flash_attention(q, k, v, block_q=128, block_k=64)
        return jnp.sum(o.transpose(0, 2, 1, 3).reshape(B, T, C) ** 2)

    gr = jax.grad(loss_rect)(qkv)
    gu = jax.grad(loss_unpacked)(qkv)
    np.testing.assert_allclose(np.asarray(gr), np.asarray(gu), atol=2e-4,
                               rtol=2e-4)


def test_model_block_routes_group_stream_past_strip_bound(monkeypatch):
    """forward() end-to-end through the packed AUTO routing when both
    residency bounds exclude the other families: the streamed group
    family must be selected and produce the split-path logits. Bounds
    are shrunk instead of using a real >2048-token model so the test
    stays in the fast tier."""
    import replicatinggpt_tpu.ops.flash_attention as fa
    import replicatinggpt_tpu.ops.flash_pallas as fp
    from replicatinggpt_tpu.config import ModelConfig
    from replicatinggpt_tpu.models.gpt import forward, init_params

    mcfg = ModelConfig(vocab_size=64, block_size=512, n_layer=1, n_head=4,
                       n_embd=128, dropout=0.0, attn_dropout=0.0,
                       dtype="float32", attention_impl="flash")
    params = init_params(jax.random.PRNGKey(0), mcfg)
    x = jax.random.randint(jax.random.PRNGKey(1), (1, 512), 0, 64)
    ref, _ = forward(params, x, mcfg)  # CPU backend -> split path

    calls = []
    orig = fp._flash_packed_group_stream

    def spy(*a, **kw):
        calls.append(True)
        return orig(*a, **kw)

    monkeypatch.setattr(fp, "PACKED_QKV_BYTES", 1)
    monkeypatch.setattr(fp, "GROUP_STRIP_BYTES", 1)
    monkeypatch.setattr(fp, "GROUP_STREAM_AUTOROUTE", True)
    monkeypatch.setattr(fp, "_flash_packed_group_stream", spy)
    monkeypatch.setattr(fa, "_packed_backend_ok", lambda: True)
    got, _ = forward(params, x, mcfg)
    assert calls, "streamed group family was not routed"
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-4,
                               rtol=2e-4)


def test_group_stream_envelope_and_routing(monkeypatch):
    """Past GROUP_STRIP_BYTES the entry must route group_stream once its
    hardware-validation gate is open; the envelope gate in
    ops.flash_attention must agree."""
    import replicatinggpt_tpu.ops.flash_pallas as fp
    from replicatinggpt_tpu.ops.flash_attention import packed_envelope_ok
    from replicatinggpt_tpu.ops.flash_pallas import (
        packed_group_stream_supported, packed_group_supported)
    # 124M shapes at T=4096: group is off-envelope, stream is on
    assert not packed_group_supported(4096, 768, 12, 2)
    assert packed_group_stream_supported(4096, 768, 12, 2)
    # longctx bench shapes (T=32k, C=256, H=4 -> D=64)
    assert packed_group_stream_supported(32768, 256, 4, 2)
    # geometry failures still excluded
    assert not packed_group_stream_supported(4096, 1600, 25, 2)
    assert not packed_group_stream_supported(192, 768, 12, 2)
    import replicatinggpt_tpu.ops.flash_attention as fa
    monkeypatch.setattr(fa, "_packed_backend_ok", lambda: True)
    qkv = jnp.zeros((1, 4096, 3 * 768), jnp.bfloat16)
    monkeypatch.setattr(fp, "GROUP_STREAM_AUTOROUTE", True)
    assert packed_envelope_ok(qkv, 12)


def test_group_stream_gated_out_of_autoroute_by_default(monkeypatch):
    """Until hw_validate's compile/parity phases pass on real Mosaic,
    group_stream must stay opt-in: with the gate at its shipped default
    the envelope excludes group_stream-only shapes (callers fall back to
    the hardware-proven unpacked streamed family) and the family=None
    entry refuses rather than silently picking it."""
    import replicatinggpt_tpu.ops.flash_attention as fa
    import replicatinggpt_tpu.ops.flash_pallas as fp
    from replicatinggpt_tpu.ops.flash_attention import packed_envelope_ok
    assert fp.GROUP_STREAM_AUTOROUTE is False  # shipped default
    monkeypatch.setattr(fa, "_packed_backend_ok", lambda: True)
    # T=4096 @ 124M widths: only group_stream covers it -> envelope closed
    qkv = jnp.zeros((1, 4096, 3 * 768), jnp.bfloat16)
    assert not packed_envelope_ok(qkv, 12)
    with pytest.raises(ValueError, match="packed families"):
        fp.pallas_flash_attention_packed(qkv, 12)
    # explicit opt-in still addresses the family (envelope fn agrees)
    assert fp.packed_group_stream_supported(4096, 768, 12, 2)


def test_packed_entry_routes_group_past_resident_bound():
    """At 124M shapes (T=1024, C=768) the resident family is off-envelope
    and the entry must route to the group family; the envelope gate in
    ops.flash_attention must agree."""
    from replicatinggpt_tpu.ops.flash_attention import packed_envelope_ok
    from replicatinggpt_tpu.ops.flash_pallas import (packed_group_supported,
                                                     packed_supported)
    assert not packed_supported(1024, 768, 12, 2)
    assert packed_group_supported(1024, 768, 12, 2)
    import replicatinggpt_tpu.ops.flash_attention as fa
    orig = fa._packed_backend_ok
    fa._packed_backend_ok = lambda: True
    try:
        qkv = jnp.zeros((1, 1024, 3 * 768), jnp.bfloat16)
        assert packed_envelope_ok(qkv, 12)
    finally:
        fa._packed_backend_ok = orig
