"""Numerical parity: JAX backend vs the PyTorch-CPU reference backend.

BASELINE.json defines correctness as parity with the PyTorch-CPU reference
path; these tests inject identical weights into both backends and require
matching logits/losses (f32, CPU)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax
import jax.numpy as jnp

from replicatinggpt_tpu.config import ModelConfig
from replicatinggpt_tpu.models.gpt import forward, init_params
from replicatinggpt_tpu.reference_torch import (RefGPT, measure_train_throughput,
                                                params_to_torch,
                                                torch_to_params)

CFG = ModelConfig(vocab_size=65, block_size=32, n_layer=2, n_head=4,
                  n_embd=64, dropout=0.0, attn_dropout=0.0, dtype="float32",
                  activation="relu", tied_head=False)


def _x(B=4, seed=0):
    return np.random.default_rng(seed).integers(
        0, CFG.vocab_size, (B, CFG.block_size)).astype(np.int32)


@pytest.mark.parametrize("tied,act", [(False, "relu"), (True, "gelu")])
def test_logits_and_loss_parity(tied, act):
    cfg = ModelConfig(**{**CFG.__dict__, "tied_head": tied,
                         "activation": act})
    params = init_params(jax.random.PRNGKey(0), cfg)
    model = params_to_torch(params, RefGPT(cfg)).eval()
    x = _x()
    jl, jloss = forward(params, jnp.asarray(x), cfg,
                        targets=jnp.asarray(x))
    with torch.no_grad():
        tl, tloss = model(torch.tensor(np.asarray(x, np.int64)),
                          torch.tensor(np.asarray(x, np.int64)))
    np.testing.assert_allclose(np.asarray(jl), tl.numpy(), atol=2e-4,
                               rtol=1e-4)
    assert abs(float(jloss) - float(tloss)) < 1e-4


def test_roundtrip_weight_transfer():
    params = init_params(jax.random.PRNGKey(1), CFG)
    model = params_to_torch(params, RefGPT(CFG))
    back = torch_to_params(model)
    for la, lb in zip(jax.tree_util.tree_leaves(params),
                      jax.tree_util.tree_leaves(back)):
        np.testing.assert_allclose(np.asarray(la), lb, atol=1e-6)


def test_grad_parity():
    """One backward pass: gradients of wte must match across backends."""
    cfg = CFG
    params = init_params(jax.random.PRNGKey(2), cfg)
    model = params_to_torch(params, RefGPT(cfg)).train()
    x = _x()
    from replicatinggpt_tpu.train.steps import loss_fn
    jg = jax.grad(loss_fn)(params, (jnp.asarray(x), jnp.asarray(x)), cfg)
    _, tloss = model(torch.tensor(np.asarray(x, np.int64)),
                     torch.tensor(np.asarray(x, np.int64)))
    tloss.backward()
    np.testing.assert_allclose(np.asarray(jg["wte"]),
                               model.wte.grad.numpy(), atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(jg["blocks"]["qkv_kernel"][0]),
        model.blocks[0].qkv_kernel.grad.numpy(), atol=2e-4)


def test_throughput_measure_runs():
    tiny = ModelConfig(vocab_size=65, block_size=16, n_layer=1, n_head=2,
                       n_embd=32, dropout=0.0, attn_dropout=0.0)
    tps = measure_train_throughput(tiny, batch_size=2, steps=1, warmup=0)
    assert tps > 0
