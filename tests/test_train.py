"""Training tests: step mechanics, convergence on tiny char-GPT, eval
semantics, runner end-to-end."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replicatinggpt_tpu.config import get_config
from replicatinggpt_tpu.train.state import create_train_state
from replicatinggpt_tpu.train.steps import (estimate_loss, make_eval_step,
                                            make_train_step)


@pytest.fixture(scope="module")
def tiny():
    return get_config("test-tiny")


def test_train_step_advances_and_reduces_loss(tiny):
    m, t = tiny.model, tiny.train
    state = create_train_state(jax.random.PRNGKey(0), m, t)
    step = make_train_step(m, t, donate=False, with_grad_norm=True)
    x = jax.random.randint(jax.random.PRNGKey(1), (8, m.block_size), 0,
                           m.vocab_size)
    first = None
    for _ in range(25):
        state, metrics = step(state, (x, x))
        first = first if first is not None else float(metrics["loss"])
    assert int(state.step) == 25
    assert float(metrics["loss"]) < first
    assert np.isfinite(float(metrics["grad_norm"]))


def test_eval_step_no_dropout_deterministic(tiny):
    m = tiny.model
    state = create_train_state(jax.random.PRNGKey(0), m, tiny.train)
    ev = make_eval_step(m)
    x = jax.random.randint(jax.random.PRNGKey(1), (4, m.block_size), 0,
                           m.vocab_size)
    a = float(ev(state.params, (x, x)))
    b = float(ev(state.params, (x, x)))
    assert a == b


def test_estimate_loss_means_over_splits(tiny):
    from replicatinggpt_tpu.data import make_batcher
    m = tiny.model
    state = create_train_state(jax.random.PRNGKey(0), m, tiny.train)
    ev = make_eval_step(m)
    data = np.random.default_rng(0).integers(0, m.vocab_size, 5000,
                                             dtype=np.int32)
    batchers = {
        "train": make_batcher("random", data, 4, m.block_size, seed=1),
        "val": make_batcher("random", data, 4, m.block_size, seed=2),
    }
    out = estimate_loss(state.params, batchers, ev, eval_iters=3)
    assert set(out) == {"train", "val"}
    # both splits ~ uniform-random → loss near ln(V)
    for v in out.values():
        assert abs(v - np.log(m.vocab_size)) < 0.5


@pytest.mark.slow
def test_runner_end_to_end_loss_decreases(tiny, tmp_path):
    """Full pipeline on real Tiny Shakespeare, 60 steps of the tiny model:
    val loss must drop below the uniform-random baseline ln(65)≈4.17."""
    import dataclasses
    from replicatinggpt_tpu.train.runner import train
    cfg = tiny.replace(
        train=dataclasses.replace(tiny.train, max_iters=60, eval_interval=0,
                                  eval_iters=8, log_interval=0),
        dataset="datasets/shakespeare.txt")
    res = train(cfg)
    assert res.final_eval["val"] < 4.0
    assert res.tokens_per_sec_per_chip > 0


def test_lr_schedule_warmup_cosine():
    import dataclasses
    from replicatinggpt_tpu.train.state import lr_schedule_fn
    t = get_config("test-tiny").train
    t = dataclasses.replace(t, lr_schedule="cosine", warmup_iters=10,
                            max_iters=100, lr=1e-3, min_lr=1e-5)
    sched = lr_schedule_fn(t)
    assert float(sched(0)) == 0.0
    assert abs(float(sched(10)) - 1e-3) < 1e-9
    assert float(sched(100)) < 1e-3 / 2
    # the runner's log-line helper surfaces the scheduled value (and stays
    # None for the reference-style constant-lr loop)
    from replicatinggpt_tpu.train.runner import _make_lr_reader
    assert _make_lr_reader(t)(10) == pytest.approx(1e-3)
    assert _make_lr_reader(get_config("test-tiny").train)(10) is None


@pytest.mark.slow
def test_train_scan_matches_single_steps(tiny):
    """K-step lax.scan dispatch must be semantically identical to K single
    steps (same per-step RNG fold, same optimizer stepping)."""
    import dataclasses
    from replicatinggpt_tpu.train.steps import make_train_scan
    m = dataclasses.replace(tiny.model, dropout=0.1, attn_dropout=0.1)
    t = tiny.train
    K, B = 6, 4
    rngs = jax.random.split(jax.random.PRNGKey(3), 2 * K)
    xs = np.stack([np.asarray(jax.random.randint(r, (B, m.block_size), 0,
                                                 m.vocab_size))
                   for r in rngs[:K]]).astype(np.int32)
    ys = np.stack([np.asarray(jax.random.randint(r, (B, m.block_size), 0,
                                                 m.vocab_size))
                   for r in rngs[K:]]).astype(np.int32)

    s1 = create_train_state(jax.random.PRNGKey(0), m, t)
    step = make_train_step(m, t, donate=False)
    losses_single = []
    for i in range(K):
        s1, met = step(s1, (xs[i], ys[i]))
        losses_single.append(float(met["loss"]))

    s2 = create_train_state(jax.random.PRNGKey(0), m, t)
    scan = make_train_scan(m, t, K, donate=False)
    s2, met = scan(s2, (jnp.asarray(xs), jnp.asarray(ys)))

    np.testing.assert_allclose(np.asarray(met["loss"]), losses_single,
                               rtol=2e-5)
    assert int(s2.step) == int(s1.step) == K
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        s1.params, s2.params)


@pytest.mark.slow
def test_runner_steps_per_dispatch_same_result(tiny):
    """Runner with steps_per_dispatch>1 reaches the same final eval as the
    single-step loop (identical seeded batch stream + step semantics)."""
    import dataclasses
    from replicatinggpt_tpu.train.runner import train
    base = tiny.replace(
        train=dataclasses.replace(tiny.train, max_iters=40, eval_interval=0,
                                  eval_iters=4, log_interval=10),
        dataset="datasets/shakespeare.txt")
    r1 = train(base)
    r2 = train(base.replace(
        train=dataclasses.replace(base.train, steps_per_dispatch=10)))
    assert abs(r1.final_eval["val"] - r2.final_eval["val"]) < 2e-3


def test_estimate_loss_scan_matches_loop(tiny):
    """Scanned eval must see the same batches and produce the same mean
    loss as the per-batch loop (float32 reduction tolerance only)."""
    from replicatinggpt_tpu.data.loader import make_batcher
    from replicatinggpt_tpu.train.steps import make_eval_scan

    m, t = tiny.model, tiny.train
    state = create_train_state(jax.random.PRNGKey(0), m, t)
    data = np.asarray(jax.random.randint(jax.random.PRNGKey(1), (5000,), 0,
                                         m.vocab_size), np.int32)

    def batchers(seed):
        return {"train": make_batcher("random", data, 4, m.block_size,
                                      seed=seed),
                "val": make_batcher("random", data, 4, m.block_size,
                                    seed=seed + 1)}

    loop = estimate_loss(state.params, batchers(5), make_eval_step(m), 6)
    scan = estimate_loss(state.params, batchers(5), make_eval_step(m), 6,
                         eval_scan=make_eval_scan(m))
    for split in ("train", "val"):
        assert abs(loop[split] - scan[split]) < 1e-5


@pytest.mark.slow
def test_grad_accum_matches_full_batch(tiny):
    """grad_accum_steps=A over (A, b, T) microbatches must take the same
    optimizer step as one full (A*b, T) batch: equal-sized microbatch
    mean-of-means == full-batch mean (dropout off; f32 summation-order
    tolerance only)."""
    import dataclasses
    m, t = tiny.model, tiny.train
    A, b = 4, 4
    x = np.asarray(jax.random.randint(jax.random.PRNGKey(1),
                                      (A * b, m.block_size), 0,
                                      m.vocab_size), np.int32)
    y = np.asarray(jax.random.randint(jax.random.PRNGKey(2),
                                      (A * b, m.block_size), 0,
                                      m.vocab_size), np.int32)

    s_full = create_train_state(jax.random.PRNGKey(0), m, t)
    full = make_train_step(m, dataclasses.replace(t, batch_size=A * b),
                           donate=False)
    s_full, met_full = full(s_full, (x, y))

    s_acc = create_train_state(jax.random.PRNGKey(0), m, t)
    acc = make_train_step(
        m, dataclasses.replace(t, batch_size=b, grad_accum_steps=A),
        donate=False)
    s_acc, met_acc = acc(
        s_acc, (x.reshape(A, b, -1), y.reshape(A, b, -1)))

    assert abs(float(met_full["loss"]) - float(met_acc["loss"])) < 1e-5
    assert int(s_acc.step) == 1
    jax.tree_util.tree_map(
        lambda p, q: np.testing.assert_allclose(p, q, rtol=1e-5, atol=1e-6),
        s_full.params, s_acc.params)


@pytest.mark.slow
def test_grad_accum_with_dropout_deterministic(tiny):
    """Under dropout, accumulation draws a distinct mask stream per
    microbatch (rng folded on the scan index) and the step is a pure
    function of (state, batch)."""
    import dataclasses
    m = dataclasses.replace(tiny.model, dropout=0.2, attn_dropout=0.2)
    t = dataclasses.replace(tiny.train, batch_size=4, grad_accum_steps=2)
    x = np.asarray(jax.random.randint(jax.random.PRNGKey(1),
                                      (2, 4, m.block_size), 0,
                                      m.vocab_size), np.int32)
    s1 = create_train_state(jax.random.PRNGKey(0), m, t)
    s2 = create_train_state(jax.random.PRNGKey(0), m, t)
    step = make_train_step(m, t, donate=False)
    s1, m1 = step(s1, (x, x))
    s2, m2 = step(s2, (x, x))
    assert float(m1["loss"]) == float(m2["loss"])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b),
        s1.params, s2.params)


@pytest.mark.slow
def test_runner_grad_accum_composes_with_scan_dispatch(tiny):
    """Runner with grad_accum_steps>1 walks the same trajectory whether
    steps are dispatched one at a time or K per lax.scan (the (K, A, B, T)
    feed path)."""
    import dataclasses
    from replicatinggpt_tpu.train.runner import train
    base = tiny.replace(
        train=dataclasses.replace(tiny.train, max_iters=12, eval_interval=6,
                                  eval_iters=2, log_interval=0, batch_size=4,
                                  grad_accum_steps=2),
        dataset="datasets/shakespeare.txt")
    r1 = train(base)
    r2 = train(base.replace(
        train=dataclasses.replace(base.train, steps_per_dispatch=3)))
    h1 = np.asarray([[tr, va] for _, tr, va in r1.history])
    h2 = np.asarray([[tr, va] for _, tr, va in r2.history])
    assert h1.shape == h2.shape
    np.testing.assert_allclose(h1, h2, rtol=2e-4)
