"""graftlint tests: every rule flags its bad fixture and passes its good
one, both pragma forms suppress, the committed baseline exactly matches
a fresh whole-package run (the tier-1 CI gate), and the generated rule
docs cannot drift from the registry."""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from replicatinggpt_tpu.analysis import (DEFAULT_BASELINE, RULES,
                                         diff_against_baseline, lint_paths,
                                         lint_source, load_baseline,
                                         render_rule_docs)

FIXTURES = Path(__file__).parent / "fixtures" / "lint"
REPO = Path(__file__).resolve().parent.parent

RULE_IDS = sorted(RULES)


def test_registry_shape():
    assert len(RULES) >= 8                    # the tentpole's rule floor
    for rid, rule in RULES.items():
        assert rid == rule.id and rid.startswith("GL") and len(rid) == 5
        assert rule.name and rule.rationale and rule.bad and rule.good
        assert callable(rule.checker)


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_bad_fixture_flagged(rule_id):
    """Each rule must flag its known-bad snippet (run with only that
    rule active, so the assertion is about THIS rule's detector)."""
    path = FIXTURES / f"bad_{rule_id.lower()}.py"
    res = lint_paths([path], [rule_id])
    assert res.findings, f"{rule_id} missed its bad fixture"
    assert {f.rule for f in res.findings} == {rule_id}
    for f in res.findings:
        assert f.line > 0 and f.text            # anchored + baselineable


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_good_fixture_clean(rule_id):
    """The matching clean snippet must pass ALL rules (fixtures are
    written to be globally clean, not just clean for their own rule)."""
    path = FIXTURES / f"good_{rule_id.lower()}.py"
    res = lint_paths([path])
    assert res.findings == [], [f.format() for f in res.findings]


def test_line_pragma_suppresses():
    res = lint_paths([FIXTURES / "suppressed_line.py"])
    assert res.findings == []
    assert [f.rule for f in res.suppressed] == ["GL004"]


def test_file_pragma_suppresses():
    res = lint_paths([FIXTURES / "suppressed_file.py"])
    assert res.findings == []
    assert {f.rule for f in res.suppressed} == {"GL004"}


def test_pragma_only_masks_named_rule():
    src = ("import numpy as np\n"
           "def f(xs):\n"
           "    for x in xs:\n"
           "        np.asarray(x)  # graftlint: disable=GL001\n")
    res = lint_source(src, "t.py")
    assert [f.rule for f in res.findings] == ["GL004"]   # wrong id: no-op


def test_syntax_error_reported_not_raised():
    res = lint_source("def broken(:\n", "t.py")
    assert [f.rule for f in res.findings] == ["GL000"]


def test_baseline_matches_fresh_whole_package_run():
    """The committed graftlint_baseline.json must EXACTLY equal a fresh
    run over the package: a new finding fails CI, and a fixed finding
    must be removed from the baseline (no silent staleness in either
    direction). Refresh with `python -m replicatinggpt_tpu lint
    --write-baseline`."""
    res = lint_paths([])                      # default: the package
    diff = diff_against_baseline(res.findings,
                                 load_baseline(DEFAULT_BASELINE))
    assert diff.exact, {
        "new": [f.format() for f in diff.new],
        "stale": diff.stale,
    }


def test_cli_gate_in_process():
    from replicatinggpt_tpu.cli import main
    assert main(["lint", "--baseline"]) == 0


def test_cli_gate_subprocess():
    """The exact tier-1 invocation: `python -m replicatinggpt_tpu lint
    --baseline` exits 0 against the committed baseline."""
    proc = subprocess.run(
        [sys.executable, "-m", "replicatinggpt_tpu", "lint", "--baseline"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_fails_on_new_finding():
    from replicatinggpt_tpu.cli import main
    bad = FIXTURES / "bad_gl004.py"
    assert main(["lint", str(bad)]) == 1
    assert main(["lint", "--baseline", str(DEFAULT_BASELINE),
                 str(bad)]) == 1              # fixtures aren't baselined


def test_cli_json_reflects_baseline_diff(capsys):
    """Under --baseline, the JSON payload must agree with the exit
    code: `findings` holds only NEW hazards (empty on a clean tree),
    absorbed ones appear as a `baselined` count."""
    from replicatinggpt_tpu.cli import main
    rc = main(["lint", "--baseline", "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["findings"] == []
    assert out["baselined"] > 0 and out["stale"] == []


def test_cli_json_format(capsys):
    from replicatinggpt_tpu.cli import main
    rc = main(["lint", "--format", "json", str(FIXTURES / "bad_gl006.py")])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["files"] == 1
    assert all(f["rule"] == "GL006" for f in out["findings"])
    assert len(out["findings"]) >= 2          # both dus spellings


def test_docs_generated_from_registry_in_sync():
    committed = (REPO / "docs" / "graftlint_rules.md").read_text()
    assert committed == render_rule_docs(), (
        "docs/graftlint_rules.md is stale — regenerate with "
        "`python -m replicatinggpt_tpu lint --docs > "
        "docs/graftlint_rules.md`")
    for rid in RULE_IDS:                      # every rule documented
        assert f"## {rid}" in committed


def test_baseline_diff_mechanics():
    """New / matched / stale bookkeeping on a synthetic baseline."""
    res = lint_paths([FIXTURES / "bad_gl001.py"])
    from collections import Counter
    from replicatinggpt_tpu.analysis import finding_key
    base = Counter(finding_key(f) for f in res.findings)
    exact = diff_against_baseline(res.findings, base)
    assert exact.exact and exact.matched == len(res.findings)
    # drop one entry -> that finding is NEW; add a bogus one -> stale
    k = finding_key(res.findings[0])
    short = base - Counter([k])
    short[("x.py", "GL001", "nope")] += 1
    diff = diff_against_baseline(res.findings, short)
    assert len(diff.new) == 1 and not diff.exact
    assert ("x.py", "GL001", "nope") in diff.stale
