"""graftlint tests: every rule flags its bad fixture and passes its good
one, the interprocedural upgrades see across files (cross-module fixture
packages), both pragma forms suppress, the committed baseline exactly
matches a fresh whole-project run (the tier-1 CI gate), the baseline
ratchet refuses growth, SARIF output has the 2.1.0 shape, and the
generated rule docs cannot drift from the registry."""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from replicatinggpt_tpu.analysis import (DEFAULT_BASELINE, DEFAULT_SEVERITY,
                                         RULES, check_ratchet,
                                         diff_against_baseline, finding_key,
                                         lint_paths, lint_source,
                                         load_baseline, render_rule_docs,
                                         severity_for, write_baseline)
from replicatinggpt_tpu.analysis.rules import Finding

FIXTURES = Path(__file__).parent / "fixtures" / "lint"
REPO = Path(__file__).resolve().parent.parent

RULE_IDS = sorted(RULES)

#: fixtures live under tests/, which the default severity map demotes to
#: warnings — fixture assertions disable the tiering to stay meaningful
NO_TIERS = {}


def test_registry_shape():
    assert len(RULES) >= 24     # v1 + mesh family + protocol family
    for rid, rule in RULES.items():
        assert rid == rule.id and rid.startswith("GL") and len(rid) == 5
        assert rule.name and rule.rationale and rule.bad and rule.good
        assert callable(rule.checker) or callable(rule.project_checker)
    for rid in ("GL010", "GL011", "GL012", "GL013", "GL014"):
        assert rid in RULES                   # the sharding/mesh family
    for rid in ("GL018", "GL019", "GL020", "GL021", "GL022", "GL023",
                "GL024"):
        assert rid in RULES                   # the protocol/async family


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_bad_fixture_flagged(rule_id):
    """Each rule must flag its known-bad snippet (run with only that
    rule active, so the assertion is about THIS rule's detector)."""
    path = FIXTURES / f"bad_{rule_id.lower()}.py"
    res = lint_paths([path], [rule_id], severity=NO_TIERS)
    assert res.findings, f"{rule_id} missed its bad fixture"
    assert {f.rule for f in res.findings} == {rule_id}
    for f in res.findings:
        assert f.line > 0 and f.text            # anchored + baselineable


@pytest.mark.parametrize("rule_id", RULE_IDS)
def test_good_fixture_clean(rule_id):
    """The matching clean snippet must pass ALL rules (fixtures are
    written to be globally clean, not just clean for their own rule)."""
    path = FIXTURES / f"good_{rule_id.lower()}.py"
    res = lint_paths([path], severity=NO_TIERS)
    assert res.findings == [], [f.format() for f in res.findings]


# ---------------------------------------------------------------------------
# interprocedural upgrades (the v2 tentpole)
# ---------------------------------------------------------------------------


def test_interprocedural_gl004_two_levels_cross_file():
    """Pinned acceptance fixture: a `.item()` two call levels (and two
    files) below the step loop is caught AT the loop's call site; the
    helper files themselves stay clean (the sync isn't in a loop
    there), and cadence-guarded / accumulate-then-sync variants stay
    silent."""
    res = lint_paths([FIXTURES / "xmod_gl004"], severity=NO_TIERS)
    assert [(f.path.rsplit("/", 1)[-1], f.rule) for f in res.findings] == \
        [("loop.py", "GL004")]
    (f,) = res.findings
    assert "log_metrics" in f.message and "item()" in f.message
    assert "leaf.py" in f.message               # the chain names the sink


def test_interprocedural_gl002_reexport():
    """Module-scope call into a wrapper whose body device-allocates:
    flagged at the import-time call site, not in the wrapper."""
    res = lint_paths([FIXTURES / "xmod_gl002"], severity=NO_TIERS)
    assert [(f.path.rsplit("/", 1)[-1], f.rule) for f in res.findings] == \
        [("consumer.py", "GL002")]
    assert "build_mask" in res.findings[0].message


def test_interprocedural_gl005_alias_read_after_donate():
    """Reading the donated buffer after the jitted call through a local
    alias is flagged; reading only the returned value is not."""
    res = lint_paths([FIXTURES / "xmod_gl005"], severity=NO_TIERS)
    assert [(f.path.rsplit("/", 1)[-1], f.rule) for f in res.findings] == \
        [("driver.py", "GL005")]
    assert "snapshot" in res.findings[0].message


def test_single_file_is_its_own_project():
    """lint_source runs the project pass over a one-file index, so a
    self-contained interprocedural hazard still fires."""
    src = ("def helper(m):\n"
           "    return m.item()\n"
           "def loop(step, s, bs):\n"
           "    for b in bs:\n"
           "        s, m = step(s, b)\n"
           "        helper(m)\n"
           "    return s\n")
    res = lint_source(src, "t.py")
    assert [f.rule for f in res.findings] == ["GL004"]
    assert res.findings[0].line == 6            # the call site in the loop


def test_loop_iterator_expression_is_not_loop_body():
    """`for b in helper():` evaluates the iterator ONCE — a sync inside
    helper is not a per-iteration stall. A call in an inner loop's
    iterator IS per-outer-iteration, and is flagged exactly once (no
    duplicate from the iterator being walked at two depths)."""
    once = ("def helper(xs):\n"
            "    return xs.item()\n"
            "def f(step, s, xs):\n"
            "    for b in helper(xs):\n"
            "        s = step(s, b)\n"
            "    return s\n")
    assert lint_source(once, "t.py").findings == []
    nested = ("def helper(a):\n"
              "    return a.item()\n"
              "def f(step, s, outer):\n"
              "    for a in outer:\n"
              "        for b in helper(a):\n"
              "            s = step(s, b)\n"
              "    return s\n")
    res = lint_source(nested, "t.py")
    assert [f.rule for f in res.findings] == ["GL004"]   # once, not twice


def test_gl010_nested_def_scope_does_not_leak():
    """A mesh built inside a nested def must not shadow (or be checked
    against) the enclosing function's mesh."""
    src = ("from jax.sharding import Mesh, NamedSharding, "
           "PartitionSpec as P\n"
           "def outer(devs, devs2):\n"
           "    mesh = Mesh(devs, ('data',))\n"
           "    def inner():\n"
           "        mesh = Mesh(devs2, ('model',))\n"
           "        return NamedSharding(mesh, P('model'))\n"
           "    return NamedSharding(mesh, P('data')), inner\n")
    assert lint_source(src, "t.py").findings == []


def test_gl013_invariant_len_not_flagged():
    """len() of a container that is never mutated inside a loop is
    loop-invariant: one program, no recompile hazard — whether the
    container is a parameter or a name bound once BEFORE the loop."""
    src = ("from functools import partial\n"
           "import jax\n"
           "import jax.numpy as jnp\n"
           "@partial(jax.jit, static_argnames=('n',))\n"
           "def window(x, n):\n"
           "    return x[:n] * jnp.ones((n,))\n"
           "def f(x, vocab, steps):\n"
           "    outs = []\n"
           "    for _ in range(steps):\n"
           "        outs.append(window(x, len(vocab)))\n"
           "    return outs\n"
           "def g(x, steps):\n"
           "    vocab = sorted(set('abc'))\n"      # bound pre-loop: invariant
           "    for _ in range(steps):\n"
           "        x = window(x, len(vocab))\n"
           "    return x\n")
    assert lint_source(src, "t.py").findings == []


def test_gl014_caller_local_sharing_global_name_not_flagged():
    """A caller parameter that merely shares the captured global's name
    is a different binding — donating it is fine."""
    src = ("from functools import partial\n"
           "import jax\n"
           "import jax.numpy as jnp\n"
           "state = jnp.zeros((8,))  # graftlint: disable=GL002\n"
           "@partial(jax.jit, donate_argnames=('s',))\n"
           "def step(s):\n"
           "    return s + state\n"
           "def caller(state):\n"
           "    return step(state)\n")
    res = lint_source(src, "t.py", ["GL014"])
    assert res.findings == []
    # ...while the real capture-and-donate still fires
    bad = src.replace("def caller(state):\n    return step(state)",
                      "def caller():\n    return step(state)")
    res = lint_source(bad, "t.py", ["GL014"])
    assert [f.rule for f in res.findings] == ["GL014"]


def test_cli_write_baseline_rejects_changed_scope(tmp_path):
    """--write-baseline from a --changed view would silently drop every
    entry in unchanged files; the combination is refused."""
    from replicatinggpt_tpu.cli import main
    assert main(["lint", "--baseline", str(tmp_path / "b.json"),
                 "--write-baseline", "--changed", "HEAD"]) == 2
    assert not (tmp_path / "b.json").exists()


def test_cli_write_committed_baseline_rejects_path_scope():
    """Writing the COMMITTED baseline from a path-restricted lint would
    drop every entry outside those paths (and pass the ratchet, since
    the set only shrinks) — refused. A custom --baseline PATH may still
    scope freely (exercised in test_cli_write_baseline_ratchets)."""
    from replicatinggpt_tpu.analysis import DEFAULT_BASELINE
    from replicatinggpt_tpu.cli import main
    before = DEFAULT_BASELINE.read_text()
    assert main(["lint", "--write-baseline",
                 "replicatinggpt_tpu/analysis"]) == 2
    assert DEFAULT_BASELINE.read_text() == before


def test_gl010_local_mesh_shadowing_not_checked():
    """A function parameter (or non-Mesh local rebind) sharing a module
    mesh's name is a DIFFERENT, unknown mesh — its specs are exempt."""
    src = ("import numpy as np\n"
           "from jax.sharding import Mesh, NamedSharding, "
           "PartitionSpec as P\n"
           "DEVS = [0]\n"
           "mesh = Mesh(np.asarray(DEVS), ('data',))\n"
           "def from_param(mesh, batch):\n"
           "    return NamedSharding(mesh, P('model'))\n"
           "def from_rebind(cfg):\n"
           "    mesh = cfg.build_mesh()\n"
           "    return NamedSharding(mesh, P('model'))\n"
           "def from_module():\n"
           "    return NamedSharding(mesh, P('model'))\n")
    res = lint_source(src, "t.py", ["GL010"])
    # only from_module (the function actually using the module mesh)
    # fires — its return is source line 11
    assert [f.line for f in res.findings] == [11]


def test_transitive_search_not_poisoned_by_depth_limit():
    """A deep chain truncated at the depth limit must not cache
    'no sync' for its tail — a later, shallower query through the same
    tail still finds the real chain (results must not depend on the
    order functions are analyzed)."""
    chain = "def f0(m):\n    return f1(m)\n"
    for i in range(1, 5):
        chain += f"def f{i}(m):\n    return f{i + 1}(m)\n"
    chain += "def f5(m):\n    return m.item()\n"
    src = (chain
           + "def long_loop(step, s, bs):\n"
             "    for b in bs:\n"
             "        s, m = step(s, b)\n"
             "        f0(m)\n"                 # 6 hops: beyond the limit
             "    return s\n"
             "def short_loop(step, s, bs):\n"
             "    for b in bs:\n"
             "        s, m = step(s, b)\n"
             "        f4(m)\n"                 # 2 hops: must still fire
             "    return s\n")
    res = lint_source(src, "t.py", ["GL004"])
    # exactly one finding: the f4(m) call in short_loop (line 21); the
    # 6-hop f0 chain is beyond the depth limit and must stay silent
    # without poisoning f4's memo entry
    assert [f.line for f in res.findings] == [21]
    # and with the query order reversed the answer is identical
    flipped = src.replace("long_loop", "zz_loop")
    res2 = lint_source(flipped, "t.py", ["GL004"])
    assert len(res2.findings) == 1


def test_gl014_fires_at_module_scope():
    """The rule's own documented bad example: module-scope donation of
    the captured global must fire (module 'locals' ARE the globals)."""
    src = ("from functools import partial\n"
           "import jax\n"
           "import jax.numpy as jnp\n"
           "state = jnp.zeros((8,))  # graftlint: disable=GL002\n"
           "@partial(jax.jit, donate_argnames=('s',))\n"
           "def step(s):\n"
           "    return s + state\n"
           "out = step(state)\n")
    res = lint_source(src, "t.py", ["GL014"])
    assert [f.rule for f in res.findings] == ["GL014"]


def test_conditional_sync_inside_helper_does_not_propagate():
    """The conditional-sync exemption applies at the SYNC side too: a
    cadence-guarded float() inside the helper is intentional, so an
    unconditional call to that helper from a loop stays clean."""
    src = ("def helper(x, step):\n"
           "    if step % 100 == 0:\n"
           "        print(float(x))\n"
           "def loop(step_fn, s, bs):\n"
           "    for i, b in enumerate(bs):\n"
           "        s, m = step_fn(s, b)\n"
           "        helper(m, i)\n"
           "    return s\n")
    assert lint_source(src, "t.py", ["GL004"]).findings == []


def test_duplicate_targets_lint_once():
    """Overlapping explicit targets (dir + file inside it, a file
    twice) must not inflate finding counts."""
    bad = FIXTURES / "bad_gl001.py"
    once = lint_paths([bad], severity=NO_TIERS)
    twice = lint_paths([bad, bad, FIXTURES], severity=NO_TIERS)
    per_file = [f for f in twice.findings
                if f.path.endswith("bad_gl001.py")]
    assert len(per_file) == len(once.findings)


def test_gl005_augassign_reads_donated_buffer():
    """`state += 1` after donating state READS the freed buffer even
    though the AST target carries Store ctx."""
    src = ("from functools import partial\n"
           "import jax\n"
           "@partial(jax.jit, donate_argnames=('state',))\n"
           "def step(state, batch):\n"
           "    return state\n"
           "def f(state, batch):\n"
           "    out = step(state, batch)\n"
           "    state += 1\n"
           "    return out, state\n")
    res = lint_source(src, "t.py", ["GL005"])
    assert [f.line for f in res.findings] == [8]


def test_gl005_terminal_else_branch_does_not_leak():
    """A donation inside an else-branch that returns never reaches the
    fall-through code — the read after the If is only on the
    non-donating path."""
    src = ("from functools import partial\n"
           "import jax\n"
           "@partial(jax.jit, donate_argnames=('state',))\n"
           "def train_step(state, batch):\n"
           "    return state\n"
           "def f(state, batch, cond):\n"
           "    if cond:\n"
           "        out = batch\n"
           "    else:\n"
           "        return train_step(state, batch)\n"
           "    return out, state.mean()\n")
    assert lint_source(src, "t.py", ["GL005"]).findings == []


def test_gl010_mesh_rebind_is_unknown():
    """Rebinding a mesh name (flow-insensitive analysis) makes it
    unknown — neither construction's axes may be checked against
    either spec."""
    src = ("import numpy as np\n"
           "from jax.sharding import Mesh, NamedSharding, "
           "PartitionSpec as P\n"
           "def f(devs):\n"
           "    mesh = Mesh(np.asarray(devs), ('data',))\n"
           "    s1 = NamedSharding(mesh, P('data'))\n"
           "    mesh = Mesh(np.asarray(devs), ('model',))\n"
           "    s2 = NamedSharding(mesh, P('model'))\n"
           "    return s1, s2\n")
    assert lint_source(src, "t.py", ["GL010"]).findings == []
    # consistent rebinding stays known: a genuine mismatch still fires
    same = src.replace("('model',)", "('data',)").replace("P('model')",
                                                          "P('bogus')")
    assert [f.rule for f in lint_source(same, "t.py", ["GL010"]).findings] \
        == ["GL010"]


def test_lint_changed_wrapper_survives_symlink(tmp_path):
    """Installed as a .git/hooks symlink, the wrapper must still cd to
    the real repo root (dirname of the symlink is .git/hooks)."""
    import subprocess
    link = tmp_path / "pre-push"
    link.symlink_to(REPO / "tools" / "lint_changed.sh")
    proc = subprocess.run([str(link), "HEAD"], capture_output=True,
                          text=True, timeout=120, cwd=tmp_path)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # a mistyped single-argument ref fails loudly (matching the CLI's
    # --changed behavior) instead of silently linting the default base
    typo = subprocess.run([str(link), "orgin/main"], capture_output=True,
                          text=True, timeout=120, cwd=tmp_path)
    assert typo.returncode != 0 and "does not resolve" in typo.stderr


def test_gl012_static_args_excluded_from_arity():
    """in_shardings zips against DYNAMIC args only — a static param
    doesn't count toward the expected spec arity."""
    src = ("from functools import partial\n"
           "import jax\n"
           "@partial(jax.jit, static_argnames=('n',),\n"
           "         in_shardings=(None,))\n"
           "def f(x, n):\n"
           "    return x[:n]\n")
    assert lint_source(src, "t.py", ["GL012"]).findings == []
    # ...but a genuinely short tuple still fires
    bad = src.replace("def f(x, n):", "def f(x, y, n):")
    assert [f.rule for f in lint_source(bad, "t.py", ["GL012"]).findings] \
        == ["GL012"]


def test_pragma_at_sync_site_stops_propagation():
    """A reviewed pragma on the sync line also blesses every caller —
    summaries drop pragma-suppressed sites before propagation."""
    src = ("def helper(m):\n"
           "    return m.item()  # graftlint: disable=GL004\n"
           "def loop(step, s, bs):\n"
           "    for b in bs:\n"
           "        s, m = step(s, b)\n"
           "        helper(m)\n"
           "    return s\n")
    res = lint_source(src, "t.py")
    assert res.findings == []


# ---------------------------------------------------------------------------
# pragmas / severity tiers
# ---------------------------------------------------------------------------


def test_line_pragma_suppresses():
    res = lint_paths([FIXTURES / "suppressed_line.py"], severity=NO_TIERS)
    assert res.findings == []
    assert [f.rule for f in res.suppressed] == ["GL004"]


def test_file_pragma_suppresses():
    res = lint_paths([FIXTURES / "suppressed_file.py"], severity=NO_TIERS)
    assert res.findings == []
    assert {f.rule for f in res.suppressed} == {"GL004"}


def test_pragma_only_masks_named_rule():
    src = ("import numpy as np\n"
           "def f(xs):\n"
           "    for x in xs:\n"
           "        np.asarray(x)  # graftlint: disable=GL001\n")
    res = lint_source(src, "t.py")
    assert [f.rule for f in res.findings] == ["GL004"]   # wrong id: no-op


def test_syntax_error_reported_not_raised():
    res = lint_source("def broken(:\n", "t.py")
    assert [f.rule for f in res.findings] == ["GL000"]


def test_severity_tiers_demote_tests_to_warnings():
    """The same hazard is an error in the package and a warning under
    tests/ — reported, never gating, never baselined."""
    src = ("import numpy as np\n"
           "def f(xs):\n"
           "    for x in xs:\n"
           "        np.asarray(x)\n")
    pkg = lint_source(src, "replicatinggpt_tpu/somewhere.py")
    assert [f.rule for f in pkg.findings] == ["GL004"] and not pkg.warnings
    tst = lint_source(src, "tests/test_somewhere.py")
    assert not tst.findings
    assert [f.rule for f in tst.warnings] == ["GL004"]
    assert tst.warnings[0].severity == "warning"
    # the knob: longest prefix wins, overridable per directory
    assert severity_for("tests/x.py", DEFAULT_SEVERITY) == "warning"
    assert severity_for("bench.py", DEFAULT_SEVERITY) == "error"
    custom = {"tests/": "warning", "tests/perf/": "error"}
    assert severity_for("tests/perf/x.py", custom) == "error"


def test_default_discovery_covers_bench_tools_tests():
    """bench.py, tools/ and tests/ no longer escape the rules (tests at
    warning tier); intentional fixture trees are pruned from discovery."""
    res = lint_paths([])
    labels = {f.path for f in (*res.findings, *res.warnings)}
    assert any(p.startswith("tests/") for p in labels)
    assert not any("fixtures" in p for p in labels)
    assert all(f.path.startswith("tests/") for f in res.warnings)
    assert not any(f.path.startswith("tests/") for f in res.findings)


# ---------------------------------------------------------------------------
# baseline: exact gate, set semantics, dedupe, ratchet
# ---------------------------------------------------------------------------


def test_baseline_matches_fresh_whole_project_run():
    """The committed graftlint_baseline.json must EXACTLY equal a fresh
    run over the project: a new finding fails CI, and a fixed finding
    must be removed from the baseline (no silent staleness in either
    direction). Refresh with `python -m replicatinggpt_tpu lint
    --write-baseline`."""
    res = lint_paths([])                      # default: the whole project
    diff = diff_against_baseline(res.findings,
                                 load_baseline(DEFAULT_BASELINE))
    assert diff.exact, {
        "new": [f.format() for f in diff.new],
        "stale": diff.stale,
    }


def test_baseline_writer_dedupes_and_sorts(tmp_path):
    """Two findings with one key become ONE entry (the v1 duplicate-entry
    bug), and entries come out stably sorted so baseline diffs review as
    plain add/remove lines."""
    mk = lambda path, rule, line, text: Finding(  # noqa: E731
        path=path, rule=rule, line=line, col=0, message="m", text=text)
    findings = [mk("b.py", "GL004", 9, "x = f()"),
                mk("a.py", "GL004", 5, "y = g()"),
                mk("b.py", "GL004", 9, "x = f()"),     # duplicate key
                mk("a.py", "GL003", 2, "k = h()")]
    out = tmp_path / "base.json"
    n = write_baseline(findings, out)
    data = json.loads(out.read_text())
    assert n == 3 and len(data["findings"]) == 3
    keys = [(e["path"], e["line"], e["rule"]) for e in data["findings"]]
    assert keys == sorted(keys)
    # one deduped entry still absorbs BOTH same-key findings on re-lint
    diff = diff_against_baseline(findings, load_baseline(out))
    assert diff.exact and diff.matched == 4


def test_baseline_ratchet_refuses_growth(tmp_path):
    mk = lambda text: Finding(path="p.py", rule="GL004", line=1,  # noqa: E731
                              col=0, message="m", text=text)
    committed = tmp_path / "base.json"
    write_baseline([mk("old")], committed)
    assert check_ratchet([mk("old")], committed) == []           # hold
    assert check_ratchet([], committed) == []                    # shrink
    grown = check_ratchet([mk("old"), mk("NEW")], committed)     # grow
    assert grown == [("p.py", "GL004", "NEW")]
    assert check_ratchet([mk("x")], tmp_path / "absent.json") == []


def test_cli_write_baseline_ratchets(tmp_path):
    """`--write-baseline` exits non-zero (and leaves the file alone)
    when the refresh would add an entry; --allow-growth overrides."""
    from replicatinggpt_tpu.cli import main
    base = tmp_path / "base.json"
    bad = FIXTURES / "bad_gl006.py"
    ok = FIXTURES / "good_gl006.py"
    sev = ["--severity", "tests/=error"]
    assert main(["lint", "--baseline", str(base), "--write-baseline",
                 str(ok)] + sev) == 0          # bootstrap: empty baseline
    before = base.read_text()
    assert main(["lint", "--baseline", str(base), "--write-baseline",
                 str(bad)] + sev) == 2         # would grow: refused
    assert base.read_text() == before
    assert main(["lint", "--baseline", str(base), "--write-baseline",
                 "--allow-growth", str(bad)] + sev) == 0
    assert json.loads(base.read_text())["findings"]


def test_baseline_diff_mechanics():
    """New / matched / stale bookkeeping on a synthetic baseline (set
    semantics: one key absorbs all findings with that key)."""
    res = lint_paths([FIXTURES / "bad_gl001.py"], severity=NO_TIERS)
    base = {finding_key(f) for f in res.findings}
    exact = diff_against_baseline(res.findings, base)
    assert exact.exact and exact.matched == len(res.findings)
    # drop one entry -> that finding is NEW; add a bogus one -> stale
    k = finding_key(res.findings[0])
    short = (base - {k}) | {("x.py", "GL001", "nope")}
    diff = diff_against_baseline(res.findings, short)
    assert len(diff.new) == 1 and not diff.exact
    assert ("x.py", "GL001", "nope") in diff.stale


# ---------------------------------------------------------------------------
# CLI: gate, json, sarif, --changed
# ---------------------------------------------------------------------------


def test_cli_gate_in_process():
    from replicatinggpt_tpu.cli import main
    assert main(["lint", "--baseline"]) == 0


def test_cli_gate_subprocess():
    """The exact tier-1 invocation: `python -m replicatinggpt_tpu lint
    --baseline` exits 0 against the committed baseline."""
    proc = subprocess.run(
        [sys.executable, "-m", "replicatinggpt_tpu", "lint", "--baseline"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_fails_on_new_finding():
    from replicatinggpt_tpu.cli import main
    bad = FIXTURES / "bad_gl004.py"
    sev = ["--severity", "tests/=error"]
    assert main(["lint", str(bad)] + sev) == 1
    assert main(["lint", "--baseline", str(DEFAULT_BASELINE),
                 str(bad)] + sev) == 1        # fixtures aren't baselined


def test_cli_json_reflects_baseline_diff(capsys):
    """Under --baseline, the JSON payload must agree with the exit
    code: `findings` holds only NEW hazards (empty on a clean tree),
    absorbed ones appear as a `baselined` count, warnings ride along
    without gating."""
    from replicatinggpt_tpu.cli import main
    rc = main(["lint", "--baseline", "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["findings"] == []
    assert out["baselined"] > 0 and out["stale"] == []
    assert all(w["path"].startswith("tests/") for w in out["warnings"])


def test_cli_json_format(capsys):
    from replicatinggpt_tpu.cli import main
    rc = main(["lint", "--format", "json", "--severity", "tests/=error",
               str(FIXTURES / "bad_gl006.py")])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["files"] == 1
    assert all(f["rule"] == "GL006" for f in out["findings"])
    assert len(out["findings"]) >= 2          # both dus spellings


def test_cli_sarif_shape(capsys):
    """`--format sarif` emits the SARIF 2.1.0 shape: version, one run
    with a tool.driver carrying the full rule table, and results whose
    locations use physicalLocation/artifactLocation/region."""
    from replicatinggpt_tpu.cli import main
    rc = main(["lint", "--format", "sarif", "--severity", "tests/=error",
               "--no-baseline", str(FIXTURES / "bad_gl004.py")])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["version"] == "2.1.0" and "sarif" in doc["$schema"]
    (run,) = doc["runs"]
    driver = run["tool"]["driver"]
    assert driver["name"] == "graftlint"
    assert {r["id"] for r in driver["rules"]} == set(RULE_IDS)
    assert run["results"], "bad fixture must produce results"
    for r in run["results"]:
        assert r["ruleId"] in RULES and r["level"] in ("error", "warning")
        loc = r["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith(".py")
        assert loc["region"]["startLine"] >= 1
        assert loc["region"]["startColumn"] >= 1
        assert r["message"]["text"]
        # ruleIndex must agree with the driver rule table
        assert driver["rules"][r["ruleIndex"]]["id"] == r["ruleId"]


def test_cli_sarif_clean_under_baseline(capsys):
    from replicatinggpt_tpu.cli import main
    rc = main(["lint", "--baseline", "--format", "sarif"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    errors = [r for r in doc["runs"][0]["results"]
              if r["level"] == "error"]
    assert errors == []                       # baselined: no new errors


def test_cli_changed_mode(capsys):
    """--changed HEAD on a clean tree reports nothing; with a bogus ref
    it fails loudly rather than linting the wrong scope."""
    from replicatinggpt_tpu.cli import main
    rc = main(["lint", "--baseline", "--changed", "HEAD"])
    assert rc == 0
    capsys.readouterr()
    with pytest.raises(SystemExit):
        main(["lint", "--changed", "definitely-not-a-ref-xyz"])


def test_docs_generated_from_registry_in_sync():
    committed = (REPO / "docs" / "graftlint_rules.md").read_text()
    assert committed == render_rule_docs(), (
        "docs/graftlint_rules.md is stale — regenerate with "
        "`python -m replicatinggpt_tpu lint --docs > "
        "docs/graftlint_rules.md`")
    for rid in RULE_IDS:                      # every rule documented
        assert f"## {rid}" in committed


# ---------------------------------------------------------------------------
# protocol & async-concurrency family (GL018-GL023): contract registry
# round-trips and mutation coverage
# ---------------------------------------------------------------------------


def _lint_sources(files, rule_ids):
    """Lint (label, source) pairs as one multi-module project — the
    mutation tests below lint real-file text with one line changed."""
    from replicatinggpt_tpu.analysis.linter import _lint_files, _parse_file
    ctxs = [_parse_file(src, label) for label, src in files]
    return _lint_files(ctxs, rule_ids, severity=NO_TIERS)


def test_changed_files_rename_and_copy_entries():
    """--changed parses `git diff --name-status -M -C`: renames (R<score>)
    and copies (C<score>) contribute their NEW path — the one that exists
    in the working tree — not the old one, and non-.py entries drop."""
    from replicatinggpt_tpu.analysis.cli import _paths_from_name_status
    out = _paths_from_name_status(
        "M\treplicatinggpt_tpu/serve/router.py\n"
        "R097\treplicatinggpt_tpu/serve/old_name.py\t"
        "replicatinggpt_tpu/serve/new_name.py\n"
        "C075\ttools/base.py\ttools/base_copy.py\n"
        "A\ttools/brand_new.py\n"
        "M\tREADME.md\n"
        "D\tgone.py\n")
    assert out == {"replicatinggpt_tpu/serve/router.py",
                   "replicatinggpt_tpu/serve/new_name.py",
                   "tools/base_copy.py", "tools/brand_new.py",
                   "gone.py"}


_GL022_ROUND_TRIP = '''ENGINE_FORWARD_FLAGS = (
    ("pool_size", "--pool-size"),{extra}
)
ENGINE_FORWARD_SWITCHES = ()


class EngineConfig:
    pool_size: int = 8
    new_knob: int = 0


def engine_config_from_args(args):
    return EngineConfig(pool_size=args.pool_size,
                        new_knob=args.new_knob)
'''


def test_gl022_registry_round_trip_synthetic_field():
    """A synthetic EngineConfig field built from args trips GL022 until
    its (dest, flag) pair lands in ENGINE_FORWARD_FLAGS."""
    bad = _GL022_ROUND_TRIP.format(extra="")
    res = lint_source(bad, "t.py", ["GL022"], severity=NO_TIERS)
    assert len(res.findings) == 1, [f.format() for f in res.findings]
    assert "new_knob" in res.findings[0].message
    good = _GL022_ROUND_TRIP.format(
        extra='\n    ("new_knob", "--new-knob"),')
    res = lint_source(good, "t.py", ["GL022"], severity=NO_TIERS)
    assert res.findings == [], [f.format() for f in res.findings]


_GL018_ROUND_TRIP = '''class Worker:
    def dispatch(self, op, doc):
        return getattr(self, "op_" + op)(doc)

    def op_submit(self, doc):
        req = doc["req"]
        return {{"accepted": bool(req)}}


class Client:
    def call(self, op, **kw):
        return {{}}

    def submit(self, payload):
        resp = self.call("submit", {sent}timeout_s=1.0)
        return resp["accepted"]
'''


def test_gl018_registry_round_trip_synthetic_verb():
    """A call site that omits a key the handler reads unconditionally
    trips GL018; sending the key makes the verb contract whole."""
    bad = _GL018_ROUND_TRIP.format(sent="")
    res = lint_source(bad, "t.py", ["GL018"], severity=NO_TIERS)
    assert len(res.findings) == 1, [f.format() for f in res.findings]
    assert "req" in res.findings[0].message
    good = _GL018_ROUND_TRIP.format(sent="req=payload, ")
    res = lint_source(good, "t.py", ["GL018"], severity=NO_TIERS)
    assert res.findings == [], [f.format() for f in res.findings]


# --- mutation coverage: each contract break produces EXACTLY ONE new
# finding against the real project files (the acceptance criterion) ----


def test_mutation_codec_key_drop_fires_exactly_one_gl018():
    """Deleting one key from serve/rpc.py's result_to_wire leaves
    result_from_wire reading a key the writer never sends: exactly one
    new GL018."""
    rel = "replicatinggpt_tpu/serve/rpc.py"
    src = (REPO / rel).read_text()
    assert lint_source(src, rel, ["GL018"], severity=NO_TIERS).findings \
        == []
    needle = '"queue_wait_s": res.queue_wait_s, '
    assert needle in src
    res = lint_source(src.replace(needle, ""), rel, ["GL018"],
                      severity=NO_TIERS)
    assert len(res.findings) == 1, [f.format() for f in res.findings]
    assert "queue_wait_s" in res.findings[0].message
    assert "never writes" in res.findings[0].message


def test_mutation_forward_flag_drop_fires_exactly_one_gl022():
    """Deleting one whitelist row from cli.py's ENGINE_FORWARD_FLAGS
    orphans the builder keyword that reads it: exactly one new GL022."""
    rel = "replicatinggpt_tpu/cli.py"
    src = (REPO / rel).read_text()
    assert lint_source(src, rel, ["GL022"], severity=NO_TIERS).findings \
        == []
    needle = '    ("page_size", "--page-size"),\n'
    assert needle in src
    res = lint_source(src.replace(needle, ""), rel, ["GL022"],
                      severity=NO_TIERS)
    assert len(res.findings) == 1, [f.format() for f in res.findings]
    assert "page_size" in res.findings[0].message


def test_mutation_counter_pin_drop_fires_exactly_one_gl021():
    """Deleting one counter from the pinned Prometheus exposition
    leaves its real inc site unpinned: exactly one new GL021. The
    mini-project holds exactly the modules that increment pinned
    counters (the full project contains fully-dynamic ``inc(name)``
    sites that rightly disable the never-incremented direction)."""
    tel_rel = "replicatinggpt_tpu/utils/telemetry.py"
    others = ["replicatinggpt_tpu/serve/router.py",
              "replicatinggpt_tpu/serve/http.py",
              "replicatinggpt_tpu/faults/procsup.py"]
    tel_src = (REPO / tel_rel).read_text()
    files = [(rel, (REPO / rel).read_text()) for rel in others]
    res = _lint_sources([(tel_rel, tel_src)] + files, ["GL021"])
    assert res.findings == [], [f.format() for f in res.findings]
    needle = '"fleet_drains", '
    assert needle in tel_src
    res = _lint_sources([(tel_rel, tel_src.replace(needle, ""))] + files,
                        ["GL021"])
    assert len(res.findings) == 1, [f.format() for f in res.findings]
    assert "fleet_drains" in res.findings[0].message
    assert res.findings[0].path.endswith("router.py")


def test_mutation_idempotent_verb_drop_fires_exactly_one_gl024():
    """Deleting one verb from serve/worker.py's IDEMPOTENT_VERBS leaves
    a mutating handler whose replies are never cached: exactly one new
    GL024 (the dispatch class itself still consults the cache, and a
    single-file lint has no call sites — only the membership check can
    fire)."""
    rel = "replicatinggpt_tpu/serve/worker.py"
    src = (REPO / rel).read_text()
    assert lint_source(src, rel, ["GL024"], severity=NO_TIERS).findings \
        == []
    needle = '"page_transfer", '
    assert needle in src
    res = lint_source(src.replace(needle, ""), rel, ["GL024"],
                      severity=NO_TIERS)
    assert len(res.findings) == 1, [f.format() for f in res.findings]
    assert "page_transfer" in res.findings[0].message
    assert "IDEMPOTENT" in res.findings[0].message


def test_mutation_unkeyed_mutating_call_site_fires_gl024():
    """Stripping the explicit idem key from router.py's submit call
    site leaves a mutating verb crossing the wire unkeyed (statically):
    GL024 flags the call site. Linted as a two-module project so the
    worker-side dispatch class arms the rule."""
    worker_rel = "replicatinggpt_tpu/serve/worker.py"
    router_rel = "replicatinggpt_tpu/serve/router.py"
    worker_src = (REPO / worker_rel).read_text()
    router_src = (REPO / router_rel).read_text()
    res = _lint_sources([(worker_rel, worker_src),
                         (router_rel, router_src)], ["GL024"])
    assert res.findings == [], [f.format() for f in res.findings]
    needle = 'idem=self._next_idem("submit"),\n'
    assert needle in router_src
    res = _lint_sources(
        [(worker_rel, worker_src),
         (router_rel, router_src.replace(needle, ""))], ["GL024"])
    assert [f.rule for f in res.findings] == ["GL024"], \
        [f.format() for f in res.findings]
    assert "submit" in res.findings[0].message
    assert res.findings[0].path.endswith("router.py")
