"""Multi-host elastic fleet tests (ISSUE 14): RPC registration with
protocol/shape validation, journal streaming over the reconcile RPC,
the router-side request ledger (torn-tail replay pinned), the
autoscaling supervisor, and ``host_loss`` chaos — the worker's machine
vanishes, journal and all, and every accepted request still finishes
exactly once.

Fast tier: protocol units over stub routers, the journal_drain frame
contract, the router-ledger torn-tail pin (in-process replicas), the
autoscale decision logic, host_loss mechanics, load-step arrivals.
Slow tier (``-m "multiproc and slow"``): the 4-worker fully-isolated
host-loss chaos soak and the autoscaler load-step soak — the ISSUE 14
acceptance criteria, end to end over real worker processes."""

import json
import pathlib
import sys
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from replicatinggpt_tpu.config import get_config
from replicatinggpt_tpu.faults import Fault, FaultPlan, installed
from replicatinggpt_tpu.faults.fleet import FLEET_STEP, KIND_HOST_LOSS
from replicatinggpt_tpu.faults.procsup import (AutoscaleConfig,
                                               ProcSupervisor, RETIRED,
                                               RUNNING, SPAWNING,
                                               SupervisorConfig,
                                               WorkerSpec,
                                               make_worker_specs,
                                               spawn_fleet,
                                               worker_spec_factory)
from replicatinggpt_tpu.serve import (EngineConfig, RequestJournal,
                                      RouterConfig)
from replicatinggpt_tpu.serve.journal import JournalBusyError
from replicatinggpt_tpu.serve.loadgen import (SessionLoadConfig,
                                              make_sessions,
                                              run_fleet_replay)
from replicatinggpt_tpu.serve.requests import Request, SamplingParams
from replicatinggpt_tpu.serve.rpc import (PROTO_VERSION, RpcClient,
                                          RpcListener, RpcProtocolError,
                                          engine_shape_hash)
from replicatinggpt_tpu.serve.worker import WorkerServer

pytestmark = [pytest.mark.fleet, pytest.mark.multiproc]

REPO = pathlib.Path(__file__).resolve().parents[1]
CFG = get_config("test-tiny").model


def _offline(prompt, n):
    import jax

    from replicatinggpt_tpu.sample import GenerateConfig, generate
    from replicatinggpt_tpu.train.state import create_train_state
    tcfg = get_config("test-tiny")
    state = create_train_state(jax.random.PRNGKey(tcfg.train.seed),
                               tcfg.model, tcfg.train)
    return np.asarray(generate(
        state.params, np.asarray(prompt, np.int32)[None, :], tcfg.model,
        GenerateConfig(max_new_tokens=n, greedy=True)))[0].tolist()


def _reqs(n, seed=7, max_new=8, prompt_len=4):
    rng = np.random.default_rng(seed)
    return [Request(
        id=f"e{seed}_{i}",
        prompt=rng.integers(1, CFG.vocab_size - 1,
                            (prompt_len,)).astype(np.int32),
        max_new_tokens=max_new, sampling=SamplingParams(greedy=True),
        rng_seed=seed * 1000 + i) for i in range(n)]


# ---------------------------------------------------------------------------
# registration handshake units (stub router, no subprocess)
# ---------------------------------------------------------------------------

class _RegStubRouter:
    """Records attach/add calls; enough surface for _handle_register."""

    def __init__(self, n):
        self.replicas = [SimpleNamespace(restarts=0) for _ in range(n)]
        self.rcfg = SimpleNamespace(step_timeout_s=5.0)
        self.supervisor = None
        self.attached = []
        self.added = []
        from replicatinggpt_tpu.utils.telemetry import NULL
        self.tel = NULL

    def attach_replica(self, idx, port, pid=None, gen=None, host=None,
                       tier=None, page_size=None):
        self.attached.append((idx, port, pid, gen, host))
        return {"kept": 0, "requeued": 0, "ghosts": 0}

    def add_replica(self, rep):
        self.added.append(rep.idx)
        self.replicas.append(rep)
        return rep.idx

    def _event(self, msg):
        pass


def _reg_doc(**over):
    doc = {"proto": PROTO_VERSION, "shape_hash": "abc",
           "worker_idx": 0, "gen": 0, "port": 1234, "pid": 42,
           "replayed": 0}
    doc.update(over)
    return doc


def test_registration_attaches_and_pins_shape(tmp_path):
    """A valid register frame attaches the router (pid/gen/peer-host
    flow over the wire); the FIRST registration pins the fleet's
    engine-shape hash, and every later worker must match it."""
    sup = ProcSupervisor([WorkerSpec(
        idx=0, cmd=[], journal_path=str(tmp_path / "j.jsonl"))])
    router = _RegStubRouter(1)
    sup.attach_router(router)
    try:
        sup.handles[0].gen = 0
        resp = sup._handle_register(_reg_doc(), "10.1.2.3")
        assert resp["idx"] == 0
        assert router.attached == [(0, 1234, 42, 0, "10.1.2.3")]
        assert sup.handles[0].state == RUNNING
        assert sup.expect_shape_hash == "abc"      # pinned
        # a second worker with a DIFFERENT shape is rejected typed
        with pytest.raises(RpcProtocolError, match="shape"):
            sup._handle_register(_reg_doc(shape_hash="zzz"), "h")
        # wrong protocol version: typed rejection too
        with pytest.raises(RpcProtocolError, match="protocol"):
            sup._handle_register(_reg_doc(proto=PROTO_VERSION + 1),
                                 "h")
        # a stale generation (pre-restart straggler) never attaches
        sup.handles[0].gen = 1
        with pytest.raises(ValueError, match="stale generation"):
            sup._handle_register(_reg_doc(gen=0), "h")
    finally:
        sup.stop_all()


def test_registration_expected_shape_from_config(tmp_path):
    """SupervisorConfig.expect_shape_hash pre-pins the fleet shape:
    the first worker is held to it too (no first-wins window)."""
    sup = ProcSupervisor(
        [WorkerSpec(idx=0, cmd=[], journal_path=str(tmp_path / "j"))],
        SupervisorConfig(expect_shape_hash="pinned"))
    sup.attach_router(_RegStubRouter(1))
    try:
        sup.handles[0].gen = 0
        with pytest.raises(RpcProtocolError, match="shape"):
            sup._handle_register(_reg_doc(shape_hash="abc"), "h")
        sup._handle_register(_reg_doc(shape_hash="pinned"), "h")
        assert sup.handles[0].state == RUNNING
    finally:
        sup.stop_all()


def test_unmanaged_worker_joins_fleet(tmp_path):
    """worker_idx=-1: a worker the supervisor never spawned (another
    machine, another operator) registers and the fleet GROWS — a new
    replica slot, attach, recorded as external."""
    sup = ProcSupervisor([WorkerSpec(
        idx=0, cmd=[], journal_path=str(tmp_path / "j.jsonl"))])
    router = _RegStubRouter(1)
    sup.attach_router(router)
    try:
        resp = sup._handle_register(
            _reg_doc(worker_idx=-1, port=5555, pid=99), "10.9.9.9")
        assert resp["idx"] == 1
        assert router.added == [1]
        assert router.attached[-1] == (1, 5555, 99, 0, "10.9.9.9")
        assert sup.external == [1]
        # its shape pinned the fleet; a mismatched second joiner fails
        with pytest.raises(RpcProtocolError):
            sup._handle_register(
                _reg_doc(worker_idx=-1, shape_hash="other"), "h")
    finally:
        sup.stop_all()


def test_rpc_protocol_error_typed_over_wire():
    """The typed rejection crosses the wire: a listener handler
    raising RpcProtocolError answers kind="protocol", and the far
    client re-raises RpcProtocolError (terminal — no retry), not a
    generic RpcError."""
    lst = RpcListener()

    def handler(doc, peer):
        raise RpcProtocolError(f"worker speaks protocol "
                               f"v{doc.get('proto')}")

    result = {}

    def client():
        c = RpcClient("127.0.0.1", lst.port, timeout_s=5.0)
        try:
            c.call("register", proto=99, idem="reg.proto-test.0")
        except Exception as e:  # noqa: BLE001 — the assertion target
            result["exc"] = e
        finally:
            c.close()

    t = threading.Thread(target=client)
    t.start()
    deadline = time.monotonic() + 10
    while "exc" not in result and time.monotonic() < deadline:
        lst.poll(handler)
        time.sleep(0.01)
    t.join(10)
    lst.close()
    assert isinstance(result.get("exc"), RpcProtocolError)
    assert "protocol v99" in str(result["exc"])


def test_worker_reregisters_after_listener_restart():
    """ROADMAP 3a remainder, pinned: registration is no longer
    once-at-startup. A worker whose router has gone SILENT (no inbound
    RPC for the idle threshold) re-sends its register frame with
    bounded backoff — and keeps retrying through the window where the
    listener is DOWN entirely, so a restarted router's fresh listener
    on the same port re-attaches it without operator action."""
    import asyncio

    from replicatinggpt_tpu.serve import worker as worker_mod

    async def scenario():
        lst = RpcListener()
        port = lst.port
        got = []

        def handler(doc, peer):
            got.append(dict(doc))
            return {"idx": 0}

        w = SimpleNamespace(stop_event=asyncio.Event(),
                            last_contact=time.monotonic() - 100.0)
        rereg = []
        task = asyncio.ensure_future(worker_mod._reregister_loop(
            w, f"127.0.0.1:{port}",
            {"port": 1, "pid": 2, "gen": 1, "worker_idx": 0,
             "replayed": 0, "proto": PROTO_VERSION, "shape_hash": "x"},
            idle_s=0.2, backoff_s=0.05, backoff_cap_s=0.4,
            on_reregister=lambda: rereg.append(time.monotonic())))
        # phase 1: silence alone triggers a re-registration
        deadline = time.monotonic() + 10
        while not got and time.monotonic() < deadline:
            lst.poll(handler)
            await asyncio.sleep(0.02)
        assert got, "no re-registration despite router silence"
        assert got[0]["op"] == "register"
        assert got[0]["gen"] == 1
        # phase 2: the listener RESTARTS (close + rebind, same port);
        # attempts in the gap fail with ConnectionError and back off
        # (bounded), then the fresh listener gets a new register frame
        lst.close()
        w.last_contact = time.monotonic() - 100.0   # router still silent
        await asyncio.sleep(0.3)                     # a few dead attempts
        lst2 = RpcListener(port=port)
        n0 = len(got)
        deadline = time.monotonic() + 10
        while len(got) <= n0 and time.monotonic() < deadline:
            lst2.poll(handler)
            await asyncio.sleep(0.02)
            w.last_contact = min(w.last_contact,
                                 time.monotonic() - 100.0)
        lst2.close()
        assert len(got) > n0, \
            "no re-registration after the listener restarted"
        assert len(rereg) >= 2
        # a healthy router (recent contact) quiets the loop again
        w.last_contact = time.monotonic()
        n1 = len(got)
        await asyncio.sleep(0.3)
        assert len(got) == n1, "re-registered despite healthy traffic"
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass

    asyncio.run(scenario())


def test_engine_shape_hash_sensitivity():
    """The hash moves with anything that must agree fleet-wide (model
    arch, pool/page shape) and is stable across processes by
    construction (pure function of the configs)."""
    import dataclasses
    mcfg = get_config("test-tiny").model
    base = engine_shape_hash(mcfg, EngineConfig())
    assert base == engine_shape_hash(mcfg, EngineConfig())
    assert base != engine_shape_hash(
        dataclasses.replace(mcfg, n_layer=mcfg.n_layer + 1),
        EngineConfig())
    assert base != engine_shape_hash(mcfg, EngineConfig(pool_size=99))


# ---------------------------------------------------------------------------
# journal streaming (journal_drain frames)
# ---------------------------------------------------------------------------

class _NullEngine:
    """WorkerServer only needs the journal side here."""

    class cfg:
        vocab_size = CFG.vocab_size

    class scheduler:
        depth = 0

    n_steps = 0
    idle = True
    _active = np.zeros((1,), bool)

    class pool:
        class alloc:
            pages_in_use = prefix_hit_tokens = prompt_tokens = 0

    def in_flight_ids(self):
        return []


def test_journal_drain_bounded_frames(tmp_path):
    """journal_drain pages the condensed journal view in bounded
    frames: finish records as {id, reason}, unfinished requests as
    wire docs (eos included), cursor/eof contract honored."""
    path = str(tmp_path / "j.jsonl")
    j = RequestJournal(path)
    reqs = _reqs(5, seed=13)
    for q in reqs:
        j.record_submit(q)
    j.record_finish(reqs[0].id, "max_tokens")
    j.record_finish(reqs[1].id, "cancelled")
    j.close()
    journal = RequestJournal(path, lock=True)
    w = WorkerServer(_NullEngine(), journal=journal)
    # page with limit=2: 2 finished + 3 unfinished = 5 records
    records, cursor = [], 0
    for _ in range(10):
        resp = w.op_journal_drain({"cursor": cursor, "limit": 2})
        assert len(resp["records"]) <= 2
        records.extend(resp["records"])
        cursor = resp["cursor"]
        if resp["eof"]:
            break
    journal.close()
    finished = {r["id"]: r["reason"] for r in records
                if r["kind"] == "finished"}
    unfinished = [r["req"] for r in records if r["kind"] == "unfinished"]
    assert finished == {reqs[0].id: "max_tokens",
                        reqs[1].id: "cancelled"}
    assert [d["id"] for d in unfinished] == [q.id for q in reqs[2:]]
    # wire docs round-trip through the shared request codec
    assert unfinished[0]["prompt"] == reqs[2].prompt.tolist()
    # a journal-less worker drains empty + eof immediately
    w2 = WorkerServer(_NullEngine(), journal=None)
    resp = w2.op_journal_drain({})
    assert resp["records"] == [] and resp["eof"]


def test_journal_records_eos_token_id(tmp_path):
    """eos_token_id survives the journal round trip: a replayed
    request keeps its stop condition (token-identity across restarts
    requires it)."""
    path = str(tmp_path / "eos.jsonl")
    j = RequestJournal(path)
    q = Request(id="eos1", prompt=np.asarray([1, 2], np.int32),
                max_new_tokens=9, sampling=SamplingParams(greedy=True),
                rng_seed=3, eos_token_id=7)
    plain = _reqs(1, seed=15)[0]
    j.record_submit(q)
    j.record_submit(plain)
    j.close()
    back = {r.id: r for r in RequestJournal.unfinished(path)}
    assert back["eos1"].eos_token_id == 7
    assert back[plain.id].eos_token_id is None


# ---------------------------------------------------------------------------
# router-side request ledger (the torn-tail satellite pin)
# ---------------------------------------------------------------------------

def _params():
    import jax

    from replicatinggpt_tpu.models.gpt import init_params
    return init_params(jax.random.PRNGKey(0), CFG)


def test_router_ledger_records_submits_and_finishes(tmp_path):
    """With ledger_path set, the router journals one submit record at
    fleet acceptance and one finish record per terminal result — the
    same RequestJournal format the workers use."""
    from replicatinggpt_tpu.serve import Router
    ledger = str(tmp_path / "ledger.jsonl")
    r = Router(_params(), CFG,
               RouterConfig(n_replicas=1, ledger_path=ledger),
               EngineConfig(pool_size=2, max_queue=8))
    try:
        reqs = _reqs(2, seed=21, max_new=4)
        for q in reqs:
            assert r.submit(q) is None
        r.drain()
    finally:
        r.close()
    recs = [json.loads(ln) for ln in
            pathlib.Path(ledger).read_text().splitlines()]
    subs = [x["id"] for x in recs if x["ev"] == "submit"]
    fins = [x["id"] for x in recs if x["ev"] == "finish"]
    assert sorted(subs) == sorted(q.id for q in reqs)
    assert sorted(fins) == sorted(q.id for q in reqs)
    # recovery over a complete ledger finds nothing to requeue
    assert RequestJournal.unfinished(ledger) == []


def test_router_ledger_torn_finish_requeues_exactly_once(tmp_path):
    """THE satellite pin: a router crash mid-finish-record leaves a
    torn tail; the restarted router must requeue (not drop, not
    double-decode) the affected id. The torn-tail tolerance is the
    utils/jsonl contract: the torn line is skipped, so the id replays
    as unfinished and re-decodes deterministically — delivered once."""
    from replicatinggpt_tpu.serve import Router
    ledger = str(tmp_path / "ledger.jsonl")
    a, b = _reqs(2, seed=23, max_new=5)
    pre = RequestJournal(ledger)
    pre.record_submit(a)
    pre.record_submit(b)
    pre.record_finish(a.id, "max_tokens")
    pre.close()
    with open(ledger, "a") as f:            # the crash landed HERE
        f.write(json.dumps({"ev": "finish", "id": b.id,
                            "reason": "max_tokens"})[:17])
    r = Router(_params(), CFG,
               RouterConfig(n_replicas=2, ledger_path=ledger),
               EngineConfig(pool_size=2, max_queue=8))
    try:
        assert r.metrics.counters["fleet_ledger_recovered"] == 1
        # b is known fleet-wide while requeued: a duplicate client
        # retry is rejected, never double-decoded
        assert r.knows(b.id)
        dup = r.submit(Request(id=b.id, prompt=b.prompt,
                               max_new_tokens=5,
                               sampling=SamplingParams(greedy=True),
                               rng_seed=b.rng_seed))
        assert dup is not None and dup.finish_reason.startswith(
            "rejected")
        stream = []
        results = {}
        deadline = time.monotonic() + 60
        while not r.idle:
            assert time.monotonic() < deadline
            for res in r.step():
                results[res.id] = res
            stream.extend(r.take_new_tokens(b.id))
        # a finished long ago: NOT resurrected. b: exactly once.
        assert set(results) == {b.id}
        want = _offline(b.prompt, 5)
        assert results[b.id].tokens == want
        assert stream == want
        total_admitted = sum(
            rep.engine.metrics.counters.get("requests_admitted", 0)
            for rep in r.replicas)
        assert total_admitted == 1          # one decode, one replica
    finally:
        r.close()
    # the re-decode journaled its finish: recovery is now empty
    assert RequestJournal.unfinished(ledger) == []


def test_router_ledger_lock_excludes_second_router(tmp_path):
    from replicatinggpt_tpu.serve import Router
    ledger = str(tmp_path / "ledger.jsonl")
    r = Router(_params(), CFG,
               RouterConfig(n_replicas=1, ledger_path=ledger),
               EngineConfig(pool_size=2, max_queue=8))
    try:
        with pytest.raises(JournalBusyError):
            RequestJournal(ledger, lock=True)
    finally:
        r.close()


# ---------------------------------------------------------------------------
# elastic router surface + autoscale decision logic
# ---------------------------------------------------------------------------

def test_offered_load_and_add_replica():
    """offered_load aggregates the gauges the autoscaler reads;
    add_replica grows a remote fleet with the new slot NOT alive until
    its registration attaches."""
    from replicatinggpt_tpu.serve.router import RemoteReplica, Router
    r = Router(rcfg=RouterConfig(n_replicas=0), backends=[])
    try:
        load = r.offered_load()
        assert load == {"queued": 0, "active": 0, "n_routable": 0}
        idx = r.add_replica(RemoteReplica(0, None))
        assert idx == 0
        assert not r.replicas[0].alive          # not routable yet
        assert r.offered_load()["n_routable"] == 0
        assert r.metrics.counters["fleet_replicas_added"] == 1
        with pytest.raises(AssertionError, match="append-only"):
            r.add_replica(RemoteReplica(5, None))
    finally:
        r.close()


class _LoadStubRouter(_RegStubRouter):
    """offered_load is scripted; drain_replica recorded."""

    def __init__(self, n):
        super().__init__(n)
        self.load = {"queued": 0, "active": 0, "n_routable": n}
        self.drained = []
        self.metrics = SimpleNamespace(inc=lambda *a, **k: None)
        for rep in self.replicas:
            rep.client = None
            rep.alive = True

    def offered_load(self):
        return dict(self.load)

    def drain_replica(self, idx):
        self.drained.append(idx)
        return 0

    def mark_down(self, idx, reason=""):
        pass

    def abandon_replica(self, idx):
        pass


def test_autoscale_scales_up_on_sustained_backlog(tmp_path):
    """Backlog above up_backlog_per_worker x routable for up_patience
    ticks spawns ONE new worker (cooldown + SPAWNING gate further
    decisions); a momentary spike scales nothing."""
    router = _LoadStubRouter(1)
    sup = ProcSupervisor(
        [WorkerSpec(idx=0, cmd=[], journal_path=str(tmp_path / "j"))],
        SupervisorConfig(probe_every=0),
        autoscale=AutoscaleConfig(min_workers=1, max_workers=2,
                                  up_backlog_per_worker=2.0,
                                  up_patience=3, down_patience=4,
                                  cooldown_ticks=0),
        spec_factory=worker_spec_factory(
            str(tmp_path / "scale"), ["--preset", "test-tiny"]))
    sup.attach_router(router)
    spawned = []
    sup._spawn = lambda h: (spawned.append(h.spec.idx),
                            setattr(h, "state", SPAWNING))
    try:
        sup.handles[0].state = RUNNING
        # a one-tick spike: no action
        router.load = {"queued": 9, "active": 1, "n_routable": 1}
        sup._tick_autoscale()
        router.load = {"queued": 0, "active": 1, "n_routable": 1}
        sup._tick_autoscale()
        assert sup.scale_ups == 0 and sup._up_streak == 0
        # sustained backlog: scale-up at patience
        router.load = {"queued": 9, "active": 2, "n_routable": 1}
        for _ in range(3):
            sup._tick_autoscale()
        assert sup.scale_ups == 1 and spawned == [1]
        assert router.added == [1]             # fleet grew a slot
        assert sup.handles[-1].spec.idx == 1
        # SPAWNING gates any further decision
        for _ in range(5):
            sup._tick_autoscale()
        assert sup.scale_ups == 1
        # max_workers caps once the spawn lands
        sup.handles[-1].state = RUNNING
        for _ in range(5):
            sup._tick_autoscale()
        assert sup.scale_ups == 1
    finally:
        sup.stop_all()


def test_autoscale_scales_down_via_drain_and_retires(tmp_path):
    """A sustained lull drains the highest-index worker through the
    rolling-restart drain path; its exit is terminal (RETIRED), not a
    respawn — and min_workers floors the shrink."""
    router = _LoadStubRouter(2)
    sup = ProcSupervisor(
        [WorkerSpec(idx=i, cmd=[],
                    journal_path=str(tmp_path / f"j{i}"))
         for i in range(2)],
        SupervisorConfig(probe_every=0),
        autoscale=AutoscaleConfig(min_workers=1, max_workers=2,
                                  up_patience=2, down_patience=3,
                                  down_active_per_worker=1.0,
                                  cooldown_ticks=0),
        spec_factory=worker_spec_factory(
            str(tmp_path / "scale"), ["--preset", "test-tiny"]))
    sup.attach_router(router)
    respawned = []
    sup._spawn = lambda h: respawned.append(h.spec.idx)
    try:
        for h in sup.handles:
            h.state = RUNNING
        router.load = {"queued": 0, "active": 1, "n_routable": 2}
        for _ in range(3):
            sup._tick_autoscale()
        assert sup.scale_downs == 1
        h1 = sup.handles[1]
        assert h1.retiring and h1.intentional_stop
        assert router.drained == [1]
        # the worker exits -> RETIRED, never respawned
        sup._on_exit(h1, 0)
        assert h1.state == RETIRED and not h1.retiring
        assert respawned == []
        assert not sup.reviving            # retiring never held requeues
        # min_workers floors further shrink (1 RUNNING left)
        router.load = {"queued": 0, "active": 0, "n_routable": 1}
        for _ in range(10):
            sup._tick_autoscale()
        assert sup.scale_downs == 1
    finally:
        sup.stop_all()


# ---------------------------------------------------------------------------
# host_loss mechanics + load-step arrivals
# ---------------------------------------------------------------------------

def test_chaos_host_loss_kills_process_and_deletes_workdir(tmp_path):
    """host_loss = SIGKILL + the worker's whole private dir gone
    (journal included): the machine vanished, not just the process."""
    wd = tmp_path / "w0"
    wd.mkdir()
    jpath = wd / "journal.jsonl"
    jpath.write_text('{"ev": "submit", "id": "x"}\n')
    spec = WorkerSpec(
        idx=0, cmd=[sys.executable, "-c", "import time; time.sleep(60)"],
        journal_path=str(jpath), workdir=str(wd))
    sup = ProcSupervisor([spec], SupervisorConfig(probe_every=0))
    try:
        sup._spawn(sup.handles[0])
        h = sup.handles[0]
        assert h.proc.poll() is None
        sup.chaos_host_loss(0)
        assert h.proc.poll() is not None       # dead
        assert not wd.exists()                 # disk gone with the host
        assert any("host_loss" in e for e in sup.events)
        # the respawn is the replacement host: empty dir recreated
        sup._spawn(h)
        assert wd.exists() and not jpath.exists()
    finally:
        sup.stop_all()


def test_load_step_session_arrivals_double_then_halve():
    """SessionLoadConfig.load_step phases the SAME seeded Poisson
    draws: middle-third inter-arrival gaps exactly halve (2x rate),
    final-third gaps exactly double (rate/2)."""
    base = SessionLoadConfig(n_sessions=9, turns=1, rate=50.0, seed=4,
                             prefix_len=4, max_new_tokens=2)
    flat = make_sessions(CFG, base)
    import dataclasses
    stepped = make_sessions(
        CFG, dataclasses.replace(base, load_step=True))
    # identical sessions otherwise (same seed, same draws)
    assert [s.group for s in flat] == [s.group for s in stepped]

    def gaps(sessions):
        t = [s.due_t for s in sessions]
        return np.diff(np.concatenate([[0.0], t]))

    g0, g1 = gaps(flat), gaps(stepped)
    assert np.allclose(g1[:3], g0[:3])            # base rate
    assert np.allclose(g1[3:6], g0[3:6] / 2.0)    # doubled load
    assert np.allclose(g1[6:], g0[6:] * 2.0)      # halved load


# ---------------------------------------------------------------------------
# acceptance soaks (slow tier: -m "multiproc and slow")
# ---------------------------------------------------------------------------

def _spawn_isolated(tmp_path, n_workers, rcfg=None, scfg=None,
                    telemetry=None, **spawn_kw):
    """A fleet on FULLY ISOLATED per-worker temp dirs + a router
    ledger: no shared journal dir, registration over RPC only."""
    base = str(tmp_path / "fleet")
    specs = make_worker_specs(n_workers, base,
                              ["--preset", "test-tiny"],
                              ["--pool-size", "2", "--max-queue", "16"])
    rcfg = rcfg or RouterConfig(
        n_replicas=n_workers, journal_dir=None,
        ledger_path=str(tmp_path / "router_ledger.jsonl"),
        step_timeout_s=5.0)
    scfg = scfg or SupervisorConfig(backoff_s=0.2, probe_every=4,
                                    probe_timeout_s=1.0)
    return spawn_fleet(specs, rcfg, scfg, telemetry=telemetry,
                       **spawn_kw)


def _drain_streaming(router, sup, ids, budget_s=300.0):
    results, streams = {}, {i: [] for i in ids}
    deadline = time.monotonic() + budget_s
    while not router.idle:
        assert time.monotonic() < deadline, (
            f"fleet did not drain: done={sorted(results)} "
            f"router={router.events[-6:]} sup={sup.events[-6:]}")
        for res in router.step():
            results[res.id] = res
        for rid in streams:
            streams[rid].extend(router.take_new_tokens(rid))
        sup.tick()
    return results, streams


@pytest.mark.chaos
@pytest.mark.slow
def test_host_loss_soak_exactly_once_streams(tmp_path):
    """THE ISSUE 14 acceptance criterion: a 4-worker fleet on fully
    isolated temp dirs (no shared journal dir, registration over RPC
    only) survives host_loss chaos — worker 0's process SIGKILLed AND
    its journal/workdir deleted mid-decode — with exactly-once greedy
    streams: every stream token-identical to the no-chaos run, zero
    duplicates, zero drops. Recovery reads NOTHING from the dead
    worker's filesystem: the respawned worker replays an empty journal
    and the router requeues from its own ledger."""
    router, sup = _spawn_isolated(tmp_path, 4)
    try:
        reqs = _reqs(8, seed=31, max_new=20)
        plan = FaultPlan(Fault(site=FLEET_STEP, kind=KIND_HOST_LOSS,
                               at=4, arg=0))
        with installed(plan):
            for q in reqs:
                assert router.submit(q) is None
            results, streams = _drain_streaming(router, sup,
                                                [q.id for q in reqs])
        assert ("fleet/step", KIND_HOST_LOSS, 4) in plan.fired
        assert len(results) == len(reqs)
        for q in reqs:
            want = _offline(q.prompt, 20)
            assert results[q.id].finish_reason == "max_tokens"
            assert streams[q.id] == want, (
                f"{q.id}: stream diverged across host_loss "
                f"(drop/duplicate): {streams[q.id]} != {want}")
        h0 = sup.handles[0]
        assert h0.crash_restarts == 1
        assert h0.gen == 1
        # the replacement "host" came up with an EMPTY journal: its
        # registration reported zero replayed requests
        assert any("host_loss" in e for e in sup.events)
        attach = [e for e in sup.events
                  if "worker 0 registered+attached (gen 1" in e]
        assert attach and "kept 0" in attach[-1]
        # the router's ledger closed every id (nothing left unfinished)
        ledger = router.rcfg.ledger_path
    finally:
        sup.stop_all()
        router.close()
    assert RequestJournal.unfinished(ledger) == []


@pytest.mark.slow
def test_autoscale_load_step_soak_zero_drops(tmp_path):
    """The other acceptance half: a load-step soak (session arrivals
    double mid-run, then halve) on a 1-worker fleet with the
    autoscaler enabled shows scale-UP under the sustained backlog and
    a drain-based scale-DOWN in the lull — with zero dropped requests
    and zero recompiles after warmup."""
    base = str(tmp_path / "fleet")
    config_args = ["--preset", "test-tiny"]
    # ONE decode slot per worker: arrivals genuinely outpace a
    # single worker, so the backlog signal is real, not simulated
    engine_args = ["--pool-size", "1", "--max-queue", "64"]
    specs = make_worker_specs(1, base, config_args, engine_args)
    rcfg = RouterConfig(
        n_replicas=1, journal_dir=None,
        ledger_path=str(tmp_path / "router_ledger.jsonl"),
        step_timeout_s=5.0, retry_max=8)
    router, sup = spawn_fleet(
        specs, rcfg,
        SupervisorConfig(backoff_s=0.2, probe_every=0),
        autoscale=AutoscaleConfig(min_workers=1, max_workers=3,
                                  up_backlog_per_worker=0.5,
                                  up_patience=2,
                                  down_active_per_worker=2.0,
                                  down_patience=20, cooldown_ticks=10),
        spec_factory=worker_spec_factory(base, config_args,
                                         engine_args))
    lcfg = SessionLoadConfig(
        n_sessions=16, turns=2, n_prefix_groups=2, prefix_len=8,
        user_len_min=1, user_len_max=2, max_new_tokens=8,
        rate=2.0, think_time_s=0.5, greedy=True, seed=0,
        load_step=True)
    try:
        summary = run_fleet_replay(None, CFG, lcfg, router=router,
                                   supervisor=sup,
                                   collect_streams=True)
        # drain the lull: keep ticking until the autoscaler had its
        # chance to retire the extra workers
        deadline = time.monotonic() + 60
        while sup.scale_downs == 0 and time.monotonic() < deadline:
            router.step()
            sup.tick()
            time.sleep(0.01)
        assert sup.scale_ups >= 1, (sup.events[-10:])
        assert sup.scale_downs >= 1, (sup.events[-10:])
        assert summary["n_completed"] == summary["n_requests"], (
            "autoscaling dropped requests")
        assert summary["n_rejected"] == 0
        # every stream delivered exactly the terminal token list
        for rid, res in summary["results"].items():
            assert summary["streams"][rid] == list(res.tokens)
        assert summary["recompiles_after_warmup"] == 0
        assert any(h.state == RETIRED for h in sup.handles)
        assert sum(h.state == RUNNING for h in sup.handles) >= 1
    finally:
        sup.stop_all()
        router.close()
