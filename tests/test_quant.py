"""Quantization subsystem (replicatinggpt_tpu/quant/, ISSUE 15): int8/
fp8 paged KV with quantize-on-write + in-kernel dequant, int8/fp8
weight inference with dequant fused into the matmuls, and the capacity
economics the subsystem exists for.

Acceptance pinned here:
- greedy token parity vs the unquantized engine on short traces, and a
  max-logit-divergence budget (quant.DIVERGENCE_BUDGET) on long
  teacher-forced traces — for int8 KV AND int8 weights;
- pages-per-request HALVED at fixed HBM in the pool-geometry test
  (page count is the admission currency);
- zero recompiles across a quantized replay containing admissions,
  prefix hits, evictions and copy-on-write;
- scales tracked through COW splits / eviction / radix prefix hits
  (the scale arrays ride the pool's page axis), and the pages metrics
  block + Prometheus exposition carrying the quant gauges;
- the engine-shape hash covering the quant knobs (mismatched fleets
  reject at registration) and the CLI forwarding them to workers.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replicatinggpt_tpu.config import ModelConfig
from replicatinggpt_tpu.models.gpt import (decode_step_paged, init_params,
                                           init_paged_kv_pool)
from replicatinggpt_tpu.quant import DIVERGENCE_BUDGET, QuantConfig
from replicatinggpt_tpu.quant.weights import (calibrate, load_calibration,
                                              params_are_quantized,
                                              quantize_params,
                                              save_calibration)
from replicatinggpt_tpu.serve import (Engine, EngineConfig, ReplayConfig,
                                      Request, SamplingParams, run_replay)
from replicatinggpt_tpu.serve.pages import n_pages_for_hbm, page_bytes

CFG = ModelConfig(vocab_size=65, block_size=32, n_layer=2, n_head=2,
                  n_embd=32, dropout=0.0, attn_dropout=0.0, dtype="float32")


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _greedy(rid, prompt, max_new=6):
    return Request(id=rid, prompt=np.asarray(prompt, np.int32),
                   max_new_tokens=max_new,
                   sampling=SamplingParams(greedy=True))


def _short_reqs():
    return [_greedy("q0", [3, 1, 4, 1, 5]), _greedy("q1", [9, 2, 6]),
            _greedy("q2", [7, 7, 7, 7])]


def _streams(params, ecfg, reqs=None):
    eng = Engine(params, CFG, ecfg)
    for r in (reqs or _short_reqs()):
        assert eng.submit(r) is None
    return {r.id: r.tokens for r in eng.drain()}, eng


# ---------------------------------------------------------------------------
# divergence budgets: greedy parity short, logit budget long
# ---------------------------------------------------------------------------

BASE = EngineConfig(pool_size=2, max_queue=8, page_size=8)


@pytest.mark.parametrize("kv,wt", [("int8", "none"), ("fp8", "none"),
                                   ("none", "int8"), ("int8", "int8")])
def test_greedy_parity_short_traces(params, kv, wt):
    """The acceptance's short-trace half: every quantized mode emits
    the exact token streams the unquantized engine does on short
    greedy traces (both layouts' paged programs quantize-on-write and
    dequant-on-gather — parity means the round-trip error stayed
    under every argmax margin on this trace)."""
    want, _ = _streams(params, BASE)
    got, eng = _streams(params, dataclasses.replace(
        BASE, kv_quant=kv, weight_quant=wt))
    assert got == want
    bits = eng.metrics_summary()["pages"]["kv_quant_bits"]
    assert bits == (8 if kv != "none" else 32)


def test_head_granularity_and_heads_layout_parity(params):
    cfg2 = dataclasses.replace(CFG, decode_cache_layout="heads")
    p2 = init_params(jax.random.PRNGKey(0), cfg2)

    def run(ecfg):
        eng = Engine(p2, cfg2, ecfg)
        for r in _short_reqs():
            assert eng.submit(r) is None
        return {r.id: r.tokens for r in eng.drain()}

    want = run(BASE)
    assert run(dataclasses.replace(BASE, kv_quant="int8",
                                   quant_granularity="head")) == want
    assert run(dataclasses.replace(BASE, kv_quant="int8")) == want


def _teacher_forced_divergence(params, qparams, pool_ref, pool_q,
                               cfg, n_steps):
    """Drive both pools through ``n_steps`` paged decode steps on the
    SAME (reference-greedy) token trajectory and return the max
    |Δlogit| — teacher forcing keeps the trajectories aligned so the
    number measures quantization error, not compounding divergence."""
    tables = jnp.asarray(np.arange(8, dtype=np.int32)[None].repeat(1, 0)
                         .reshape(1, 8))
    pos = jnp.asarray(np.array([0], np.int32))
    act = jnp.asarray(np.array([True]))
    tok = jnp.asarray(np.array([3], np.int32))
    worst = 0.0
    for _ in range(n_steps):
        lr, pool_ref = decode_step_paged(params, tok, pos, act, tables,
                                         pool_ref, cfg)
        lq, pool_q = decode_step_paged(qparams, tok, pos, act, tables,
                                       pool_q, cfg)
        worst = max(worst, float(jnp.abs(lr - lq).max()))
        tok = jnp.argmax(lr, axis=-1).astype(jnp.int32)   # teacher force
        pos = pos + 1
    return worst


def test_kv_int8_logit_divergence_budget_long(params):
    """The acceptance's long-trace half for int8 KV: max |Δlogit| over
    a full-buffer teacher-forced decode stays under the pinned
    budget."""
    q = QuantConfig(kv_dtype="int8")
    worst = _teacher_forced_divergence(
        params, params,
        init_paged_kv_pool(CFG, 8, 8),
        init_paged_kv_pool(CFG, 8, 8, quant=q),
        CFG, n_steps=CFG.block_size - 1)
    assert 0.0 < worst < DIVERGENCE_BUDGET["int8"], worst


def test_weight_int8_logit_divergence_budget_long(params):
    """Ditto for int8 weights (unquantized KV on both sides isolates
    the weight error)."""
    qp = quantize_params(params, "int8")
    worst = _teacher_forced_divergence(
        params, qp,
        init_paged_kv_pool(CFG, 8, 8),
        init_paged_kv_pool(CFG, 8, 8),
        CFG, n_steps=CFG.block_size - 1)
    assert 0.0 < worst < DIVERGENCE_BUDGET["int8"], worst


def test_fp8_weight_divergence_budget(params):
    qp = quantize_params(params, "fp8")
    worst = _teacher_forced_divergence(
        params, qp,
        init_paged_kv_pool(CFG, 8, 8),
        init_paged_kv_pool(CFG, 8, 8),
        CFG, n_steps=16)
    assert 0.0 < worst < DIVERGENCE_BUDGET["fp8"], worst


# ---------------------------------------------------------------------------
# pool geometry: pages-per-request halved at fixed HBM
# ---------------------------------------------------------------------------

def test_pages_per_request_halved_at_fixed_hbm():
    """The acceptance's capacity half, as pool geometry: size two
    pools from ONE HBM byte budget — bf16 K/V vs int8+scales — and a
    request's whole-lifetime page reservation is HALF the pool
    fraction on the quantized side (page count is the admission
    currency, so that IS doubled concurrency)."""
    cfg = dataclasses.replace(CFG, n_embd=512, n_head=8,
                              dtype="bfloat16", block_size=256)
    psz = 16
    pb_bf16 = page_bytes(cfg, psz)                  # 2 bytes/elem
    pb_int8 = page_bytes(cfg, psz, "int8")          # 1 byte + scales
    # bytes/page ratio: ~2x minus the per-row scale metadata (8 bytes
    # per token per layer at page granularity vs 1024 row bytes)
    assert 1.9 < pb_bf16 / pb_int8 <= 2.0
    # fixed budget = exactly 2N int8 pages: bf16 fits only N, so a
    # request needing k pages reserves k/N of the bf16 pool but
    # k/(2N) — HALF — of the int8 pool
    N = 64
    hbm = 2 * N * pb_int8
    assert n_pages_for_hbm(hbm, cfg, psz, "int8") == 2 * N
    assert n_pages_for_hbm(hbm, cfg, psz) == N
    # head-granularity scales cost H x the metadata but still land
    # close to the 2x (H=8: 64 bytes vs 1024 row bytes per token)
    assert n_pages_for_hbm(hbm, cfg, psz, "int8", "head") >= int(1.8 * N)


def test_quantized_pool_stats_and_bytes(params):
    eng = Engine(params, CFG, dataclasses.replace(BASE, kv_quant="int8"))
    pg = eng.metrics_summary()["pages"]
    assert pg["kv_quant"] == "int8"
    assert pg["quant_granularity"] == "page"
    assert pg["kv_quant_bits"] == 8
    assert pg["bytes_per_page"] == page_bytes(CFG, pg["page_size"],
                                              "int8")
    assert eng.pool.cache["k"].dtype == jnp.int8
    assert eng.pool.cache["ks"].dtype == jnp.float32


# ---------------------------------------------------------------------------
# zero recompiles + scales through COW / eviction / prefix hits
# ---------------------------------------------------------------------------

def test_quantized_replay_zero_recompiles_with_all_page_events(params):
    """The steady-state acceptance: a quantized replay whose pool
    pressure forces admissions, prefix hits, LRU evictions AND
    copy-on-write splits compiles nothing after warmup — quantize-on-
    write and scale scatters are traced math, never new programs. The
    trace interleaves byte-identical full-page prompts (the
    full-prompt-hit arm, the only path to COW), same-prefix tails
    (partial hits) and disjoint prompts (eviction pressure on a
    6-page pool)."""
    rng = np.random.default_rng(3)
    shared = ((np.arange(16) % 13) + 1).astype(np.int32)  # 2 full pages
    reqs = []
    for i in range(16):
        if i % 4 == 1:
            prompt = shared.copy()                 # full-prompt hit/COW
        elif i % 4 == 2:
            prompt = np.concatenate(
                [shared[:8], rng.integers(1, 60, (4,)).astype(np.int32)])
        else:
            prompt = rng.integers(1, 60, (12,)).astype(np.int32)
        reqs.append(Request(id=f"z{i}", prompt=prompt, max_new_tokens=4,
                            sampling=SamplingParams(greedy=True)))
    trace = [(i * 1e-4, r) for i, r in enumerate(reqs)]
    rcfg = ReplayConfig(n_requests=16, greedy=True)
    ecfg = EngineConfig(pool_size=2, max_queue=32, page_size=8,
                        n_pages=6, kv_quant="int8")
    s = run_replay(params, CFG, rcfg, ecfg, trace=trace)
    assert s["n_completed"] == 16
    assert s["recompiles_after_warmup"] == 0
    pg = s["pages"]
    assert pg["prefix_hits"] > 0
    assert pg["evictions"] > 0
    assert pg["cow_copies"] > 0
    assert pg["kv_quant"] == "int8"


def test_cow_split_carries_scales(params):
    """Scales track COW: a full-prompt prefix hit splits the frontier
    page with a device page copy, and the copy carries the page's
    scale rows (ks/vs share the page axis) — the split page dequants
    to the same K/V the shared original holds."""
    ecfg = dataclasses.replace(BASE, kv_quant="int8")
    eng = Engine(params, CFG, ecfg)
    prompt = ((np.arange(16) % 13) + 1).astype(np.int32)  # 2 full pages
    assert eng.submit(_greedy("w", prompt, max_new=2)) is None
    eng.drain()                                 # registers both pages
    assert eng.submit(_greedy("c", prompt, max_new=2)) is None
    eng.step()                                  # admission + 1st decode
    slot = eng.pool.slot_of("c")
    assert slot is not None
    claim = eng.pool._claims[slot]
    assert claim.cow, "full-prompt hit must have COW-split"
    src, dst = claim.cow[0]
    ks = np.asarray(eng.pool.cache["ks"], np.float32)
    k = np.asarray(eng.pool.cache["k"], np.float32)
    # the first decode write rewrote only position P-1 (row 7 of the
    # dst page); rows 0..6 are the verbatim copy, scales included
    np.testing.assert_array_equal(ks[:, dst, :7], ks[:, src, :7])
    np.testing.assert_array_equal(k[:, dst, :7, :], k[:, src, :7, :])
    assert eng.metrics_summary()["pages"]["cow_copies"] == 1
    eng.drain()


def test_quantized_pool_allocator_fuzz():
    """The 400-op seeded fuzz (tests/test_pages.py's reference-model
    invariants) re-run through a QUANTIZED PagedCachePool's host API:
    the allocator/radix/COW planning must be storage-agnostic, and the
    pool's scale arrays must keep their page axis aligned with the K/V
    arrays through every acquire / prefix hit / COW plan / eviction /
    release — a page id indexes rows AND scales or the device programs
    scatter scales onto the wrong page."""
    from test_pages import _check_allocator

    from replicatinggpt_tpu.serve.pages import PagedCachePool
    rng = np.random.default_rng(42)
    psz = 4
    pool = PagedCachePool(CFG, 8, page_size=psz, n_pages=20,
                          quant=QuantConfig(kv_dtype="int8"))
    # the scale arrays share the physical page axis (axis 1) with the
    # pool arrays for the engine's COW copy + the mesh scale spec
    for name in ("ks", "vs"):
        assert pool.cache[name].shape[:2] == pool.cache["k"].shape[:2]
    seen, live, next_id = [], {}, 0
    for _ in range(400):
        op = rng.choice(["acquire", "advance", "release"],
                        p=[0.45, 0.3, 0.25])
        if op == "acquire":
            if seen and rng.random() < 0.35:
                prompt = seen[int(rng.integers(len(seen)))].copy()
            else:
                P = int(rng.integers(1, 17))
                prompt = rng.integers(0, 3, (P,)).astype(np.int32)
                seen.append(prompt)
            cap = int(rng.integers(1, 9))
            rid = f"f{next_id}"
            adm = pool.acquire(rid, prompt, cap)
            if adm is None:
                continue
            claim = pool._claims[adm.slot]
            # COW plans stay inside the physical pool: scale scatters
            # use the same ids, so an out-of-range dst would corrupt
            for src, dst in adm.cow:
                assert 0 <= src < pool.n_pages
                assert 0 <= dst < pool.n_pages
            pool.commit_admission(adm.slot)
            live[rid] = (claim, int(prompt.size) - 1)
            next_id += 1
        elif op == "advance" and live:
            rid = str(rng.choice(list(live)))
            claim, pos = live[rid]
            pos += int(rng.integers(1, 5))
            slot = pool.slot_of(rid)
            pool.positions[slot] = pos
            pool.flush_pending()
            live[rid] = (claim, pos)
        elif op == "release" and live:
            rid = str(rng.choice(list(live)))
            claim, _ = live.pop(rid)
            pool.release(pool.slot_of(rid))
        _check_allocator(pool.alloc,
                         {i: v for i, v in enumerate(live.values())})
    a = pool.alloc
    assert a.prefix_hits > 0, "fuzz never exercised a prefix hit"
    assert a.evictions > 0, "fuzz never exercised eviction"
    assert a.cow_copies > 0, "fuzz never exercised copy-on-write"


def test_prefix_hit_reuses_quantized_pages_with_parity(params):
    """Radix prefix hits on a quantized pool: the claimer attends the
    registrant's int8 pages + scales and still matches the unquantized
    engine's streams (3 same-prefix requests, prefill only pays the
    tails)."""
    shared = ((np.arange(8) % 11) + 1).astype(np.int32)
    reqs = [
        _greedy(f"p{i}", np.concatenate([shared, np.array([i + 1, i + 2],
                                                          np.int32)]))
        for i in range(3)]
    want, _ = _streams(params, BASE, reqs=[dataclasses.replace(r)
                                           for r in reqs])
    got, eng = _streams(params, dataclasses.replace(BASE,
                                                    kv_quant="int8"),
                        reqs=reqs)
    assert got == want
    assert eng.metrics_summary()["pages"]["prefix_hits"] >= 2


def test_speculative_verify_quantized_parity(params):
    """The speculative verify program scatters its drafted window
    through the quantized pool too (same _scatter_kv discipline):
    greedy streams with an n-gram drafter match the quantized
    plain-decode engine token-for-token on a repetitive trace."""
    from replicatinggpt_tpu.serve.speculative import make_drafter
    reqs = lambda: [_greedy("s0", [5, 6, 5, 6, 5, 6], max_new=8),  # noqa: E731
                    _greedy("s1", [2, 3, 2, 3], max_new=6)]
    ecfg = dataclasses.replace(BASE, kv_quant="int8")
    want, _ = _streams(params, ecfg, reqs=reqs())
    eng = Engine(params, CFG, ecfg,
                 drafter=make_drafter("ngram", 3, 3, ecfg.pool_size))
    for r in reqs():
        assert eng.submit(r) is None
    got = {r.id: r.tokens for r in eng.drain()}
    assert got == want


# ---------------------------------------------------------------------------
# kernel routes (interpret mode): in-kernel dequant parity
# ---------------------------------------------------------------------------

def test_quantized_kernel_routes_greedy_parity(params, monkeypatch):
    """Both Pallas routes (fused all-layers + per-layer paged
    attention) dequant int8 pages IN-KERNEL and fake-quantize the
    fresh column — greedy streams stay identical to the quantized XLA
    gather route, which is itself parity-pinned against bf16 above."""
    from replicatinggpt_tpu.ops import decode_pallas, paged_pallas
    monkeypatch.setattr(paged_pallas, "_paged_attn_backend_ok",
                        lambda: True)
    cfg = dataclasses.replace(CFG, n_embd=64, vocab_size=65,
                              decode_cache_layout="packed")
    p64 = init_params(jax.random.PRNGKey(1), cfg)
    reqs = lambda: [_greedy("k0", [3, 1, 4, 1, 5], max_new=6),  # noqa: E731
                    _greedy("k1", [9, 2, 6], max_new=5)]

    def run(ecfg):
        eng = Engine(p64, cfg, ecfg)
        for r in reqs():
            assert eng.submit(r) is None
        return {r.id: r.tokens for r in eng.drain()}, eng

    ecfg = EngineConfig(pool_size=2, max_queue=4, page_size=8,
                        kv_quant="int8")
    want, _ = run(ecfg)
    got, eng = run(dataclasses.replace(ecfg, paged_kernel=True))
    assert eng._use_fused and not eng._use_pallas
    assert got == want
    monkeypatch.setattr(decode_pallas, "fused_paged_decode_supported",
                        lambda *a, **kw: False)
    got2, eng2 = run(dataclasses.replace(ecfg, paged_kernel=True))
    assert eng2._use_pallas and not eng2._use_fused
    assert got2 == want


def test_kernel_envelopes_accept_every_quant_mode(params):
    """ISSUE 20 flips the old seams: fp8 pools and head-granularity
    scales dequant INSIDE the unified kernel family now, so the
    envelopes accept every shipped (kv_quant, granularity) cell —
    decided once per engine and exported via kernel_route."""
    from replicatinggpt_tpu.ops.decode_pallas import (
        fused_paged_decode_supported)
    from replicatinggpt_tpu.ops.paged_pallas import paged_decode_supported
    cfg = dataclasses.replace(CFG, n_embd=64,
                              decode_cache_layout="packed")
    for kvq in ("none", "int8", "fp8"):
        for gran in ("page", "head"):
            assert fused_paged_decode_supported(cfg, 2, 8, 1,
                                                kv_quant=kvq,
                                                granularity=gran), \
                (kvq, gran)
            assert paged_decode_supported(2, 32, 8, 1, kv_quant=kvq,
                                          granularity=gran), (kvq, gran)
    # unknown modes still gate (the reasons vocabulary stays honest)
    assert not paged_decode_supported(2, 32, 8, 1, kv_quant="int4")
    assert not fused_paged_decode_supported(cfg, 2, 8, 1,
                                            granularity="token")


# ---------------------------------------------------------------------------
# weight calibration workflow
# ---------------------------------------------------------------------------

def test_calibration_roundtrip_and_budget(params, tmp_path):
    """The checkpoint-adjacent workflow: calibrate measures a logit
    divergence under the pinned budget, serializes scales + report,
    and a reload quantizes BIT-IDENTICALLY from the stored scales."""
    qp, report = calibrate(params, CFG, "int8")
    assert params_are_quantized(qp)
    assert not params_are_quantized(params)
    assert 0.0 < report["max_logit_div"] < DIVERGENCE_BUDGET["int8"]
    save_calibration(str(tmp_path), qp, report)
    scales, rep2 = load_calibration(str(tmp_path))
    assert rep2["max_logit_div"] == report["max_logit_div"]
    qp2 = quantize_params(params, "int8",
                          scales={k: jnp.asarray(v)
                                  for k, v in scales.items()})
    for name, arr in qp["blocks"].items():
        np.testing.assert_array_equal(np.asarray(arr, np.float32),
                                      np.asarray(qp2["blocks"][name],
                                                 np.float32))
    assert load_calibration(str(tmp_path / "missing")) == (None, None)


def test_fake_quant_row_matches_batched_helper():
    """The fused kernel's in-body fake-quant (fake_quantize_row_f32 —
    pure f32, no int8 materialization) must stay value-identical to
    the batched quantize/dequantize helper the scatter path uses: this
    equality IS the fused-vs-XLA token-identical contract."""
    from replicatinggpt_tpu.quant.kv import (fake_quantize_row_f32,
                                             fake_quantize_rows, kv_qmax)
    rng = np.random.default_rng(0)
    rows = jnp.asarray(rng.normal(size=(5, 1, 64)) * 3.0, jnp.float32)
    batched = fake_quantize_rows(rows.reshape(5, 64), "int8", 2, "page")
    for i in range(5):
        np.testing.assert_array_equal(
            np.asarray(fake_quantize_row_f32(rows[i], kv_qmax("int8"))),
            np.asarray(batched[i])[None])


def test_load_calibration_tolerates_corrupt_artifact(params, tmp_path):
    """A torn/corrupt quant_scales.npz (crashed writer predating the
    atomic rename) must read as 'no calibration' — the caller then
    recalibrates instead of a fleet worker dying at startup."""
    qp, report = calibrate(params, CFG, "int8")
    npz, _ = save_calibration(str(tmp_path), qp, report)
    with open(npz, "wb") as f:
        f.write(b"\x00not a zip")
    assert load_calibration(str(tmp_path)) == (None, None)


def test_quantize_params_idempotent_and_dtypes(params):
    qp = quantize_params(params, "int8")
    assert qp["blocks"]["qkv_kernel"].dtype == jnp.int8
    assert qp["blocks"]["qkv_kernel_scale"].dtype == jnp.float32
    assert quantize_params(qp, "int8") is qp      # already quantized
    # non-kernel params untouched
    assert qp["blocks"]["ln1_scale"].dtype == \
        params["blocks"]["ln1_scale"].dtype
    assert qp["wte"].dtype == params["wte"].dtype


# ---------------------------------------------------------------------------
# config plumbing: validation, shape hash, CLI forwarding, prometheus
# ---------------------------------------------------------------------------

def test_quant_config_validation():
    QuantConfig().validate()
    QuantConfig(kv_dtype="int8", weight_dtype="fp8",
                granularity="head").validate()
    with pytest.raises(ValueError):
        QuantConfig(kv_dtype="int4").validate()
    with pytest.raises(ValueError):
        QuantConfig(granularity="tensor").validate()
    with pytest.raises(ValueError):
        Engine(None, CFG, EngineConfig(kv_quant="int4"))


def test_shape_hash_covers_quant_knobs():
    """Mismatched quant modes are DIFFERENT engines numerically: the
    registration hash must move with every quant knob so a mixed
    fleet rejects at the handshake, never mid-stream."""
    from replicatinggpt_tpu.serve.rpc import engine_shape_hash
    base = engine_shape_hash(CFG, EngineConfig())
    assert engine_shape_hash(CFG, EngineConfig(kv_quant="int8")) != base
    assert engine_shape_hash(CFG, EngineConfig(weight_quant="int8")) \
        != base
    assert engine_shape_hash(
        CFG, EngineConfig(kv_quant="int8", quant_granularity="head")) \
        != engine_shape_hash(CFG, EngineConfig(kv_quant="int8"))
    assert engine_shape_hash(
        CFG, EngineConfig(weight_quant="int8", act_quant="int8")) \
        != engine_shape_hash(CFG, EngineConfig(weight_quant="int8"))
    assert engine_shape_hash(CFG, EngineConfig()) == base


def test_cli_forwards_quant_flags():
    """serve --multiproc must respawn workers with the quant knobs —
    the ENGINE_FORWARD_FLAGS round trip covers them."""
    import argparse

    from replicatinggpt_tpu.cli import (add_engine_flags,
                                        engine_config_from_args,
                                        engine_forward_args)
    p = argparse.ArgumentParser()
    add_engine_flags(p)
    args = p.parse_args(["--kv-quant", "int8", "--weight-quant", "int8",
                         "--quant-granularity", "head",
                         "--act-quant", "int8"])
    fwd = engine_forward_args(args)
    assert "--kv-quant" in fwd and "int8" in fwd
    args2 = p.parse_args(fwd)
    e1, e2 = (engine_config_from_args(a) for a in (args, args2))
    assert e1 == e2
    assert e1.kv_quant == "int8" and e1.weight_quant == "int8"
    assert e1.quant_granularity == "head"
    assert e1.act_quant == "int8"


def test_prometheus_carries_quant_gauges(params, tmp_path):
    out = tmp_path / "metrics.prom"
    rcfg = ReplayConfig(n_requests=3, rate=5000.0, seed=1,
                        prompt_len_min=4, prompt_len_max=8,
                        max_new_tokens=3, greedy=True)
    run_replay(params, CFG,
               rcfg, dataclasses.replace(BASE, kv_quant="int8"),
               metrics_out=str(out))
    text = out.read_text()
    assert "bytes_per_page" in text
    assert "kv_quant_bits 8" in text
