"""Request-lifecycle tracing + telemetry export (utils/telemetry.py,
tools/trace_check.py): zero-cost disabled mode (no buffer growth, the
shared null span, GL004-clean with zero pragmas), the three exporters
(Perfetto Chrome trace validated by trace_check, metrics-timeline
JSONL, Prometheus text), per-request span trees with
prefix-hit/COW/recovery markers from a real shared-prefix replay, and
the torn-tail-tolerant JSONL sink."""

import importlib.util
import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax

from replicatinggpt_tpu.config import ModelConfig
from replicatinggpt_tpu.faults.watchdog import (LoadShedder,
                                                ResilienceConfig,
                                                SpecHealth, StepWatchdog)
from replicatinggpt_tpu.models.gpt import init_params
from replicatinggpt_tpu.serve import (Engine, EngineConfig, ReplayConfig,
                                      Request, RequestJournal,
                                      SamplingParams, run_replay)
from replicatinggpt_tpu.utils.logging import Metrics
from replicatinggpt_tpu.utils.telemetry import (ENGINE_TRACK, NULL,
                                                MetricsTimeline,
                                                Telemetry,
                                                chrome_trace_from_jsonl,
                                                load_jsonl,
                                                prometheus_text)

REPO = Path(__file__).resolve().parent.parent

CFG = ModelConfig(vocab_size=65, block_size=32, n_layer=2, n_head=2,
                  n_embd=32, dropout=0.0, attn_dropout=0.0,
                  dtype="float32")


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _trace_check():
    spec = importlib.util.spec_from_file_location(
        "trace_check", REPO / "tools" / "trace_check.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _greedy(rid, prompt, max_new=4):
    return Request(id=rid, prompt=np.asarray(prompt, np.int32),
                   max_new_tokens=max_new,
                   sampling=SamplingParams(greedy=True))


def _names(tel):
    return {ev["name"] for ev in tel.events}


# ---------------------------------------------------------------------------
# disabled mode: zero cost, zero state, zero lint findings (satellite)
# ---------------------------------------------------------------------------

def test_null_telemetry_is_stateless_and_allocation_free():
    """The disabled recorder accumulates nothing and its span() hands
    back ONE shared context manager — the structural pin behind the
    'disabled telemetry changes nothing' claim (events is a tuple: it
    CANNOT grow)."""
    assert not NULL.enabled
    s1, s2 = NULL.span("a", 3, x=1), NULL.span("b")
    assert s1 is s2                       # shared instance, no per-call alloc
    with s1 as v:
        assert v is None
    NULL.begin("a"), NULL.end("a"), NULL.instant("m", step=1)
    NULL.complete("x", 0, 0.0, 1.0)
    NULL.name_track(0, "engine")
    assert NULL.now_us() == 0.0 and NULL.ts_us(123.0) == 0.0
    assert NULL.events == ()
    NULL.close()


def test_engine_without_telemetry_holds_null_and_records_nothing(params):
    """Default engine construction wires the NULL recorder end to end
    (engine, paged pool, allocator) and a full replay leaves no
    telemetry state anywhere — the disabled serve step path is the
    seed's."""
    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=8))
    assert eng.tel is NULL
    assert eng.pool.alloc.tel is NULL
    for i in range(3):
        assert eng.submit(_greedy(f"r{i}", [1 + i, 2, 3])) is None
    res = eng.drain()
    assert len(res) == 3
    assert NULL.events == ()


def test_telemetry_module_is_gl004_clean_with_zero_pragmas():
    """The recorder is called from inside engine/runner step loops, so
    it must contain NO host-sync sites (float()/.item()/np.asarray/
    device_get) and claim NO pragma exemptions — graftlint's dataflow
    would otherwise propagate a sync into every instrumented loop.
    (The whole-project baseline gate in test_lint.py enforces the
    instrumented call sites themselves.)"""
    from replicatinggpt_tpu.analysis import lint_paths
    for rel in ("replicatinggpt_tpu/utils/telemetry.py",
                "tools/trace_check.py"):
        path = REPO / rel
        assert "graftlint: disable" not in path.read_text(), rel
        res = lint_paths([path], severity={})
        assert not res.findings, (rel, res.findings)


# ---------------------------------------------------------------------------
# Metrics.hist_summary schema (satellite)
# ---------------------------------------------------------------------------

def test_metrics_hist_summary_schema_pinned():
    """Exporters (Prometheus summaries, the timeline) index hist_summary
    keys directly — pin the schema, including the new ``min``."""
    m = Metrics()
    assert set(m.hist_summary("empty")) == set(Metrics.HIST_KEYS)
    for v in (5.0, 1.0, 3.0):
        m.observe("lat", v)
    h = m.hist_summary("lat")
    assert set(h) == set(Metrics.HIST_KEYS) == {
        "n", "mean", "min", "p50", "p90", "p99", "max"}
    assert h["n"] == 3 and h["min"] == 1.0 and h["max"] == 5.0
    assert h["mean"] == pytest.approx(3.0)
    assert set(m.summary()) == {"counters", "gauges", "histograms"}


# ---------------------------------------------------------------------------
# recorder + exporters (unit)
# ---------------------------------------------------------------------------

def test_ring_buffer_bounded():
    tel = Telemetry(capacity=8)
    for i in range(100):
        tel.instant("m", step=i)
    assert len(tel.events) == 8
    assert tel.events[0]["args"]["step"] == 92    # oldest dropped


def test_span_nests_and_exports_chrome_trace(tmp_path):
    t = [0.0]
    tel = Telemetry(clock=lambda: t[0])
    tel.name_track(0, "engine")
    tel.begin("request", 1, ts_us=0.0, request="r1")
    t[0] = 0.001
    with tel.span("work", 1, request="r1"):
        t[0] = 0.002
    t[0] = 0.003
    tel.end("request", 1, ts_us=tel.now_us(), request="r1")
    out = tmp_path / "trace.json"
    n = tel.export_chrome_trace(str(out))
    doc = json.loads(out.read_text())
    assert len(doc["traceEvents"]) == n
    tc = _trace_check()
    assert tc.check_trace(str(out), min_requests=1) == []


def test_jsonl_sink_tolerates_torn_tail(tmp_path):
    """The sink's reason to exist is the crash window: a torn final
    line must not poison the offline trace assembly."""
    sink = tmp_path / "events.jsonl"
    tel = Telemetry(jsonl_path=str(sink))
    tel.begin("request", 1, ts_us=0.0, request="r1")
    tel.instant("marker", 1)
    tel.end("request", 1, ts_us=5.0, request="r1")
    tel.close()
    with open(sink, "a") as f:
        f.write('{"ph": "i", "name": "torn')     # crash mid-write
    evs = load_jsonl(str(sink))
    assert [e["ph"] for e in evs] == ["B", "i", "E"]
    out = tmp_path / "trace.json"
    assert chrome_trace_from_jsonl(str(sink), str(out)) == 3
    tc = _trace_check()
    assert tc.check_trace(str(out), min_requests=1) == []


def test_shared_jsonl_reader_contract_and_dedup(tmp_path):
    """utils.jsonl is THE torn-tail reader: telemetry and the request
    journal import it rather than carrying private copies, and its
    contract (skip blank, skip unparseable, missing file == empty
    history) is pinned here once for all three consumers."""
    from replicatinggpt_tpu.serve import journal as journal_mod
    from replicatinggpt_tpu.utils import jsonl as jsonl_mod
    from replicatinggpt_tpu.utils import telemetry as telemetry_mod

    # dedup: both consumers resolve to the one shared implementation
    assert telemetry_mod.load_jsonl is jsonl_mod.load_jsonl
    assert (journal_mod.load_jsonl_if_exists
            is jsonl_mod.load_jsonl_if_exists)

    p = tmp_path / "records.jsonl"
    p.write_text('{"a": 1}\n'
                 '\n'                        # blank line
                 'not json at all\n'         # interior corruption
                 '{"b": 2}\n'
                 '{"c": 3, "torn')           # crash mid-write
    assert jsonl_mod.load_jsonl(str(p)) == [{"a": 1}, {"b": 2}]
    assert list(jsonl_mod.iter_jsonl(str(p))) == [{"a": 1}, {"b": 2}]
    # a journal that was never created is an empty history, not an error
    assert jsonl_mod.load_jsonl_if_exists(str(tmp_path / "never")) == []
    with pytest.raises(FileNotFoundError):
        jsonl_mod.load_jsonl(str(tmp_path / "never"))


def test_metrics_timeline_interval_and_forced_final(tmp_path):
    t = [0.0]
    m = Metrics()
    m.inc("steps")
    path = tmp_path / "tl.jsonl"
    tl = MetricsTimeline(m, str(path), interval_s=1.0, clock=lambda: t[0])
    tl.snapshot(step=0)
    t[0] = 0.5
    assert not tl.maybe_snapshot(step=1)          # inside the interval
    t[0] = 1.5
    m.inc("steps")
    assert tl.maybe_snapshot(step=2)
    tl.close(step=3)                              # forced final point
    rows = MetricsTimeline.load(str(path))
    assert len(rows) == 3 == tl.n_snapshots
    assert rows[0]["counters"]["steps"] == 1
    assert rows[1]["counters"]["steps"] == 2
    assert rows[-1]["step"] == 3
    assert rows[1]["t_s"] == pytest.approx(1.5)


def test_prometheus_text_exposition():
    m = Metrics()
    m.inc("requests_admitted", 3)
    m.gauge("queue depth!", 7)                    # needs sanitizing
    for v in (0.1, 0.2, 0.3):
        m.observe("ttft_s", v)
    txt = prometheus_text(m, prefix="tpu_gpt",
                          extra_gauges={"pages_in_use": 5})
    assert "# TYPE tpu_gpt_requests_admitted counter" in txt
    assert "tpu_gpt_requests_admitted 3" in txt
    assert "# TYPE tpu_gpt_queue_depth_ gauge" in txt
    assert "tpu_gpt_pages_in_use 5" in txt
    assert "# TYPE tpu_gpt_ttft_s summary" in txt
    assert 'tpu_gpt_ttft_s{quantile="0.5"} 0.2' in txt
    assert "tpu_gpt_ttft_s_count 3" in txt
    assert "tpu_gpt_ttft_s_min 0.1" in txt
    assert "tpu_gpt_ttft_s_sum" in txt
    # full precision: a big counter must not collapse to %g notation
    # (1.23457e+06 would corrupt every rate computed from the scrape)
    m.inc("decode_tokens", 1_234_567)
    assert "tpu_gpt_decode_tokens 1234567" in prometheus_text(
        m, prefix="tpu_gpt")


def test_artifact_paths_overwrite_not_append(tmp_path):
    """A reused --trace-out/--metrics-timeline path holds ONE run: the
    JSONL sink and timeline open 'w' (appending a rerun would duplicate
    request envelopes, which trace_check rightly rejects)."""
    sink = tmp_path / "events.jsonl"
    for _ in range(2):
        tel = Telemetry(jsonl_path=str(sink))
        tel.begin("request", 1, ts_us=0.0, request="r1")
        tel.end("request", 1, ts_us=5.0, request="r1")
        tel.close()
    assert len(load_jsonl(str(sink))) == 2        # second run only
    out = tmp_path / "trace.json"
    chrome_trace_from_jsonl(str(sink), str(out))
    assert _trace_check().check_trace(str(out), min_requests=1) == []
    tl = tmp_path / "tl.jsonl"
    m = Metrics()
    for _ in range(2):
        t = MetricsTimeline(m, str(tl))
        t.snapshot(step=0)
        t.close(step=1)
    assert len(MetricsTimeline.load(str(tl))) == 2


# ---------------------------------------------------------------------------
# recovery markers (faults/watchdog.py, faults/supervise.py seam)
# ---------------------------------------------------------------------------

def test_watchdog_policies_emit_instant_markers():
    tel = Telemetry()
    rcfg = ResilienceConfig(stall_factor=2.0, stall_floor_s=0.0,
                            stall_min_steps=4, stall_skip_steps=0,
                            spec_disable_threshold=0.5, spec_window=2,
                            shed_watermark=0.25, shed_patience=1)
    wd = StepWatchdog(rcfg, telemetry=tel)
    for _ in range(8):
        wd.observe(0.01)
    assert wd.observe(10.0)                       # stall
    sh = SpecHealth(rcfg, telemetry=tel)
    sh.observe(4, 0)
    assert sh.observe(4, 0)                       # accept-rate collapse
    sh.on_disable()
    for _ in range(rcfg.spec_reprobe_after):
        if sh.tick_disabled():
            break
    sh.on_reenable()
    shd = LoadShedder(rcfg, telemetry=tel)
    assert shd.observe(depth=8, max_queue=8) > 0
    names = _names(tel)
    assert {"watchdog_stall", "spec_disable", "spec_reprobe",
            "spec_probe_healthy", "load_shed"} <= names


def test_journal_replay_marker(tmp_path):
    tel = Telemetry()
    path = str(tmp_path / "journal.jsonl")
    j = RequestJournal(path)
    j.record_submit(_greedy("a", [1, 2]))
    j.record_submit(_greedy("b", [3, 4]))
    j.record_finish("a", "max_tokens")
    j.close()
    reqs = RequestJournal.unfinished(path, telemetry=tel)
    assert [r.id for r in reqs] == ["b"]
    ev = [e for e in tel.events if e["name"] == "journal_replay"]
    assert len(ev) == 1 and ev[0]["args"]["requeued"] == 1


# ---------------------------------------------------------------------------
# engine span trees: prefix hits, COW, full replay acceptance
# ---------------------------------------------------------------------------

def test_engine_trace_has_request_tree_prefix_hit_and_cow(params, tmp_path):
    """Two identical page-aligned prompts back to back: the second is a
    full-prompt radix hit, which takes the copy-on-write path — the
    trace must carry the complete span tree for both requests plus the
    prefix_hit and cow_split markers, and validate."""
    tel = Telemetry()
    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=8,
                                           page_size=4),
                 telemetry=tel)
    prompt = np.arange(1, 9, dtype=np.int32)      # 8 tokens = 2 full pages
    eng.submit(_greedy("a", prompt, max_new=5))
    eng.drain()
    eng.submit(_greedy("b", prompt, max_new=5))
    eng.drain()
    assert eng.pool.alloc.cow_copies == 1         # scenario sanity
    names = _names(tel)
    assert {"request", "queue", "admit", "prefill_chunk", "decode",
            "decode_step", "engine_step", "prefix_hit",
            "cow_split"} <= names
    out = tmp_path / "trace.json"
    tel.export_chrome_trace(str(out))
    tc = _trace_check()
    assert tc.check_trace(str(out), min_requests=2) == []
    # the request trees live on per-slot tracks, markers carry args
    cow = [e for e in tel.events if e["name"] == "cow_split"]
    assert cow and cow[0]["args"]["request"] == "b"
    doc = json.loads(out.read_text())
    thread_names = {e["args"]["name"] for e in doc["traceEvents"]
                    if e.get("name") == "thread_name"}
    assert "engine" in thread_names and "slot 0" in thread_names


def test_shared_prefix_replay_emits_all_three_artifacts(params, tmp_path):
    """The acceptance run: a CPU shared-prefix replay emits (a) a
    Perfetto-loadable trace with one complete nested span tree per
    request, (b) a metrics-timeline JSONL with >= 2 snapshots, (c)
    Prometheus text — all validated."""
    tr = str(tmp_path / "trace.json")
    tl = str(tmp_path / "timeline.jsonl")
    mo = str(tmp_path / "metrics.prom")
    s = run_replay(params, CFG,
                   ReplayConfig(n_requests=12, rate=5000.0, seed=3,
                                prompt_len_min=10, prompt_len_max=16,
                                shared_prefix_len=8, max_new_tokens=4,
                                greedy=True, prompt_mode="shared_prefix"),
                   EngineConfig(pool_size=4, max_queue=32, page_size=8),
                   trace_out=tr, metrics_timeline=tl, metrics_out=mo)
    assert s["n_completed"] == 12
    assert s["recompiles_after_warmup"] == 0      # tracing adds no compiles
    art = s["artifacts"]
    assert art["trace_out"] == tr and art["trace_events"] > 0
    # (a) Perfetto trace: every request's spans nest and close, with
    # prefix-hit markers from the radix cache on the same timeline
    tc = _trace_check()
    assert tc.check_trace(tr, min_requests=12) == []
    doc = json.loads(Path(tr).read_text())
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"request", "queue", "admit", "decode", "prefix_hit"} <= names
    # (b) metrics timeline: >= 2 snapshots, full Metrics schema each
    rows = MetricsTimeline.load(tl)
    assert len(rows) >= 2 and art["metrics_timeline_snapshots"] >= 2
    for row in rows:
        assert {"t_s", "step", "counters", "gauges",
                "histograms"} <= set(row)
    assert (rows[-1]["counters"]["requests_admitted"] == 12)
    # (c) Prometheus text: counters + summary quantiles + pages gauges
    txt = Path(mo).read_text()
    assert "# TYPE tpu_gpt_requests_admitted counter" in txt
    assert "tpu_gpt_requests_admitted 12" in txt
    assert 'tpu_gpt_ttft_s{quantile="0.99"}' in txt
    assert "tpu_gpt_pages_in_use" in txt


def test_run_replay_flushes_artifacts_on_midrun_crash(params, tmp_path,
                                                      monkeypatch):
    """A replay that dies mid-run must still export the trace and
    force-close the timeline (and stop the profiler) — the crash
    window is exactly when the artifacts matter."""
    from replicatinggpt_tpu.serve import replay as replay_mod
    real_step = replay_mod.Engine.step
    calls = {"n": 0}

    def boom(self):
        calls["n"] += 1
        if calls["n"] > 3:
            raise RuntimeError("injected mid-replay crash")
        return real_step(self)

    monkeypatch.setattr(replay_mod.Engine, "step", boom)
    tr = str(tmp_path / "t.json")
    tl = str(tmp_path / "tl.jsonl")
    with pytest.raises(RuntimeError, match="injected"):
        run_replay(params, CFG,
                   ReplayConfig(n_requests=8, rate=5000.0, seed=0,
                                prompt_len_max=8, max_new_tokens=6,
                                greedy=True),
                   EngineConfig(pool_size=2, max_queue=16),
                   warmup=False, trace_out=tr, metrics_timeline=tl)
    doc = json.loads(Path(tr).read_text())
    assert any(e.get("name") == "request" for e in doc["traceEvents"])
    assert len(MetricsTimeline.load(tl)) >= 2     # attach + forced final


def test_decode_window_spans_and_token_instants(params, tmp_path):
    """Async-engine telemetry: one decode X span per DISPATCH carrying
    ``k`` and tokens-emitted args, multiple per-request ``token``
    instants inside a window span with strictly increasing indices —
    and the whole trace still validates through trace_check."""
    from replicatinggpt_tpu.serve import EngineConfig, ReplayConfig
    out = tmp_path / "window_trace.json"
    rcfg = ReplayConfig(n_requests=6, rate=50_000.0, seed=3,
                        prompt_len_min=4, prompt_len_max=8,
                        max_new_tokens=12, greedy=True)
    s = run_replay(params, CFG, rcfg,
                   EngineConfig(pool_size=3, max_queue=16,
                                decode_window=4),
                   trace_out=str(out))
    assert s["n_completed"] == 6
    assert s["recompiles_after_warmup"] == 0
    tc = _trace_check()
    assert tc.check_trace(str(out), min_requests=6) == []
    doc = json.loads(out.read_text())
    evs = doc["traceEvents"]
    # engine-track window spans carry k + tokens; some are real windows
    steps = [e for e in evs if e.get("ph") == "X"
             and e.get("name") == "decode_step"]
    assert steps and all("k" in e["args"] and "tokens" in e["args"]
                         for e in steps)
    assert any(e["args"]["k"] == 4 and e["args"]["tokens"] > 1
               for e in steps), "no multi-token window span in trace"
    # slot-track decode spans: one per dispatch per live request, with
    # the window's token count
    slot_spans = [e for e in evs if e.get("ph") == "X"
                  and e.get("name") == "decode"]
    assert any(e["args"].get("tokens", 0) > 1 for e in slot_spans)
    # token instants: > 1 per window span, strictly increasing per id
    toks = [e for e in evs if e.get("ph") == "i"
            and e.get("name") == "token"]
    assert toks
    by_req = {}
    for e in toks:
        by_req.setdefault(e["args"]["request"], []).append(
            e["args"]["index"])
    for rid, idxs in by_req.items():
        assert idxs == sorted(idxs) and len(set(idxs)) == len(idxs), \
            (rid, idxs)
    assert any(len(v) > 4 for v in by_req.values())


def test_trace_check_rejects_bad_token_indices(tmp_path):
    """The window-delivery check has teeth: duplicate / backwards /
    non-int token indices fail, a well-formed multi-token window
    passes."""
    tc = _trace_check()

    def write(tokens):
        env = [{"ph": "B", "name": "request", "tid": 1, "ts": 0.0,
                "args": {"request": "r"}}]
        env += [{"ph": "i", "name": "token", "tid": 1, "ts": 1.0 + i,
                 "args": {"request": "r", "index": ix}}
                for i, ix in enumerate(tokens)]
        env += [{"ph": "E", "name": "request", "tid": 1, "ts": 50.0,
                 "args": {"request": "r"}}]
        p = tmp_path / "tok.json"
        p.write_text(json.dumps({"traceEvents": env}))
        return str(p)

    assert tc.check_trace(write([1, 2, 3, 4])) == []
    assert tc.check_trace(write([3, 4, 5])) == []   # ring-buffer suffix
    assert tc.check_trace(write([1, 2, 2]))         # duplicate
    assert tc.check_trace(write([2, 1]))            # backwards
    assert tc.check_trace(write([0, 1]))            # index < 1
    assert tc.check_trace(write(["x"]))             # non-int


def test_trace_check_rejects_malformed_traces(tmp_path):
    """The validator actually validates: unclosed envelopes, crossed
    B/E, negative durations, out-of-envelope spans all fail."""
    tc = _trace_check()

    def write(events):
        p = tmp_path / "bad.json"
        p.write_text(json.dumps({"traceEvents": events}))
        return str(p)

    assert tc.check_trace(str(tmp_path / "missing.json"))
    p = tmp_path / "notjson.json"
    p.write_text("{")
    assert tc.check_trace(str(p))
    # unclosed request envelope
    assert tc.check_trace(write([
        {"ph": "B", "name": "request", "tid": 1, "ts": 0.0,
         "args": {"request": "r"}}]))
    # crossed spans
    assert tc.check_trace(write([
        {"ph": "B", "name": "a", "tid": 1, "ts": 0.0},
        {"ph": "B", "name": "b", "tid": 1, "ts": 1.0},
        {"ph": "E", "name": "a", "tid": 1, "ts": 2.0},
        {"ph": "E", "name": "b", "tid": 1, "ts": 3.0}]))
    # negative duration
    assert tc.check_trace(write([
        {"ph": "X", "name": "x", "tid": 1, "ts": 0.0, "dur": -1.0}]))
    # tagged span outside its request envelope
    assert tc.check_trace(write([
        {"ph": "B", "name": "request", "tid": 1, "ts": 10.0,
         "args": {"request": "r"}},
        {"ph": "X", "name": "decode", "tid": 1, "ts": 0.0, "dur": 2.0,
         "args": {"request": "r"}},
        {"ph": "E", "name": "request", "tid": 1, "ts": 20.0,
         "args": {"request": "r"}}]))
    # min_requests enforced
    assert tc.check_trace(write([]), min_requests=1)
    # and a valid trace still passes through the same writer
    assert tc.check_trace(write([
        {"ph": "B", "name": "request", "tid": 1, "ts": 0.0,
         "args": {"request": "r"}},
        {"ph": "X", "name": "decode", "tid": 1, "ts": 1.0, "dur": 2.0,
         "args": {"request": "r"}},
        {"ph": "E", "name": "request", "tid": 1, "ts": 5.0,
         "args": {"request": "r"}}]), min_requests=1) == []


# ---------------------------------------------------------------------------
# CLI surface (serve-replay flags incl. the mirrored profiler flags)
# ---------------------------------------------------------------------------

def test_serve_replay_cli_observability_flags(tmp_path, capsys):
    from replicatinggpt_tpu.cli import main
    tr = str(tmp_path / "trace.json")
    tl = str(tmp_path / "tl.jsonl")
    mo = str(tmp_path / "m.prom")
    prof = str(tmp_path / "prof")
    rc = main(["serve-replay", "--preset", "test-tiny", "--n-requests",
               "8", "--pool-size", "4", "--rate", "2000",
               "--request-max-new-tokens", "4", "--greedy",
               "--trace-out", tr, "--metrics-timeline", tl,
               "--metrics-out", mo,
               "--profile-dir", prof, "--profile-start", "1",
               "--profile-steps", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "8 completed" in out
    tc = _trace_check()
    assert tc.check_trace(tr, min_requests=8) == []
    assert len(MetricsTimeline.load(tl)) >= 2
    assert "requests_admitted" in Path(mo).read_text()
    # mirrored profiler flags: a real device trace landed next to the
    # span trace, from the same run
    import glob
    assert glob.glob(f"{prof}/**/*.xplane.pb", recursive=True)


def test_trace_check_cli_smoke(tmp_path):
    p = tmp_path / "t.json"
    p.write_text(json.dumps({"traceEvents": [
        {"ph": "B", "name": "request", "tid": 1, "ts": 0.0,
         "args": {"request": "r"}},
        {"ph": "E", "name": "request", "tid": 1, "ts": 5.0,
         "args": {"request": "r"}}]}))
    r = subprocess.run([sys.executable, str(REPO / "tools" /
                                            "trace_check.py"),
                        str(p), "--min-requests", "1"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "OK" in r.stdout
    r = subprocess.run([sys.executable, str(REPO / "tools" /
                                            "trace_check.py"),
                        str(p), "--min-requests", "2"],
                       capture_output=True, text=True)
    assert r.returncode == 1
    assert "expected >= 2" in r.stderr
