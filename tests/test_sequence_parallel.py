"""Sequence parallelism: ring attention (ppermute KV rotation) and Ulysses
(head<->sequence all-to-all) against the dense causal core, on the 8-device
virtual CPU mesh (conftest). Covers the capability the reference hard-caps
at a single device's block_size (GPT1.py:106, GPT-2.py:109)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replicatinggpt_tpu.config import MeshConfig, ModelConfig, TrainConfig
from replicatinggpt_tpu.ops.attention import full_causal_attention
from replicatinggpt_tpu.parallel.compat import shard_map
from replicatinggpt_tpu.parallel import (make_ring_attention_fn,
                                         make_ulysses_attention_fn,
                                         select_attention_fn)
from replicatinggpt_tpu.parallel.mesh import (make_batch_sharding, make_mesh,
                                              shard_train_state)
from replicatinggpt_tpu.parallel.ring_attention import ring_attention
from replicatinggpt_tpu.parallel.ulysses import ulysses_attention


def _qkv(B=2, H=4, T=64, D=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, H, T, D), jnp.float32) for k in ks)


def _mesh(data=1, seq=8, model=1):
    cfg = MeshConfig(data=data, seq=seq, model=model)
    return make_mesh(cfg), cfg


@pytest.mark.parametrize("axes", [(1, 8, 1), (2, 2, 2)])
@pytest.mark.slow
def test_ring_matches_dense(axes):
    data, seq, model = axes
    mesh, _ = _mesh(data, seq, model)
    q, k, v = _qkv()
    want = full_causal_attention(q, k, v)
    got = ring_attention(q, k, v, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("axes", [(1, 4, 1), (2, 2, 1)])
def test_ulysses_matches_dense(axes):
    data, seq, model = axes
    mesh, _ = _mesh(data, seq, model)
    q, k, v = _qkv()  # H=4 divisible by seq
    want = full_causal_attention(q, k, v)
    got = ulysses_attention(q, k, v, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.slow
def test_ring_gradients_match_dense():
    mesh, _ = _mesh(1, 8, 1)
    q, k, v = _qkv(T=32)

    def dense_loss(q, k, v):
        return jnp.sum(full_causal_attention(q, k, v) ** 2)

    def ring_loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh) ** 2)

    gw = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gg, gw):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_ulysses_gradients_match_dense():
    mesh, _ = _mesh(1, 4, 1)
    q, k, v = _qkv(T=32)

    def dense_loss(q, k, v):
        return jnp.sum(full_causal_attention(q, k, v) ** 2)

    def uly_loss(q, k, v):
        return jnp.sum(ulysses_attention(q, k, v, mesh=mesh) ** 2)

    gw = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(uly_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gg, gw):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_ring_under_jit_with_sharded_inputs():
    mesh, _ = _mesh(2, 2, 2)
    q, k, v = _qkv()
    from jax.sharding import NamedSharding, PartitionSpec as P
    s = NamedSharding(mesh, P("data", "model", "seq", None))
    qs, ks, vs = (jax.device_put(t, s) for t in (q, k, v))
    fn = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh=mesh))
    got = fn(qs, ks, vs)
    want = full_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


# ---------------------------------------------------------------------------
# in-core attention-weight dropout (the GPT1.py:117 capability, previously a
# documented deviation on the seq-parallel paths)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("core,axes", [("ring", (1, 4, 1)),
                                       ("ring", (2, 2, 2)),
                                       ("ulysses", (1, 4, 1))])
@pytest.mark.slow
def test_seq_parallel_dropout_statistics(core, axes):
    """q=k=0 makes weights uniform over the causal prefix; with v=1 each
    output entry is (#kept / #allowed) / (1 - rate_q), so the global mean
    estimates 1 (unbiasedness) and recovers the empirical keep rate."""
    fn = ring_attention if core == "ring" else ulysses_attention
    mesh, _ = _mesh(*axes)
    B, H, T, D = 2, 4, 128, 8
    rate, rate_q = 0.5, 128 / 256
    q = jnp.zeros((B, H, T, D), jnp.float32)
    v = jnp.ones((B, H, T, D), jnp.float32)
    out = fn(q, q, v, mesh=mesh, dropout_rate=rate,
             rng=jax.random.PRNGKey(42), train=True)
    rows = np.asarray(out)[..., 0]                     # (B, H, T)
    n_allowed = np.arange(1, T + 1, dtype=np.float64)
    keeps = rows * n_allowed * (1.0 - rate_q)
    keep_frac = keeps.sum() / (B * H * n_allowed.sum())
    assert abs(keep_frac - (1.0 - rate_q)) < 0.02, keep_frac
    assert abs(rows.mean() - 1.0) < 0.03, rows.mean()
    # deterministic in rng; decorrelated across batch/head shards
    out2 = fn(q, q, v, mesh=mesh, dropout_rate=rate,
              rng=jax.random.PRNGKey(42), train=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
    r = np.asarray(out)[..., 0]
    assert not np.array_equal(r[0], r[1]), "mask repeats across batch"
    assert not np.array_equal(r[:, 0], r[:, 1]), "mask repeats across heads"


@pytest.mark.parametrize("core", ["ring", "ulysses"])
@pytest.mark.slow
def test_seq_parallel_dropout_off_paths_unchanged(core):
    """rate=0 / train=False / rng=None must all reduce to the exact
    dropout-free computation."""
    fn = ring_attention if core == "ring" else ulysses_attention
    mesh, _ = _mesh(1, 4, 1)
    q, k, v = _qkv()
    want = np.asarray(fn(q, k, v, mesh=mesh))
    for kw in [dict(dropout_rate=0.0, rng=jax.random.PRNGKey(0), train=True),
               dict(dropout_rate=0.3, rng=jax.random.PRNGKey(0), train=False),
               dict(dropout_rate=0.3, rng=None, train=True)]:
        np.testing.assert_array_equal(
            np.asarray(fn(q, k, v, mesh=mesh, **kw)), want)


@pytest.mark.parametrize("core", ["ring", "ulysses"])
@pytest.mark.slow
def test_seq_parallel_dropout_grads_match_finite_difference(core):
    """Both cores' dropout masks regenerate deterministically from
    (rng, shard indices, and for the ring: hop, chunk) in the VJP
    recomputation, so autodiff of the fixed-seed dropout attention must
    match finite differences."""
    fn = ring_attention if core == "ring" else ulysses_attention
    mesh, _ = _mesh(1, 4, 1)
    # H=4: divisible by the seq axis, as Ulysses requires
    q, k, v = _qkv(B=1, H=4, T=32, D=8, seed=3)
    w = jax.random.normal(jax.random.PRNGKey(9), q.shape)
    rng = jax.random.PRNGKey(11)

    def loss(q, k, v):
        out = fn(q, k, v, mesh=mesh, dropout_rate=0.25, rng=rng,
                 train=True)
        return jnp.sum(out * w)

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    eps = 1e-2
    for arg, (g, rd) in enumerate(zip(
            grads, jax.random.split(jax.random.PRNGKey(13), 3))):
        d = jax.random.normal(rd, g.shape)
        d = d / jnp.linalg.norm(d)
        args = [q, k, v]
        ap = list(args); ap[arg] = args[arg] + eps * d
        am = list(args); am[arg] = args[arg] - eps * d
        fd = (loss(*ap) - loss(*am)) / (2 * eps)
        np.testing.assert_allclose(float(jnp.sum(g * d)), float(fd),
                                   rtol=2e-2, atol=2e-3)


@pytest.mark.slow
def test_ring_q_chunking_matches_unchunked():
    """Chunking only re-blocks the q rows; every row's reductions run in
    the same order, so chunked and unchunked results are identical."""
    import functools

    from jax.sharding import PartitionSpec as P

    from replicatinggpt_tpu.parallel.ring_attention import _ring_local

    mesh, _ = _mesh(1, 4, 1)
    q, k, v = _qkv(T=64)
    want = np.asarray(ring_attention(q, k, v, mesh=mesh))
    # q_chunk=4 divides T_local=16; q_chunk=5 does not and must fall
    # back to the largest divisor (4), keeping the memory bound rather
    # than silently processing the whole shard in one tile
    for q_chunk in (4, 5):
        fn = shard_map(
            functools.partial(_ring_local, axis_name="seq", scale=None,
                              q_chunk=q_chunk),
            mesh=mesh, in_specs=(P("data", "model", "seq", None),) * 3,
            out_specs=P("data", "model", "seq", None), check_vma=False)
        got = np.asarray(fn(q, k, v))
        np.testing.assert_allclose(got, want, atol=1e-6, rtol=1e-6)
    # and with dropout: chunked mask streams are keyed per chunk, so only
    # statistics (not bits) are comparable — check determinism instead
    a = shard_map(
        functools.partial(_ring_local, axis_name="seq", scale=None,
                          q_chunk=4, dropout_rate=0.3,
                          rng=jax.random.PRNGKey(5), train=True),
        mesh=mesh, in_specs=(P("data", "model", "seq", None),) * 3,
        out_specs=P("data", "model", "seq", None), check_vma=False)(q, k, v)
    b = shard_map(
        functools.partial(_ring_local, axis_name="seq", scale=None,
                          q_chunk=4, dropout_rate=0.3,
                          rng=jax.random.PRNGKey(5), train=True),
        mesh=mesh, in_specs=(P("data", "model", "seq", None),) * 3,
        out_specs=P("data", "model", "seq", None), check_vma=False)(q, k, v)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# Pallas chunk-kernel ring hops (hop_impl='flash'; interpret mode on CPU)
# ---------------------------------------------------------------------------


def _ring_fn(mesh, **kw):
    import functools

    from jax.sharding import PartitionSpec as P

    from replicatinggpt_tpu.parallel.ring_attention import _ring_local

    spec = P("data", "model", "seq", None)
    return shard_map(
        functools.partial(_ring_local, axis_name="seq", scale=None, **kw),
        mesh=mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False)


@pytest.mark.slow
def test_ring_flash_hops_match_einsum_hops():
    """hop_impl='flash' routes hops through the Pallas chunk kernel with
    lse-merged accumulation; output and grads must match the einsum ring
    (and therefore the dense core)."""
    mesh, _ = _mesh(1, 4, 1)
    q, k, v = _qkv(T=512, D=32)  # T_local=128, kernel-eligible
    want = np.asarray(_ring_fn(mesh)(q, k, v))
    got = np.asarray(_ring_fn(mesh, hop_impl="flash")(q, k, v))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)

    def loss(fn, q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    ge = jax.grad(lambda q, k, v: loss(_ring_fn(mesh), q, k, v),
                  argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(
        lambda q, k, v: loss(_ring_fn(mesh, hop_impl="flash"), q, k, v),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, ge):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_ring_flash_hop_dropout_statistics():
    """In-kernel dropout on the flash hops: uniform-weights construction
    recovers the quantized keep rate; deterministic in rng."""
    mesh, _ = _mesh(1, 2, 1)
    B, H, T, D = 1, 2, 256, 32
    rate, rate_q = 0.5, 128 / 256
    q = jnp.zeros((B, H, T, D), jnp.float32)
    v = jnp.ones((B, H, T, D), jnp.float32)
    fn = _ring_fn(mesh, hop_impl="flash", dropout_rate=rate,
                  rng=jax.random.PRNGKey(42), train=True)
    out = fn(q, q, v)
    rows = np.asarray(out)[..., 0]
    n_allowed = np.arange(1, T + 1, dtype=np.float64)
    keeps = rows * n_allowed * (1.0 - rate_q)
    keep_frac = keeps.sum() / (B * H * n_allowed.sum())
    assert abs(keep_frac - (1.0 - rate_q)) < 0.03, keep_frac
    assert abs(rows.mean() - 1.0) < 0.04, rows.mean()
    np.testing.assert_array_equal(np.asarray(out), np.asarray(fn(q, q, v)))


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
@pytest.mark.slow
def test_train_step_with_sequence_parallelism(impl):
    """Full sharded train step, seq axis 2: loss finite and close to the
    unsharded single-device step on identical init + batch."""
    from replicatinggpt_tpu.train.state import create_train_state
    from replicatinggpt_tpu.train.steps import make_train_step

    mcfg = ModelConfig(vocab_size=64, block_size=32, n_layer=2, n_head=4,
                       n_embd=64, dropout=0.0, attn_dropout=0.0,
                       dtype="float32", attention_impl=impl)
    tcfg = TrainConfig(batch_size=4, lr=1e-3)
    mesh_cfg = MeshConfig(data=2, seq=2, model=2)
    mesh = make_mesh(mesh_cfg)

    rng = np.random.default_rng(0)
    x = rng.integers(0, 64, (4, 32), dtype=np.int32)
    batch_np = (x, np.roll(x, -1, axis=1).astype(np.int32))

    # reference: unsharded train step
    state0 = create_train_state(jax.random.PRNGKey(0), mcfg, tcfg)
    step0 = make_train_step(mcfg, tcfg, donate=False)
    _, m0 = step0(state0, (jnp.asarray(batch_np[0]), jnp.asarray(batch_np[1])))

    # sharded with seq-parallel attention
    attention_fn = select_attention_fn(mcfg, mesh_cfg, mesh)
    assert attention_fn is not None
    state = shard_train_state(
        lambda: create_train_state(jax.random.PRNGKey(0), mcfg, tcfg),
        mesh, mesh_cfg)
    bs = make_batch_sharding(mesh)
    batch = (jax.device_put(batch_np[0], bs), jax.device_put(batch_np[1], bs))
    step = make_train_step(mcfg, tcfg, donate=False, attention_fn=attention_fn)
    new_state, metrics = step(state, batch)
    loss = float(jax.device_get(metrics["loss"]))
    assert np.isfinite(loss)
    np.testing.assert_allclose(loss, float(m0["loss"]), atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_chunk_fused_bwd_matches_split_kernels():
    """The kv-major fused chunk backward (default within the dq-scratch
    bound) must match the split dq + dkv chunk kernels — multi-kv-tile
    shapes, runtime offsets (incl. a partially-masked hop), dropout, and
    a loss that feeds both o and lse cotangents."""
    from replicatinggpt_tpu.ops import flash_pallas as fp

    B, H, Tq, Tk, D = 1, 2, 256, 256, 32
    q = jax.random.normal(jax.random.PRNGKey(0), (B, H, Tq, D), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, H, Tk, D), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, H, Tk, D), jnp.float32)

    def grads(q_off, rate, scratch_bytes):
        old = fp.FUSED_DQ_SCRATCH_BYTES
        fp.FUSED_DQ_SCRATCH_BYTES = scratch_bytes
        try:
            def loss(q, k, v):
                kw = dict(q_offset=jnp.int32(q_off),
                          k_offset=jnp.int32(0),
                          block_q=128, block_k=128)
                if rate > 0:
                    kw.update(dropout_rate=rate,
                              dropout_rng=jax.random.PRNGKey(9))
                o, lse = fp.pallas_flash_chunk(q, k, v, **kw)
                safe = jnp.where(jnp.isfinite(lse), lse, 0.0)
                return jnp.sum(o ** 2) + 0.1 * jnp.sum(safe ** 2)
            return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        finally:
            fp.FUSED_DQ_SCRATCH_BYTES = old

    # fully visible hop; partially masked hop; diagonal self-hop (q_off=0
    # drives the causal q-tile skip jb0 >= 1 for the later kv blocks)
    for q_off in (Tk, 128, 0):
        for rate in (0.0, 0.2):
            fused = grads(q_off, rate, fp.FUSED_DQ_SCRATCH_BYTES)
            split = grads(q_off, rate, 0)
            for a, b in zip(fused, split):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_chunk_streamed_kernels_match_resident():
    """The streamed chunk kernels (kv/q grid axis + scratch state; engaged
    past STREAM_KV_BYTES) must match the resident chunk kernels — (o, lse)
    outputs and all three grads, across runtime offsets (fully visible,
    partially masked, diagonal, fully masked hops), dropout, and a loss
    feeding both cotangents."""
    from replicatinggpt_tpu.ops import flash_pallas as fp

    B, H, Tq, Tk, D = 1, 2, 256, 256, 32
    q = jax.random.normal(jax.random.PRNGKey(0), (B, H, Tq, D), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, H, Tk, D), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, H, Tk, D), jnp.float32)

    def run(q_off, rate, stream_bytes):
        old = fp.STREAM_KV_BYTES
        fp.STREAM_KV_BYTES = stream_bytes
        try:
            kw = dict(q_offset=jnp.int32(q_off), k_offset=jnp.int32(0),
                      block_q=128, block_k=128)
            if rate > 0:
                kw.update(dropout_rate=rate,
                          dropout_rng=jax.random.PRNGKey(9))
            o, lse = fp.pallas_flash_chunk(q, k, v, **kw)

            def loss(q, k, v):
                o, lse = fp.pallas_flash_chunk(q, k, v, **kw)
                safe = jnp.where(jnp.isfinite(lse), lse, 0.0)
                return jnp.sum(o ** 2) + 0.1 * jnp.sum(safe ** 2)

            g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
            return (o, lse) + tuple(g)
        finally:
            fp.STREAM_KV_BYTES = old

    big = 4 * 1024 * 1024
    # q_off = -Tk: every (q, k) pair masked (k > q globally) -> lse -inf,
    # o = 0; the clipped finalize-at-kb==0 path must produce the same
    # (zero) grads as the resident kernels, so grads run for it too
    for q_off in (Tk, 128, 0, -Tk):
        for rate in (0.0, 0.2):
            res = run(q_off, rate, big)
            stm = run(q_off, rate, 0)
            for a, b in zip(stm, res):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=2e-4, atol=2e-4)
            if q_off == -Tk:  # fully masked: grads must actually be zero
                for gz in stm[2:]:
                    np.testing.assert_array_equal(np.asarray(gz),
                                                  np.zeros_like(gz))


@pytest.mark.slow
def test_ring_streamed_hops_match_einsum_hops(monkeypatch):
    """With STREAM_KV_BYTES forced to 0 every flash hop routes through the
    streamed chunk kernels; the ring must still match the einsum-hop ring
    (and the envelope keeps flash hops past the old resident bound)."""
    from replicatinggpt_tpu.ops import flash_pallas as fp

    monkeypatch.setattr(fp, "STREAM_KV_BYTES", 0)
    mesh, _ = _mesh(1, 4, 1)
    q, k, v = _qkv(T=512, D=32)  # T_local=128
    want = np.asarray(_ring_fn(mesh)(q, k, v))
    got = np.asarray(_ring_fn(mesh, hop_impl="flash")(q, k, v))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)

    def loss(fn, q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    ge = jax.grad(lambda q, k, v: loss(_ring_fn(mesh), q, k, v),
                  argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(
        lambda q, k, v: loss(_ring_fn(mesh, hop_impl="flash"), q, k, v),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, ge):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_flash_hop_envelope_has_no_residency_bound(monkeypatch):
    """Round-3 verdict item 4: _flash_hop_supported must not reject long
    per-device shards anymore (the streamed chunk kernels cover them)."""
    import replicatinggpt_tpu.ops.flash_attention as fa
    from replicatinggpt_tpu.parallel.ring_attention import \
        _flash_hop_supported

    monkeypatch.setattr(fa.jax, "default_backend", lambda: "tpu")
    # 64k rows x D=64 bf16 = 16 MiB K+V: far past STREAM_KV_BYTES
    q = jnp.zeros((1, 1, 65536, 64), jnp.bfloat16)
    assert _flash_hop_supported(q)
