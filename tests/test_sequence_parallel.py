"""Sequence parallelism: ring attention (ppermute KV rotation) and Ulysses
(head<->sequence all-to-all) against the dense causal core, on the 8-device
virtual CPU mesh (conftest). Covers the capability the reference hard-caps
at a single device's block_size (GPT1.py:106, GPT-2.py:109)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replicatinggpt_tpu.config import MeshConfig, ModelConfig, TrainConfig
from replicatinggpt_tpu.ops.attention import full_causal_attention
from replicatinggpt_tpu.parallel import (make_ring_attention_fn,
                                         make_ulysses_attention_fn,
                                         select_attention_fn)
from replicatinggpt_tpu.parallel.mesh import (make_batch_sharding, make_mesh,
                                              shard_train_state)
from replicatinggpt_tpu.parallel.ring_attention import ring_attention
from replicatinggpt_tpu.parallel.ulysses import ulysses_attention


def _qkv(B=2, H=4, T=64, D=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, H, T, D), jnp.float32) for k in ks)


def _mesh(data=1, seq=8, model=1):
    cfg = MeshConfig(data=data, seq=seq, model=model)
    return make_mesh(cfg), cfg


@pytest.mark.parametrize("axes", [(1, 8, 1), (2, 2, 2)])
def test_ring_matches_dense(axes):
    data, seq, model = axes
    mesh, _ = _mesh(data, seq, model)
    q, k, v = _qkv()
    want = full_causal_attention(q, k, v)
    got = ring_attention(q, k, v, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("axes", [(1, 4, 1), (2, 2, 1)])
def test_ulysses_matches_dense(axes):
    data, seq, model = axes
    mesh, _ = _mesh(data, seq, model)
    q, k, v = _qkv()  # H=4 divisible by seq
    want = full_causal_attention(q, k, v)
    got = ulysses_attention(q, k, v, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_ring_gradients_match_dense():
    mesh, _ = _mesh(1, 8, 1)
    q, k, v = _qkv(T=32)

    def dense_loss(q, k, v):
        return jnp.sum(full_causal_attention(q, k, v) ** 2)

    def ring_loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh) ** 2)

    gw = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gg, gw):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_ulysses_gradients_match_dense():
    mesh, _ = _mesh(1, 4, 1)
    q, k, v = _qkv(T=32)

    def dense_loss(q, k, v):
        return jnp.sum(full_causal_attention(q, k, v) ** 2)

    def uly_loss(q, k, v):
        return jnp.sum(ulysses_attention(q, k, v, mesh=mesh) ** 2)

    gw = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(uly_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gg, gw):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_ring_under_jit_with_sharded_inputs():
    mesh, _ = _mesh(2, 2, 2)
    q, k, v = _qkv()
    from jax.sharding import NamedSharding, PartitionSpec as P
    s = NamedSharding(mesh, P("data", "model", "seq", None))
    qs, ks, vs = (jax.device_put(t, s) for t in (q, k, v))
    fn = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh=mesh))
    got = fn(qs, ks, vs)
    want = full_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_train_step_with_sequence_parallelism(impl):
    """Full sharded train step, seq axis 2: loss finite and close to the
    unsharded single-device step on identical init + batch."""
    from replicatinggpt_tpu.train.state import create_train_state
    from replicatinggpt_tpu.train.steps import make_train_step

    mcfg = ModelConfig(vocab_size=64, block_size=32, n_layer=2, n_head=4,
                       n_embd=64, dropout=0.0, attn_dropout=0.0,
                       dtype="float32", attention_impl=impl)
    tcfg = TrainConfig(batch_size=4, lr=1e-3)
    mesh_cfg = MeshConfig(data=2, seq=2, model=2)
    mesh = make_mesh(mesh_cfg)

    rng = np.random.default_rng(0)
    x = rng.integers(0, 64, (4, 32), dtype=np.int32)
    batch_np = (x, np.roll(x, -1, axis=1).astype(np.int32))

    # reference: unsharded train step
    state0 = create_train_state(jax.random.PRNGKey(0), mcfg, tcfg)
    step0 = make_train_step(mcfg, tcfg, donate=False)
    _, m0 = step0(state0, (jnp.asarray(batch_np[0]), jnp.asarray(batch_np[1])))

    # sharded with seq-parallel attention
    attention_fn = select_attention_fn(mcfg, mesh_cfg, mesh)
    assert attention_fn is not None
    state = shard_train_state(
        lambda: create_train_state(jax.random.PRNGKey(0), mcfg, tcfg),
        mesh, mesh_cfg)
    bs = make_batch_sharding(mesh)
    batch = (jax.device_put(batch_np[0], bs), jax.device_put(batch_np[1], bs))
    step = make_train_step(mcfg, tcfg, donate=False, attention_fn=attention_fn)
    new_state, metrics = step(state, batch)
    loss = float(jax.device_get(metrics["loss"]))
    assert np.isfinite(loss)
    np.testing.assert_allclose(loss, float(m0["loss"]), atol=1e-4, rtol=1e-4)
