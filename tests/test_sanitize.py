"""Runtime-sanitizer tests (utils/sanitize.py): CompileGuard catches
deliberately-induced recompiles on BOTH the train step and the serve
decode step (the acceptance criterion), the in-bounds guard hard-fails
eager out-of-range prefill/decode writes, donation reporting behaves on
a donation-less backend, and GRAFT_SANITIZE mode toggles jax's checks."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replicatinggpt_tpu.config import ModelConfig, get_config
from replicatinggpt_tpu.models.gpt import (decode_step, init_kv_cache,
                                           init_params,
                                           prefill_chunk_into_slot)
from replicatinggpt_tpu.utils.sanitize import (CompileGuard, DonationError,
                                               RecompileError,
                                               assert_donated,
                                               check_finite,
                                               check_in_bounds,
                                               donation_report,
                                               donation_supported,
                                               sanitize_enabled, sanitized)

CFG = ModelConfig(vocab_size=65, block_size=32, n_layer=2, n_head=2,
                  n_embd=32, dropout=0.0, attn_dropout=0.0, dtype="float32")


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


# ---------------------------------------------------------------------------
# CompileGuard
# ---------------------------------------------------------------------------

def test_compile_guard_counts_and_budget():
    f = jax.jit(lambda x: x + 1)
    g = CompileGuard(f, "plus-one")
    g(jnp.ones((2,)))
    g(jnp.ones((2,)))                       # cache hit: still 1 program
    assert g.compiles == 1 and g.calls == 2
    with pytest.raises(RecompileError, match="plus-one"):
        g(jnp.ones((3,)))                   # new shape: budget exceeded
    assert g.expect(2).check() == 2         # widened budget: now legal
    assert g.stats() == {"calls": 3, "compiles": 2, "budget": 2}


def test_compile_guard_relative_to_construction():
    """Module-jit caches accumulate across owners; a guard built after
    warmup must count only growth since ITS construction."""
    f = jax.jit(lambda x: x * 2)
    f(jnp.ones((4,)))                       # pre-existing program
    g = CompileGuard(f, "warm")
    g(jnp.ones((4,)))                       # same shape: zero growth
    assert g.compiles == 0


def test_compile_guard_catches_train_step_recompile():
    """Acceptance: a deliberately-induced recompile of the TRAIN step
    (batch shape change mid-run) raises instead of silently retracing."""
    from replicatinggpt_tpu.train.steps import make_train_step
    tiny = get_config("test-tiny")
    step = CompileGuard(make_train_step(tiny.model, tiny.train),
                        "train/step")
    from replicatinggpt_tpu.train.state import create_train_state
    state = create_train_state(jax.random.PRNGKey(0), tiny.model, tiny.train)
    x = jnp.zeros((4, tiny.model.block_size), jnp.int32)
    state, _ = step(state, (x, x))
    state, _ = step(state, (x, x))          # steady state: one program
    assert step.compiles == 1
    bad = jnp.zeros((5, tiny.model.block_size), jnp.int32)
    with pytest.raises(RecompileError, match="train/step"):
        step(state, (bad, bad))


def test_compile_guard_catches_serve_decode_recompile(params):
    """Acceptance: a deliberately-induced recompile of the serve DECODE
    step (per-slot sampling array flips dtype) raises from engine.step()."""
    from replicatinggpt_tpu.serve import Engine, EngineConfig
    from replicatinggpt_tpu.serve.requests import Request, SamplingParams
    # pool_size=7 is used by NO other test: the decode program must be
    # cold here, so the warm drain is this guard's one budgeted compile
    # and the induced f16 recompile is the over-budget second. (With a
    # pre-warmed program — e.g. the chaos suite's pool-2 engines ran
    # first — the warm drain would compile nothing and the induced
    # recompile would fit the budget, vacuously passing.)
    eng = Engine(params, CFG, EngineConfig(pool_size=7, max_queue=8))
    eng.submit(Request(id="a", prompt=np.array([1, 2], np.int32),
                       max_new_tokens=2,
                       sampling=SamplingParams(greedy=True)))
    eng.drain()                              # warm: one decode program
    assert eng._decode_guard.compiles == 1
    # induce a jit-key change: f16 survives jnp.asarray (f64 would be
    # silently downcast back to f32 under jax's x32 default)
    eng._temp = eng._temp.astype(np.float16)
    eng.submit(Request(id="b", prompt=np.array([3], np.int32),
                       max_new_tokens=2,
                       sampling=SamplingParams(greedy=True)))
    with pytest.raises(RecompileError, match="serve/decode"):
        eng.drain()


def test_compile_guard_ignores_other_engines_compiles(params):
    """Guards over the SHARED module-level jits must attribute only
    compiles that happen during their own calls: a second engine with
    a different pool shape compiling new programs must not trip the
    first engine's guard."""
    from replicatinggpt_tpu.serve import Engine, EngineConfig
    from replicatinggpt_tpu.serve.requests import Request, SamplingParams

    def req(rid):
        return Request(id=rid, prompt=np.array([1, 2], np.int32),
                       max_new_tokens=2,
                       sampling=SamplingParams(greedy=True))

    eng1 = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=8))
    eng1.submit(req("a"))
    eng1.drain()
    # different pool shape: compiles fresh programs into the SAME jits
    eng2 = Engine(params, CFG, EngineConfig(pool_size=3, max_queue=8))
    eng2.submit(req("b"))
    eng2.drain()
    eng1.submit(req("c"))                    # pure cache hit for eng1
    res = eng1.drain()                       # must NOT raise
    assert len(res) == 1
    assert eng1._decode_guard.compiles <= 1


def test_train_runner_wraps_step_in_guard(tmp_path):
    """The runner's train step is guarded end-to-end (steady state: no
    raise, guard visible on the returned history path)."""
    from replicatinggpt_tpu.train.runner import train
    tiny = get_config("test-tiny")
    cfg = tiny.replace(
        train=dataclasses.replace(tiny.train, max_iters=3, eval_interval=0,
                                  eval_iters=2, log_interval=0,
                                  batch_size=2),
        dataset="datasets/shakespeare.txt")
    res = train(cfg)                         # would raise on any recompile
    assert int(jax.device_get(res.state.step)) == 3


# ---------------------------------------------------------------------------
# check_in_bounds (the GL006 sanctioned guard)
# ---------------------------------------------------------------------------

def test_check_in_bounds_concrete():
    assert check_in_bounds(3, 2, 8)
    assert check_in_bounds(np.int32(0), 8, 8)
    assert check_in_bounds(jnp.int32(6), 2, 8)      # concrete jax scalar
    assert check_in_bounds(np.array([1, 5, 3]), 2, 8)
    with pytest.raises(IndexError, match="CLAMP"):
        check_in_bounds(7, 2, 8)
    with pytest.raises(IndexError):
        check_in_bounds(-1, 1, 8)
    with pytest.raises(IndexError):
        check_in_bounds(np.array([0, 7]), 2, 8)     # max row out of range


def test_check_in_bounds_traced_is_noop():
    @jax.jit
    def f(buf, row, p):
        assert not check_in_bounds(p, 1, buf.shape[0])  # tracer: unchecked
        return jax.lax.dynamic_update_slice(buf, row, (p,))

    out = f(jnp.zeros((4,)), jnp.ones((1,)), jnp.int32(2))
    assert float(out[2]) == 1.0


def test_prefill_chunk_guard_rejects_out_of_bounds(params):
    """Eager chunked prefill past the slot buffer must hard-fail (the
    exact clamp-corruption path of PR 1), valid offsets must work."""
    cache = init_kv_cache(CFG, 2)
    chunk = jnp.zeros((1, 8), jnp.int32)
    ok = prefill_chunk_into_slot(params, chunk, jnp.int32(24), jnp.int32(0),
                                 cache, CFG)
    assert ok["k"].shape == cache["k"].shape
    with pytest.raises(IndexError, match="prefill chunk write"):
        prefill_chunk_into_slot(params, chunk, jnp.int32(28), jnp.int32(0),
                                cache, CFG)          # 28 + 8 > 32
    with pytest.raises(IndexError, match="slot"):
        prefill_chunk_into_slot(params, chunk, jnp.int32(0), jnp.int32(2),
                                cache, CFG)          # slot 2 of pool of 2


def test_decode_step_guard_rejects_out_of_bounds(params):
    cache = init_kv_cache(CFG, 1)
    tok = jnp.zeros((1,), jnp.int32)
    with pytest.raises(IndexError, match="decode_step cache write"):
        decode_step(params, tok, jnp.int32(CFG.block_size), cache, CFG)


# ---------------------------------------------------------------------------
# donation verification
# ---------------------------------------------------------------------------

def test_donation_report_counts_deleted_and_live():
    a, b = jnp.ones((4,)), jnp.ones((4,))
    a.delete()
    rep = donation_report({"a": a, "b": b})
    assert rep == {"deleted": 1, "live": 1}


def test_assert_donated_skips_on_unsupported_backend():
    """CPU ignores donation; asserting would always fail, so the check
    reports 'unchecked' (False) instead of raising."""
    assert not donation_supported()          # tests force JAX_PLATFORMS=cpu
    live = {"w": jnp.ones((4,))}
    assert assert_donated(live) is False     # no DonationError on CPU


def test_assert_donated_raises_when_supported(monkeypatch):
    monkeypatch.setattr("replicatinggpt_tpu.utils.sanitize."
                        "donation_supported", lambda: True)
    live = {"w": jnp.ones((4,))}
    with pytest.raises(DonationError, match="still alive"):
        assert_donated(live, what="train state")
    gone = jnp.ones((2,))
    gone.delete()
    assert assert_donated({"w": gone}) is True


# ---------------------------------------------------------------------------
# GRAFT_SANITIZE mode
# ---------------------------------------------------------------------------

def test_sanitize_enabled_env(monkeypatch):
    monkeypatch.delenv("GRAFT_SANITIZE", raising=False)
    assert not sanitize_enabled()
    monkeypatch.setenv("GRAFT_SANITIZE", "0")
    assert not sanitize_enabled()
    monkeypatch.setenv("GRAFT_SANITIZE", "1")
    assert sanitize_enabled()


def test_sanitized_context_toggles_and_restores():
    assert not jax.config.jax_debug_nans
    with sanitized(True) as on:
        assert on
        assert jax.config.jax_debug_nans
        assert jax.config.jax_check_tracer_leaks
        with pytest.raises(FloatingPointError):
            jnp.log(jnp.float32(-1.0))       # NaN raises inside the block
    assert not jax.config.jax_debug_nans
    assert not jax.config.jax_check_tracer_leaks
    with sanitized(False) as on:
        assert not on and not jax.config.jax_debug_nans


def test_check_finite():
    check_finite(1.25, "loss")
    with pytest.raises(FloatingPointError, match="train loss"):
        check_finite(float("nan"), "train loss")


def test_engine_sanitize_validates_tokens(monkeypatch, params):
    """GRAFT_SANITIZE=1 on the engine: a healthy run passes the token
    range check; an out-of-range fetch raises."""
    monkeypatch.setenv("GRAFT_SANITIZE", "1")
    from replicatinggpt_tpu.serve import Engine, EngineConfig
    from replicatinggpt_tpu.serve.requests import Request, SamplingParams
    eng = Engine(params, CFG, EngineConfig(pool_size=1, max_queue=4))
    assert eng._sanitize
    eng.submit(Request(id="a", prompt=np.array([1], np.int32),
                       max_new_tokens=3,
                       sampling=SamplingParams(greedy=True)))
    res = eng.drain()
    assert len(res) == 1 and len(res[0].tokens) == 3


# ---------------------------------------------------------------------------
# the slow sanitize tier: full train + serve under GRAFT_SANITIZE=1
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.sanitize
def test_sanitize_mode_tiny_train_and_serve(monkeypatch, params):
    """GRAFT_SANITIZE=1 end-to-end: a tiny real-corpus training run and
    a replay through the serving engine both complete under jax's
    tracer-leak + NaN checks (and the engine's token validation)."""
    monkeypatch.setenv("GRAFT_SANITIZE", "1")
    from replicatinggpt_tpu.serve import EngineConfig, ReplayConfig, run_replay
    from replicatinggpt_tpu.train.runner import train
    tiny = get_config("test-tiny")
    cfg = tiny.replace(
        train=dataclasses.replace(tiny.train, max_iters=12, eval_interval=6,
                                  eval_iters=2, log_interval=4,
                                  batch_size=4),
        dataset="datasets/shakespeare.txt")
    res = train(cfg)
    assert np.isfinite(res.final_eval["val"])
    s = run_replay(params, CFG,
                   ReplayConfig(n_requests=8, rate=2000.0, seed=0,
                                prompt_len_max=12, max_new_tokens=4,
                                greedy=True),
                   EngineConfig(pool_size=2, max_queue=16))
    assert s["n_completed"] == 8
    assert s["recompiles_after_warmup"] == 0
