"""Host-side multi-process glue that can be tested without a pod: the
sharded SequentialBatcher must tile the exact single-host token stream."""

import pytest

pytestmark = pytest.mark.slow  # multi-process spawns
import numpy as np

from replicatinggpt_tpu.data.loader import SequentialBatcher


def test_sequential_shards_tile_the_global_stream():
    data = np.arange(4 * 4 * 8 * 3 + 1, dtype=np.int64)  # 3 global windows
    B_global, T, n = 8, 4, 4
    B_local = B_global // n
    ref = SequentialBatcher(data, B_global, T)
    shards = [SequentialBatcher(data, B_local, T, shard=(i, n))
              for i in range(n)]
    for _ in range(5):  # crosses the wraparound
        gx, gy = ref.next_batch()
        parts = [s.next_batch() for s in shards]
        x = np.concatenate([p[0] for p in parts], axis=0)
        y = np.concatenate([p[1] for p in parts], axis=0)
        np.testing.assert_array_equal(x, gx)
        np.testing.assert_array_equal(y, gy)
    # cursor is global state: identical on every shard
    assert len({s.position for s in shards}) == 1
    assert shards[0].position == ref.position
