"""Sharded serving (ISSUE 12): the async engine on a (data, model)
mesh. Acceptance: greedy streams token-identical between a
single-device engine and a forced-multi-device-CPU 2x2 mesh engine
through a trace containing prefix hits, COW splits, LRU eviction and a
mid-window admission; compile_counts flat after warmup with
recompiles_after_warmup == 0 on the mesh path; the sampled token block
leaves the device fully replicated (the host fetch is a local read);
the pages block reports per-chip and aggregate utilization; the
multiproc engine-flag forwarding round-trips the mesh slice; and the
graftlint mesh rules (GL010-14) run clean over the sharded serve path.

Mesh tests skip below 4 devices so tier-1 stays green on one device
(tests/conftest.py forces 8 CPU devices, so they RUN in tier-1)."""

import dataclasses

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from replicatinggpt_tpu.config import ModelConfig
from replicatinggpt_tpu.models.gpt import init_params
from replicatinggpt_tpu.sample import GenerateConfig, generate
from replicatinggpt_tpu.serve import (Engine, EngineConfig, Request,
                                      SamplingParams, compile_counts)

CFG = ModelConfig(vocab_size=65, block_size=32, n_layer=2, n_head=2,
                  n_embd=32, dropout=0.0, attn_dropout=0.0,
                  dtype="float32")

needs4 = pytest.mark.skipif(
    jax.device_count() < 4,
    reason="needs >= 4 devices (JAX_PLATFORMS=cpu with XLA_FLAGS="
           "--xla_force_host_platform_device_count=4; tests/conftest.py "
           "forces 8, so tier-1 runs these)")

#: the acceptance mesh: pages sharded 2-way over 'data', TP 2-way over
#: 'model' (n_head=2, n_embd=32 both divide)
MESH = dict(mesh_data=2, mesh_model=2)


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _greedy(rid, prompt, max_new=4, eos=None):
    return Request(id=rid, prompt=np.asarray(prompt, np.int32),
                   max_new_tokens=max_new,
                   sampling=SamplingParams(greedy=True),
                   eos_token_id=eos)


def _offline_greedy(params, reqs, cfg=CFG):
    return {r.id: np.asarray(generate(
        params, r.prompt[None, :], cfg,
        GenerateConfig(max_new_tokens=min(
            r.max_new_tokens, cfg.block_size - int(r.prompt.size) + 1),
            greedy=True)))[0].tolist() for r in reqs}


def _pressure_trace(n=10, max_new=4):
    """The test_pages eviction trace shape: a shared page-aligned
    prompt every third request (prefix hit + full-prompt COW) among
    random prompts that overrun a 6-page pool (LRU evictions)."""
    rng = np.random.default_rng(1)
    shared = ((np.arange(16) % 9) + 2).astype(np.int32)
    reqs = []
    for i in range(n):
        if i % 3 == 0:
            prompt = shared.copy()
        else:
            prompt = rng.integers(0, CFG.vocab_size, (int(
                rng.integers(3, 20)),)).astype(np.int32)
        reqs.append(_greedy(f"e{i}", prompt, max_new=max_new))
    return shared, reqs


def _run(params, ecfg, reqs):
    eng = Engine(params, CFG, ecfg)
    for r in reqs:
        assert eng.submit(r) is None, r.id
    return eng, {r.id: r.tokens for r in eng.drain()}


# ---------------------------------------------------------------------------
# acceptance: greedy parity 1x1 vs 2x2 through prefix/COW/eviction,
# zero recompiles in mesh steady state
# ---------------------------------------------------------------------------

@needs4
def test_mesh_greedy_parity_prefix_cow_eviction(params):
    """The ISSUE 12 acceptance bar: the SAME trace (prefix hits, COW
    splits, evictions under a 6-page pool) through a single-device and
    a 2x2-mesh engine produces byte-identical greedy streams — and the
    mesh engine matches offline generate() too (sharding changed the
    layout, not the math)."""
    shared, reqs = _pressure_trace()
    base = EngineConfig(pool_size=2, max_queue=64, page_size=8,
                        n_pages=6)
    want = _offline_greedy(params, reqs)
    e1, got1 = _run(params, base, [dataclasses.replace(r) for r in reqs])
    e2, got2 = _run(params, dataclasses.replace(base, **MESH),
                    [dataclasses.replace(r) for r in reqs])
    assert got1 == got2
    assert got2 == want
    pg = e2.metrics_summary()["pages"]
    assert pg["evictions"] > 0 and pg["cow_copies"] > 0
    assert pg["prefix_hit_tokens"] > 0
    # the mesh engine's host bookkeeping is untouched by sharding
    assert e2.pool.alloc.ref.max() == 0
    assert e2.mesh is not None and e2.mesh.size == 4


@needs4
def test_mesh_zero_recompiles_at_steady_state(params):
    """compile_counts stays pinned flat across a SECOND mesh replay
    containing admissions + hits + evictions + COW — the zero-recompile
    steady state survives sharding (every program keys on the engine's
    static ServeShardings, so the sharded variants compiled once at
    warmup are the only ones that ever exist)."""
    _, reqs = _pressure_trace()
    ecfg = EngineConfig(pool_size=2, max_queue=64, page_size=8,
                        n_pages=6, decode_window=4, **MESH)
    eng, _ = _run(params, ecfg, reqs)          # warmup: compiles happen
    base = compile_counts()
    _, reqs2 = _pressure_trace()
    for r in reqs2:
        assert eng.submit(_greedy("x" + r.id, r.prompt,
                                  r.max_new_tokens)) is None
    eng.drain()
    assert compile_counts() == base
    for name, g in eng.metrics_summary()["compile_guards"].items():
        assert g["compiles"] <= g["budget"], (name, g)


@needs4
def test_mesh_mid_window_admission_parity(params):
    """A request arriving while a 4-step window is in flight on the
    mesh: the window drains at the boundary, the admission runs the k=1
    fallback, and both streams stay identical to the 1x1 engine's."""
    rng = np.random.default_rng(7)
    reqs = [_greedy(f"r{i}", rng.integers(0, CFG.vocab_size, (int(
        rng.integers(2, 15)),)).astype(np.int32), max_new=20)
        for i in range(3)]

    def run(ecfg):
        eng = Engine(params, CFG, ecfg)
        assert eng.submit(dataclasses.replace(reqs[0])) is None
        out = []
        out.extend(eng.step())                 # admission (blocked k=1)
        out.extend(eng.step())                 # window launched
        assert eng._inflight is not None, "window should be in flight"
        assert eng.submit(dataclasses.replace(reqs[1])) is None
        assert eng.submit(dataclasses.replace(reqs[2])) is None
        out.extend(eng.drain())
        return {r.id: r.tokens for r in out}

    base = EngineConfig(pool_size=2, max_queue=8, decode_window=4)
    assert run(base) == run(dataclasses.replace(base, **MESH))


@needs4
def test_mesh_spec_verify_parity(params):
    """Speculative decoding on the mesh: the paged verify program runs
    TP-sharded (drafter stays single-device host-side) and greedy
    streams match both the 1x1 spec engine and the plain mesh engine."""
    from replicatinggpt_tpu.serve.speculative import make_drafter
    pat = (np.arange(3) % CFG.vocab_size).astype(np.int32) + 3
    reqs = [_greedy(f"s{i}", np.tile(pat, 4 + i)[:12 + i], max_new=6)
            for i in range(3)]
    base = EngineConfig(pool_size=2, max_queue=8, page_size=8)

    def run(ecfg, spec):
        dr = make_drafter("ngram" if spec else "off", 3, 3,
                          ecfg.pool_size, None, None, 0)
        eng = Engine(params, CFG, ecfg, drafter=dr)
        for r in reqs:
            assert eng.submit(dataclasses.replace(r)) is None
        out = {r.id: r.tokens for r in eng.drain()}
        return eng, out

    _, spec1 = run(base, True)
    eng, spec2 = run(dataclasses.replace(base, **MESH), True)
    _, plain = run(dataclasses.replace(base, **MESH), False)
    assert spec1 == spec2 == plain
    g = eng.metrics_summary()["compile_guards"]["verify"]
    assert g["compiles"] <= g["budget"]


# ---------------------------------------------------------------------------
# sharding mechanics: replicated token block, pinned pool layout
# ---------------------------------------------------------------------------

@needs4
def test_mesh_token_block_replicated_and_pool_pinned(params):
    """The async fetch contract under sharding: the in-flight window's
    (k, n_slots) token block is FULLY REPLICATED (np.asarray reads a
    local shard — no cross-device gather on the host path), and the
    page pool's committed sharding survives every dispatch exactly
    (donation aliased, no GSPMD drift between windows)."""
    ecfg = EngineConfig(pool_size=2, max_queue=8, page_size=8,
                        decode_window=4, **MESH)
    eng = Engine(params, CFG, ecfg)
    pool_sharding = eng.pool.cache["k"].sharding
    assert pool_sharding == eng._plan.cache
    spec = eng._plan.cache.spec
    assert spec[1] == "data", spec             # page axis over 'data'
    assert "model" in spec, spec               # model dim over 'model'
    assert eng.submit(_greedy("a", np.arange(1, 10), max_new=16)) is None
    eng.step()                                 # admission
    eng.step()                                 # steady state: window up
    assert eng._inflight is not None
    assert eng._inflight.toks.sharding.is_fully_replicated
    assert eng._inflight.emitted.sharding.is_fully_replicated
    eng.drain()
    assert eng.pool.cache["k"].sharding == pool_sharding
    assert eng.pool.cache["v"].sharding == pool_sharding


@needs4
def test_mesh_pages_per_chip_and_aggregate_stats(params):
    """metrics_summary()['pages'] on a mesh: aggregate_pages stays the
    admission currency, pages_per_chip is the per-device HBM share of
    it, and the by-chip occupancy splits the in-use count exactly."""
    ecfg = EngineConfig(pool_size=2, max_queue=8, page_size=8,
                        n_pages=8, **MESH)
    eng = Engine(params, CFG, ecfg)
    assert eng.submit(_greedy("a", np.arange(1, 17), max_new=4)) is None
    eng.step()
    pg = eng.metrics_summary()["pages"]
    assert pg["mesh_shape"] == [2, 2]
    assert pg["aggregate_pages"] == 8 and pg["pages_per_chip"] == 4
    assert len(pg["pages_in_use_by_chip"]) == 2
    assert sum(pg["pages_in_use_by_chip"]) == pg["pages_in_use"]
    assert len(pg["page_utilization_by_chip"]) == 2
    eng.drain()


def test_page_pool_pspec_layouts_and_divisibility():
    """The design-first layout (parallel.mesh): packed pools shard C
    over 'model', heads pools shard H; the page axis shards over
    'data'; non-divisible dims drop their axis (never pad-shard); and
    trailing Nones are trimmed to the jit-normalized representation
    (the representation IS the jit cache key)."""
    from replicatinggpt_tpu.parallel.mesh import page_pool_pspec
    heads = CFG
    packed = dataclasses.replace(CFG, decode_cache_layout="packed")
    assert page_pool_pspec(heads, 8, 2, 2) == P(None, "data", "model")
    assert page_pool_pspec(packed, 8, 2, 2) == \
        P(None, "data", None, "model")
    # 7 pages on data=2: page axis drops to replication
    assert page_pool_pspec(heads, 7, 2, 2) == P(None, None, "model")
    # n_head=2 on model=4: TP axis drops (heads layout)
    assert page_pool_pspec(heads, 8, 2, 4) == P(None, "data")
    # fully non-divisible -> fully replicated, trimmed to P()
    assert page_pool_pspec(heads, 7, 2, 4) == P()


# ---------------------------------------------------------------------------
# satellites: multiproc forwarding round-trip, graftlint mesh rules
# ---------------------------------------------------------------------------

def test_engine_forward_args_round_trips_mesh_shape():
    """`serve --multiproc` must spawn workers owning the SAME engine
    shape — mesh slice included: every add_engine_flags knob set on the
    parent survives engine_forward_args -> a fresh serve-worker-style
    parser -> engine_config_from_args (the PR 9 model-override
    round-trip, applied to the engine flags)."""
    import argparse

    from replicatinggpt_tpu.cli import (add_engine_flags,
                                        engine_config_from_args,
                                        engine_forward_args)

    def parse(argv):
        p = argparse.ArgumentParser()
        add_engine_flags(p)
        return p.parse_args(argv)

    argv = ["--pool-size", "4", "--max-queue", "32", "--prefill-chunk",
            "16", "--page-size", "8", "--n-pages", "24",
            "--decode-window", "4", "--mesh-shape", "2x2",
            "--no-prefix-cache"]
    parent = parse(argv)
    forwarded = parse(engine_forward_args(parent))
    assert engine_config_from_args(forwarded) == \
        engine_config_from_args(parent)
    if jax.device_count() >= 4:
        assert engine_config_from_args(parent).mesh_shape == (2, 2)


def test_mesh_shape_downgrades_past_device_count(capsys):
    """A mesh the process cannot satisfy runs unsharded with a warning
    (the _build_mesh_if_needed convention), never crashes."""
    import argparse

    from replicatinggpt_tpu.cli import (add_engine_flags,
                                        engine_config_from_args)
    p = argparse.ArgumentParser()
    add_engine_flags(p)
    args = p.parse_args(["--mesh-shape", "64x64"])
    ecfg = engine_config_from_args(args)
    assert ecfg.mesh_shape == (1, 1)
    assert "running unsharded" in capsys.readouterr().err


def test_parse_mesh_shape_formats():
    from replicatinggpt_tpu.parallel.mesh import parse_mesh_shape
    assert parse_mesh_shape("2x2") == (2, 2)
    assert parse_mesh_shape("4,1") == (4, 1)
    assert parse_mesh_shape("1X2") == (1, 2)
    for bad in ("", "2", "2x2x2", "0x2", "ax2"):
        with pytest.raises(ValueError):
            parse_mesh_shape(bad)


def test_graftlint_mesh_rules_clean_over_sharded_serve_path():
    """GL010-14 (the mesh/sharding family) over the files this PR
    shards — zero findings, zero pragmas (the PR 6 parallel/+serve/
    pin, extended to the sharded serve path)."""
    from pathlib import Path

    from replicatinggpt_tpu.analysis import lint_paths
    repo = Path(__file__).resolve().parent.parent / "replicatinggpt_tpu"
    res = lint_paths(
        [repo / "serve", repo / "parallel" / "mesh.py",
         repo / "models" / "gpt.py"],
        ["GL010", "GL011", "GL012", "GL013", "GL014"],
        severity={})
    assert not res.findings, [f.format() for f in res.findings]
    assert not res.warnings, [f.format() for f in res.warnings]
