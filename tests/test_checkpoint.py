"""Checkpoint tests: full-state roundtrip, resume continues identically,
data-loader cursor restoration, latest-step selection."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replicatinggpt_tpu.config import get_config
from replicatinggpt_tpu.data import SequentialBatcher
from replicatinggpt_tpu.train.checkpoint import CheckpointManager
from replicatinggpt_tpu.train.state import create_train_state
from replicatinggpt_tpu.train.steps import make_train_step


@pytest.fixture()
def tiny():
    return get_config("test-tiny")


def _trees_equal(a, b):
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


@pytest.mark.slow
def test_roundtrip_full_state(tiny, tmp_path):
    m, t = tiny.model, tiny.train
    state = create_train_state(jax.random.PRNGKey(0), m, t)
    step = make_train_step(m, t, donate=False)
    x = jax.random.randint(jax.random.PRNGKey(1), (4, m.block_size), 0,
                           m.vocab_size)
    for _ in range(3):
        state, _ = step(state, (x, x))
    ck = CheckpointManager(str(tmp_path / "ck"))
    ck.save(state, wait=True)
    restored = ck.restore(3, state)
    _trees_equal(state, restored)
    assert int(restored.step) == 3
    ck.close()


@pytest.mark.slow
def test_resume_training_is_identical(tiny, tmp_path):
    """Save at step 2, keep training to 5; restore at 2 and retrain to 5 —
    final params must be bit-identical (step-keyed dropout RNG makes the
    tail deterministic)."""
    m, t = tiny.model, tiny.train
    step = make_train_step(m, t, donate=False)
    x = jax.random.randint(jax.random.PRNGKey(1), (4, m.block_size), 0,
                           m.vocab_size)
    state = create_train_state(jax.random.PRNGKey(0), m, t)
    ck = CheckpointManager(str(tmp_path / "ck"))
    for _ in range(2):
        state, _ = step(state, (x, x))
    ck.save(state, wait=True)
    cont = state
    for _ in range(3):
        cont, _ = step(cont, (x, x))
    resumed = ck.restore(2, state)
    for _ in range(3):
        resumed, _ = step(resumed, (x, x))
    _trees_equal(cont.params, resumed.params)
    ck.close()


def test_batcher_cursor_roundtrip(tiny, tmp_path):
    m, t = tiny.model, tiny.train
    state = create_train_state(jax.random.PRNGKey(0), m, t)
    data = np.arange(5000, dtype=np.int32)
    b = SequentialBatcher(data, 4, m.block_size)
    b.next_batch(); b.next_batch()
    expected_next, _ = SequentialBatcher(data, 4, m.block_size), None
    ck = CheckpointManager(str(tmp_path / "ck"))
    ck.save(state, batcher=b, wait=True)
    want, _ = b.next_batch()
    b2 = SequentialBatcher(data, 4, m.block_size)
    ck.restore(0, state, batcher=b2)
    got, _ = b2.next_batch()
    np.testing.assert_array_equal(want, got)
    ck.close()


@pytest.mark.slow
def test_latest_step(tiny, tmp_path):
    m, t = tiny.model, tiny.train
    state = create_train_state(jax.random.PRNGKey(0), m, t)
    ck = CheckpointManager(str(tmp_path / "ck"))
    assert ck.latest_step() is None
    assert ck.restore_latest(state) is None
    ck.save(state, wait=True)
    step = make_train_step(m, t, donate=False)
    x = jax.random.randint(jax.random.PRNGKey(1), (4, m.block_size), 0,
                           m.vocab_size)
    state2, _ = step(state, (x, x))
    ck.save(state2, wait=True)
    assert ck.latest_step() == 1
    r = ck.restore_latest(state)
    assert int(r.step) == 1
    ck.close()


@pytest.mark.slow
def test_graceful_stop_checkpoints_and_resumes(tmp_path):
    """stop_event mid-run saves a resumable checkpoint (the preemption
    path, SURVEY.md §5 failure-detection row: the reference loses the whole
    run on any interruption)."""
    import dataclasses

    from replicatinggpt_tpu.config import get_config
    from replicatinggpt_tpu.train.checkpoint import CheckpointManager
    from replicatinggpt_tpu.train.runner import train

    cfg = get_config("test-tiny")
    cfg = cfg.replace(
        train=dataclasses.replace(cfg.train, max_iters=500, eval_interval=0,
                                  eval_iters=2, log_interval=0),
        dataset="datasets/shakespeare.txt")
    ck = CheckpointManager(str(tmp_path / "ck"))

    class StopAfterPolls:
        """Duck-typed Event whose flag raises after N loop-top polls —
        deterministic, unlike a wall-clock timer racing the train loop."""

        def __init__(self, n):
            self.polls, self.n = 0, n

        def is_set(self):
            self.polls += 1
            return self.polls > self.n

    stop = StopAfterPolls(7)
    res = train(cfg, checkpoint_manager=ck, stop_event=stop)
    ck.wait()
    stopped_at = int(jax.device_get(res.state.step))
    assert stopped_at == 7, "stop polled once per loop iteration"
    assert ck.latest_step() == stopped_at

    # resume picks up exactly where the stop left off
    ck2 = CheckpointManager(str(tmp_path / "ck"))
    cfg2 = cfg.replace(train=dataclasses.replace(cfg.train,
                                                 max_iters=stopped_at + 5))
    res2 = train(cfg2, checkpoint_manager=ck2, resume=True)
    ck2.wait()
    assert int(jax.device_get(res2.state.step)) == stopped_at + 5


def test_save_is_idempotent_per_step(tmp_path):
    # periodic save + graceful stop + end-of-run can all land on one step;
    # orbax raises StepAlreadyExistsError on duplicates, we must not
    import dataclasses
    import jax.numpy as jnp

    from replicatinggpt_tpu.config import get_config
    from replicatinggpt_tpu.train.checkpoint import CheckpointManager
    from replicatinggpt_tpu.train.state import create_train_state

    cfg = get_config("test-tiny")
    state = create_train_state(jax.random.PRNGKey(0), cfg.model, cfg.train)
    ck = CheckpointManager(str(tmp_path / "ck"))
    assert ck.save(state, wait=True) == 0
    assert ck.save(state, wait=True) == 0  # no raise
    assert ck.latest_step() == 0


def test_restore_rejects_mismatched_rng_impl(tmp_path):
    # threefry keys are shape (2,), rbg (4,): resuming across impls must
    # fail loudly, not with a cryptic orbax shape error
    import dataclasses
    import jax.numpy as jnp
    import pytest

    from replicatinggpt_tpu.config import get_config
    from replicatinggpt_tpu.train.checkpoint import CheckpointManager
    from replicatinggpt_tpu.train.state import create_train_state

    cfg = get_config("test-tiny")
    state = create_train_state(jax.random.PRNGKey(0), cfg.model, cfg.train)
    ck = CheckpointManager(str(tmp_path / "ck"))
    ck.save(state, wait=True)
    template = state._replace(rng=jnp.zeros((4,), jnp.uint32))
    with pytest.raises(ValueError, match="PRNG impl"):
        ck.restore_latest(template)


@pytest.mark.slow
def test_sharded_resume_restores_mesh_layout(tmp_path):
    """FSDP-mesh run: checkpoint at step 5, resume to 10 — restored leaves
    must carry their mesh shardings (an FSDP model must never restore
    replicated) and the continued run must be bit-identical to an
    uninterrupted 10-step run."""
    from replicatinggpt_tpu.config import MeshConfig, get_config
    from replicatinggpt_tpu.parallel.mesh import make_mesh, state_pspecs
    from replicatinggpt_tpu.train.runner import train

    cfg = get_config("test-tiny")
    mesh_cfg = MeshConfig(data=8, fsdp=True)
    base = cfg.replace(
        train=dataclasses.replace(cfg.train, max_iters=10, eval_interval=0,
                                  eval_iters=2, log_interval=0, batch_size=8,
                                  checkpoint_every=5),
        mesh=mesh_cfg, dataset="datasets/shakespeare.txt")
    mesh = make_mesh(mesh_cfg)
    full = train(base, mesh=mesh)

    ck = CheckpointManager(str(tmp_path / "ck"))
    first = base.replace(train=dataclasses.replace(base.train, max_iters=5))
    train(first, mesh=mesh, checkpoint_manager=ck)
    ck.wait()
    resumed = train(base, mesh=mesh, checkpoint_manager=ck, resume=True)
    assert int(jax.device_get(resumed.state.step)) == 10

    # every restored param kept its FSDP layout (state_pspecs is the
    # oracle; equivalence, not spec equality — jax normalizes size-1 axes
    # and trailing Nones when reporting a live array's sharding)
    from jax.sharding import NamedSharding
    specs = state_pspecs(resumed.state, mesh_cfg).params
    mismatched = []
    for (path, leaf), (_, spec) in zip(
            jax.tree_util.tree_flatten_with_path(resumed.state.params)[0],
            jax.tree_util.tree_flatten_with_path(specs)[0]):
        want = NamedSharding(mesh, spec)
        if not leaf.sharding.is_equivalent_to(want, leaf.ndim):
            mismatched.append((jax.tree_util.keystr(path),
                               leaf.sharding.spec, spec))
    assert not mismatched, mismatched
    # FSDP actually sharded something (guard against a vacuous pass)
    assert any("data" in tuple(l.sharding.spec)
               for l in jax.tree_util.tree_leaves(resumed.state.params))

    _trees_equal(full.state.params, resumed.state.params)
    ck.close()


@pytest.mark.slow
def test_midrun_checkpoint_cursor_not_skewed_by_prefetch(tmp_path):
    """The prefetch producer draws scan_k x depth batches ahead of the
    consumed step; a mid-run checkpoint must save the cursor as-of the
    checkpointed step (not the raced-ahead live batcher), so resume
    continues on the exact token stream."""
    from replicatinggpt_tpu.config import get_config
    from replicatinggpt_tpu.train.runner import train

    cfg = get_config("test-tiny")
    base = cfg.replace(
        train=dataclasses.replace(cfg.train, max_iters=8, eval_interval=0,
                                  eval_iters=2, log_interval=0, batch_size=8,
                                  sampling="sequential",
                                  steps_per_dispatch=4,
                                  checkpoint_every=4),
        dataset="datasets/shakespeare.txt")
    full = train(base)
    ck = CheckpointManager(str(tmp_path / "ck"))
    part = base.replace(train=dataclasses.replace(base.train, max_iters=4))
    train(part, checkpoint_manager=ck)
    ck.wait()
    resumed = train(base, checkpoint_manager=ck, resume=True)
    assert int(jax.device_get(resumed.state.step)) == 8
    _trees_equal(full.state.params, resumed.state.params)
    ck.close()
