"""Checkpoint tests: full-state roundtrip, resume continues identically,
data-loader cursor restoration, latest-step selection."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replicatinggpt_tpu.config import get_config
from replicatinggpt_tpu.data import SequentialBatcher
from replicatinggpt_tpu.train.checkpoint import CheckpointManager
from replicatinggpt_tpu.train.state import create_train_state
from replicatinggpt_tpu.train.steps import make_train_step


@pytest.fixture()
def tiny():
    return get_config("test-tiny")


def _trees_equal(a, b):
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_roundtrip_full_state(tiny, tmp_path):
    m, t = tiny.model, tiny.train
    state = create_train_state(jax.random.PRNGKey(0), m, t)
    step = make_train_step(m, t, donate=False)
    x = jax.random.randint(jax.random.PRNGKey(1), (4, m.block_size), 0,
                           m.vocab_size)
    for _ in range(3):
        state, _ = step(state, (x, x))
    ck = CheckpointManager(str(tmp_path / "ck"))
    ck.save(state, wait=True)
    restored = ck.restore(3, state)
    _trees_equal(state, restored)
    assert int(restored.step) == 3
    ck.close()


def test_resume_training_is_identical(tiny, tmp_path):
    """Save at step 2, keep training to 5; restore at 2 and retrain to 5 —
    final params must be bit-identical (step-keyed dropout RNG makes the
    tail deterministic)."""
    m, t = tiny.model, tiny.train
    step = make_train_step(m, t, donate=False)
    x = jax.random.randint(jax.random.PRNGKey(1), (4, m.block_size), 0,
                           m.vocab_size)
    state = create_train_state(jax.random.PRNGKey(0), m, t)
    ck = CheckpointManager(str(tmp_path / "ck"))
    for _ in range(2):
        state, _ = step(state, (x, x))
    ck.save(state, wait=True)
    cont = state
    for _ in range(3):
        cont, _ = step(cont, (x, x))
    resumed = ck.restore(2, state)
    for _ in range(3):
        resumed, _ = step(resumed, (x, x))
    _trees_equal(cont.params, resumed.params)
    ck.close()


def test_batcher_cursor_roundtrip(tiny, tmp_path):
    m, t = tiny.model, tiny.train
    state = create_train_state(jax.random.PRNGKey(0), m, t)
    data = np.arange(5000, dtype=np.int32)
    b = SequentialBatcher(data, 4, m.block_size)
    b.next_batch(); b.next_batch()
    expected_next, _ = SequentialBatcher(data, 4, m.block_size), None
    ck = CheckpointManager(str(tmp_path / "ck"))
    ck.save(state, batcher=b, wait=True)
    want, _ = b.next_batch()
    b2 = SequentialBatcher(data, 4, m.block_size)
    ck.restore(0, state, batcher=b2)
    got, _ = b2.next_batch()
    np.testing.assert_array_equal(want, got)
    ck.close()


def test_latest_step(tiny, tmp_path):
    m, t = tiny.model, tiny.train
    state = create_train_state(jax.random.PRNGKey(0), m, t)
    ck = CheckpointManager(str(tmp_path / "ck"))
    assert ck.latest_step() is None
    assert ck.restore_latest(state) is None
    ck.save(state, wait=True)
    step = make_train_step(m, t, donate=False)
    x = jax.random.randint(jax.random.PRNGKey(1), (4, m.block_size), 0,
                           m.vocab_size)
    state2, _ = step(state, (x, x))
    ck.save(state2, wait=True)
    assert ck.latest_step() == 1
    r = ck.restore_latest(state)
    assert int(r.step) == 1
    ck.close()
