"""Generation tests: greedy KV-cache decode must match a naive
re-encode-everything rollout (the reference's algorithm, GPT1.py:196-212);
sampling modes; long generation via window refresh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replicatinggpt_tpu.config import ModelConfig
from replicatinggpt_tpu.models.gpt import forward, init_params
from replicatinggpt_tpu.sample import GenerateConfig, generate

CFG = ModelConfig(vocab_size=65, block_size=32, n_layer=2, n_head=2,
                  n_embd=32, dropout=0.0, attn_dropout=0.0, dtype="float32")


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _naive_greedy(params, prompt, n_new):
    """Reference-style rollout: full forward over the (cropped) window per
    token, argmax of the last position (GPT1.py:200-208 with argmax)."""
    idx = np.asarray(prompt)
    out = []
    for _ in range(n_new):
        window = idx[:, -CFG.block_size:]
        logits, _ = forward(params, jnp.asarray(window), CFG)
        nxt = np.argmax(np.asarray(logits[:, -1, :]), axis=-1)[:, None]
        idx = np.concatenate([idx, nxt], axis=1)
        out.append(nxt)
    return np.concatenate(out, axis=1).astype(np.int32)


@pytest.mark.slow
def test_greedy_matches_naive_rollout(params):
    prompt = np.array([[1, 5, 9], [3, 3, 3]], dtype=np.int32)
    n_new = 12  # stays within block_size
    got = np.asarray(generate(params, prompt, CFG,
                              GenerateConfig(max_new_tokens=n_new,
                                             greedy=True)))
    want = _naive_greedy(params, prompt, n_new)
    np.testing.assert_array_equal(got, want)


def test_zero_context_start(params):
    """The reference's 500-from-zero workload shape (GPT1.py:235-236)."""
    prompt = np.zeros((1, 1), dtype=np.int32)
    toks = generate(params, prompt, CFG,
                    GenerateConfig(max_new_tokens=10))
    assert toks.shape == (1, 10)
    assert int(toks.min()) >= 0 and int(toks.max()) < CFG.vocab_size


def test_sampling_deterministic_given_rng(params):
    prompt = np.array([[1, 2]], dtype=np.int32)
    g = GenerateConfig(max_new_tokens=8, temperature=0.8, top_k=10)
    a = generate(params, prompt, CFG, g, rng=jax.random.PRNGKey(7))
    b = generate(params, prompt, CFG, g, rng=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = generate(params, prompt, CFG, g, rng=jax.random.PRNGKey(8))
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_top_k_restricts_support(params):
    """With top_k=1, sampling degenerates to greedy."""
    prompt = np.array([[4, 7, 2]], dtype=np.int32)
    greedy = generate(params, prompt, CFG,
                      GenerateConfig(max_new_tokens=6, greedy=True))
    k1 = generate(params, prompt, CFG,
                  GenerateConfig(max_new_tokens=6, top_k=1),
                  rng=jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(k1))


def test_long_generation_window_refresh(params):
    """Generate 3x block_size tokens — exercises the half-window refresh
    path that replaces the reference's per-token crop (GPT1.py:200)."""
    prompt = np.zeros((2, 1), dtype=np.int32)
    n = CFG.block_size * 3
    toks = generate(params, prompt, CFG, GenerateConfig(max_new_tokens=n))
    assert toks.shape == (2, n)
    assert int(toks.max()) < CFG.vocab_size
    # trained-free model should still produce varied tokens, not a constant
    assert len(np.unique(np.asarray(toks))) > 3


def test_temperature_extremes(params):
    prompt = np.array([[1]], dtype=np.int32)
    cold = generate(params, prompt, CFG,
                    GenerateConfig(max_new_tokens=6, temperature=1e-4),
                    rng=jax.random.PRNGKey(0))
    greedy = generate(params, prompt, CFG,
                      GenerateConfig(max_new_tokens=6, greedy=True))
    np.testing.assert_array_equal(np.asarray(cold), np.asarray(greedy))


@pytest.mark.slow
def test_sharded_decode_matches_single_device(params):
    """TP-sharded decoding (shard_for_decode + the unchanged generate)
    must produce the same greedy tokens as the single-device path: the
    Megatron TP specs shard qkv heads and the vocab dims, GSPMD inserts
    the psum/gather collectives, and the result is numerically the same
    computation."""
    import dataclasses

    from replicatinggpt_tpu.config import MeshConfig
    from replicatinggpt_tpu.parallel.mesh import make_mesh
    from replicatinggpt_tpu.sample import shard_for_decode

    # vocab 64 divides the model axis, so wte/lm_head really shard over
    # 'model' and the gather-at-sampling step is exercised (vocab 65
    # would silently drop the vocab-parallel specs via the divisibility
    # fallback in parallel.mesh._leaf_spec)
    cfg = dataclasses.replace(CFG, vocab_size=64)
    vparams = init_params(jax.random.PRNGKey(1), cfg)
    prompt = jnp.asarray([[1, 5, 9], [3, 3, 3]], jnp.int32)
    gcfg = GenerateConfig(max_new_tokens=12, greedy=True)
    want = generate(vparams, prompt, cfg, gcfg)

    mesh_cfg = MeshConfig(data=2, model=2)
    mesh = make_mesh(mesh_cfg)
    sp, sprompt = shard_for_decode(vparams, prompt, cfg, mesh, mesh_cfg)
    from jax.sharding import PartitionSpec as P
    assert sp["wte"].sharding.spec == P("model", None), sp["wte"].sharding
    got = generate(sp, sprompt, cfg, gcfg)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # window refresh (long generation) under sharding
    gcfg_long = GenerateConfig(max_new_tokens=2 * cfg.block_size,
                               greedy=True)
    long_want = generate(vparams, prompt, cfg, gcfg_long)
    long_got = generate(sp, sprompt, cfg, gcfg_long)
    np.testing.assert_array_equal(np.asarray(long_got),
                                  np.asarray(long_want))


def test_generate_compile_stability(params):
    """A long sample must cost a fixed small set of compiled segment
    shapes (bucketed prompt pad + fixed refresh shape), and repeat runs
    with different lengths/prompts within the same buckets must add NO new
    compiles — the recompile-per-segment failure mode stays dead."""
    cfg = CFG
    from replicatinggpt_tpu.sample import generate
    from replicatinggpt_tpu.sample.generate import _decode_segment

    _decode_segment.clear_cache()
    gcfg = GenerateConfig(max_new_tokens=3 * cfg.block_size, top_k=10)
    out = generate(params, jnp.zeros((1, 1), jnp.int32), cfg, gcfg,
                   rng=jax.random.PRNGKey(0))
    assert out.shape == (1, 3 * cfg.block_size)
    n_first = _decode_segment._cache_size()
    assert n_first <= 2, n_first
    # same buckets, different length/rng: zero fresh compiles
    gcfg2 = GenerateConfig(max_new_tokens=3 * cfg.block_size - 17, top_k=10)
    generate(params, jnp.zeros((1, 1), jnp.int32), cfg, gcfg2,
             rng=jax.random.PRNGKey(1))
    assert _decode_segment._cache_size() == n_first


def test_top_p_filter_keeps_nucleus_only():
    """The nucleus filter keeps exactly the smallest descending-probability
    prefix reaching mass p (always >= 1 token), masks the rest to -inf."""
    import jax.numpy as jnp
    from replicatinggpt_tpu.sample.generate import _top_p_filter

    # probs ~ [0.6, 0.3, 0.08, 0.02] after softmax
    logits = jnp.log(jnp.asarray([[0.6, 0.3, 0.08, 0.02]], jnp.float32))
    out = _top_p_filter(logits, 0.5)      # 0.6 alone reaches 0.5
    assert jnp.isfinite(out[0, 0]) and not jnp.any(jnp.isfinite(out[0, 1:]))
    out = _top_p_filter(logits, 0.85)     # needs 0.6 + 0.3
    assert bool(jnp.all(jnp.isfinite(out[0, :2])))
    assert not jnp.any(jnp.isfinite(out[0, 2:]))
    out = _top_p_filter(logits, 1.0)      # keeps everything
    assert bool(jnp.all(jnp.isfinite(out)))
    # extreme p always keeps the argmax
    out = _top_p_filter(logits, 1e-9)
    assert jnp.isfinite(out[0, 0]) and not jnp.any(jnp.isfinite(out[0, 1:]))
    # boundary ties cannot widen the nucleus (rank-based, not
    # value-thresholded): fully tied row at p=0.25 keeps exactly one
    tied = jnp.zeros((1, 4), jnp.float32)
    out = _top_p_filter(tied, 0.25)
    assert int(jnp.sum(jnp.isfinite(out))) == 1


def test_sample_token_top_p_never_draws_masked_tail():
    """_sample_token with top_p draws only nucleus members: over many
    draws from a known distribution, the masked tail never appears (this
    pins the guard wiring, not just the filter math)."""
    import jax
    import jax.numpy as jnp
    from replicatinggpt_tpu.sample.generate import (GenerateConfig,
                                                    _sample_token)

    logits = jnp.log(jnp.asarray([[0.6, 0.3, 0.08, 0.02]], jnp.float32))
    batched = jnp.broadcast_to(logits, (500, 4))
    draws = _sample_token(jax.random.PRNGKey(0), batched,
                          GenerateConfig(top_p=0.5))
    assert bool(jnp.all(draws == 0))              # nucleus = {0}
    draws = _sample_token(jax.random.PRNGKey(1), batched,
                          GenerateConfig(top_p=0.85))
    assert bool(jnp.all(draws <= 1))              # nucleus = {0, 1}
    assert bool(jnp.any(draws == 1))              # and it still samples


def test_generate_top_p_end_to_end():
    """End-to-end: top-p generation produces valid tokens and greedy
    decoding ignores top_p (nucleus membership itself is pinned by
    test_sample_token_top_p_never_draws_masked_tail)."""
    import jax
    import jax.numpy as jnp
    from replicatinggpt_tpu.config import get_config
    from replicatinggpt_tpu.sample import GenerateConfig, generate
    from replicatinggpt_tpu.train.state import create_train_state

    cfg = get_config("test-tiny")
    m = cfg.model
    state = create_train_state(jax.random.PRNGKey(0), m, cfg.train)
    toks = generate(state.params, jnp.zeros((1, 1), jnp.int32), m,
                    GenerateConfig(max_new_tokens=24, top_p=0.9),
                    rng=jax.random.PRNGKey(1))
    assert toks.shape == (1, 24)
    assert bool(jnp.all((toks >= 0) & (toks < m.vocab_size)))
    # greedy unaffected by top_p
    g1 = generate(state.params, jnp.zeros((1, 1), jnp.int32), m,
                  GenerateConfig(max_new_tokens=8, greedy=True, top_p=0.5),
                  rng=jax.random.PRNGKey(2))
    g2 = generate(state.params, jnp.zeros((1, 1), jnp.int32), m,
                  GenerateConfig(max_new_tokens=8, greedy=True),
                  rng=jax.random.PRNGKey(3))
    assert bool(jnp.all(g1 == g2))


def test_top_k_filter_radix_matches_sort():
    """The radix-select top-k filter must be bit-identical to the
    lax.top_k formulation (same kept set, same tie semantics) — across
    random rows, heavy ties, -inf entries, and the k=1 / k=V edges."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from replicatinggpt_tpu.sample.generate import _top_k_filter

    def ref_filter(logits, k):
        kth = jax.lax.top_k(logits, k)[0][:, -1:]
        return jnp.where(logits < kth, -jnp.inf, logits)

    rng = np.random.default_rng(0)
    V = 1031  # not a multiple of anything convenient
    cases = []
    cases.append(rng.normal(size=(3, V)).astype(np.float32))
    tied = rng.normal(size=(2, V)).astype(np.float32)
    tied[:, : V // 2] = tied[:, :1]            # half the row ties at one value
    cases.append(tied)
    winf = rng.normal(size=(2, V)).astype(np.float32)
    winf[:, ::3] = -np.inf                     # -inf entries survive bitspace
    cases.append(winf)
    cases.append(np.full((1, V), 2.5, np.float32))   # fully tied row
    neg = -np.abs(rng.normal(size=(2, V))).astype(np.float32)  # all negative
    cases.append(neg)
    for x in cases:
        xj = jnp.asarray(x)
        for k in (1, 7, 50, V):
            got = np.asarray(_top_k_filter(xj, k))
            want = np.asarray(ref_filter(xj, k))
            np.testing.assert_array_equal(got, want)


def test_kth_largest_exact_values():
    import jax.numpy as jnp
    import numpy as np
    from replicatinggpt_tpu.sample.generate import _kth_largest

    x = jnp.asarray([[5.0, -1.0, 3.0, 3.0, 0.0, -jnp.inf],
                     [0.5, 0.25, 0.125, -0.5, -0.25, -0.125]], jnp.float32)
    np.testing.assert_array_equal(np.asarray(_kth_largest(x, 1)),
                                  np.asarray([5.0, 0.5], np.float32))
    np.testing.assert_array_equal(np.asarray(_kth_largest(x, 3)),
                                  np.asarray([3.0, 0.125], np.float32))
    np.testing.assert_array_equal(np.asarray(_kth_largest(x, 6)),
                                  np.asarray([-np.inf, -0.5], np.float32))


def test_prefill_matches_sequential_decode():
    """The parallel prefill must build the same KV cache (and leave the
    decode continuation identical) as teacher-forcing the prompt through
    sequential decode_steps."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from replicatinggpt_tpu.config import get_config
    from replicatinggpt_tpu.models.gpt import (decode_step, init_kv_cache,
                                               prefill)
    from replicatinggpt_tpu.train.state import create_train_state

    cfg = get_config("test-tiny").model
    state = create_train_state(jax.random.PRNGKey(0), cfg,
                               get_config("test-tiny").train)
    B, P = 2, 12
    prompt = jax.random.randint(jax.random.PRNGKey(1), (B, P), 0,
                                cfg.vocab_size)
    cache_p = prefill(state.params, prompt, init_kv_cache(cfg, B), cfg)
    cache_s = init_kv_cache(cfg, B)
    for pos in range(P):
        logits_s, cache_s = decode_step(state.params, prompt[:, pos],
                                        jnp.int32(pos), cache_s, cfg)
    for key in ("k", "v"):
        np.testing.assert_allclose(
            np.asarray(cache_p[key][:, :, :, :P], np.float32),
            np.asarray(cache_s[key][:, :, :, :P], np.float32),
            atol=2e-5, rtol=2e-5)
    # continuations agree: next decode step from either cache matches
    nxt = jnp.argmax(logits_s, -1).astype(jnp.int32)
    lp, _ = decode_step(state.params, nxt, jnp.int32(P), cache_p, cfg)
    ls, _ = decode_step(state.params, nxt, jnp.int32(P), cache_s, cfg)
    np.testing.assert_allclose(np.asarray(lp), np.asarray(ls), atol=2e-5,
                               rtol=2e-5)


def test_refresh_group_matches_sequential_segments():
    """One _refresh_group(n_seg=2) dispatch must produce exactly the
    tokens of two sequential _decode_segment calls (same ordinal-keyed
    rngs, same window sliding) — non-greedy, so rng threading is
    covered too."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from replicatinggpt_tpu.config import get_config
    from replicatinggpt_tpu.sample.generate import (GenerateConfig,
                                                    _decode_segment,
                                                    _refresh_group)
    from replicatinggpt_tpu.train.state import create_train_state

    cfg = get_config("test-tiny")
    m = cfg.model
    state = create_train_state(jax.random.PRNGKey(0), m, cfg.train)
    gcfg = GenerateConfig(max_new_tokens=0, top_k=5)
    S = m.block_size
    Pw, n_mid = S // 2, S // 2 + 1
    B = 2
    window = jax.random.randint(jax.random.PRNGKey(3), (B, Pw), 0,
                                m.vocab_size)
    base = jax.random.PRNGKey(11)

    grouped, gw = _refresh_group(state.params, window, 2, jnp.int32(0),
                                 base, m, gcfg)

    seq_chunks = []
    w = window
    for ordinal in range(2):
        sub = jax.random.fold_in(base, ordinal)
        toks = _decode_segment(state.params, w, Pw, n_mid, sub, m, gcfg)
        seq_chunks.append(toks)
        w = jnp.concatenate([w, toks], axis=1)[:, -Pw:]
    sequential = jnp.concatenate(seq_chunks, axis=1)

    np.testing.assert_array_equal(np.asarray(grouped),
                                  np.asarray(sequential))
    np.testing.assert_array_equal(np.asarray(gw), np.asarray(w))


def test_decode_chunks_cover_exactly():
    """_decode_chunks partitions [0, n_new) with attend_len always a
    valid bound for every position its chunk writes (pos <= P_pad-1+i
    < attend_len) and never exceeding S."""
    from replicatinggpt_tpu.sample.generate import _decode_chunks
    GRANULE = 128
    for P_pad, n_new, S in [(1, 1024, 1024), (512, 513, 1024),
                            (1, 1, 32), (32, 1, 32), (7, 250, 256),
                            (128, 897, 1024)]:
        chunks = _decode_chunks(P_pad, n_new, S, GRANULE)
        i = 0
        for n_c, a in chunks:
            assert n_c >= 1 and a <= S
            assert a % GRANULE == 0 or a == S
            last_pos = P_pad - 1 + i + n_c - 1
            assert last_pos < a, (P_pad, n_new, S, chunks)
            i += n_c
        assert i == n_new
        assert P_pad - 1 + n_new - 1 <= S - 1


@pytest.mark.slow
def test_chunked_segment_matches_monolithic():
    """The chunked-attend decode scan must produce the bit-identical
    sampled trajectory of a single full-S scan (the rng-split sequence
    per step is unchanged; the cache prefix slice only drops slots the
    mask already zeroed). attend_granule is a GenerateConfig field —
    part of the static jit key — so the two arms compile separately
    with no cache clearing."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompt = np.array([[1, 5, 9], [3, 3, 3]], dtype=np.int32)
    rng = jax.random.PRNGKey(42)
    # granule S = one chunk at full attend width (the old monolithic scan)
    mono_cfg = GenerateConfig(max_new_tokens=60, temperature=0.9, top_k=8,
                              attend_granule=CFG.block_size)
    mono = np.asarray(generate(params, prompt, CFG, mono_cfg, rng=rng))
    # granule 8 engages real chunking at block_size=32
    chunk_cfg = GenerateConfig(max_new_tokens=60, temperature=0.9, top_k=8,
                               attend_granule=8)
    chunked = np.asarray(generate(params, prompt, CFG, chunk_cfg, rng=rng))
    np.testing.assert_array_equal(mono, chunked)


@pytest.mark.slow
def test_decode_step_short_cache_parity():
    """decode_step on a shorter cache buffer (init_kv_cache max_len)
    returns the same logits and cache writes as the full bucket while
    pos stays inside it — the invariant the chunked grow-as-you-go
    decode relies on."""
    from replicatinggpt_tpu.models.gpt import decode_step, init_kv_cache
    params = init_params(jax.random.PRNGKey(0), CFG)
    B = 2
    rng = jax.random.PRNGKey(5)
    cache_a = init_kv_cache(CFG, B)                  # full block_size=32
    cache_b = init_kv_cache(CFG, B, max_len=16)      # short buffer
    toks = jax.random.randint(rng, (B, 10), 0, CFG.vocab_size)
    for pos in range(10):
        la, cache_a = decode_step(params, toks[:, pos], jnp.int32(pos),
                                  cache_a, CFG)
        lb, cache_b = decode_step(params, toks[:, pos], jnp.int32(pos),
                                  cache_b, CFG)
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    for key in ("k", "v"):
        np.testing.assert_array_equal(
            np.asarray(cache_a[key][:, :, :, :16]),
            np.asarray(cache_b[key]))


def test_fused_decode_step_matches_unfused(monkeypatch):
    """The fused Pallas decode kernel (interpret mode on CPU) must match
    the XLA layer-loop decode_step: logits and cache, across positions
    including pos=0 and a mid-sequence pos with a warm cache."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import replicatinggpt_tpu.models.gpt as gpt
    from replicatinggpt_tpu.config import get_config
    from replicatinggpt_tpu.models.gpt import decode_step, init_kv_cache
    from replicatinggpt_tpu.ops.decode_pallas import fused_decode_supported
    from replicatinggpt_tpu.train.state import create_train_state

    from replicatinggpt_tpu.config import ModelConfig

    m = ModelConfig(vocab_size=64, block_size=64, n_layer=2, n_head=2,
                    n_embd=128, dropout=0.0, attn_dropout=0.0,
                    dtype="float32")
    assert fused_decode_supported(m, 1, 4)
    assert not fused_decode_supported(m, 2, 4)          # B != 1
    assert not fused_decode_supported(
        get_config("test-tiny").model, 1, 4)            # D=16 unsupported
    state = create_train_state(jax.random.PRNGKey(0), m,
                               get_config("test-tiny").train)
    toks = jax.random.randint(jax.random.PRNGKey(1), (6,), 0, m.vocab_size)

    def run(fused):
        monkeypatch.setattr(gpt, "_fused_decode_backend_ok", lambda: fused)
        cache = init_kv_cache(m, 1)
        outs = []
        for pos in range(toks.shape[0]):
            # allow_pallas=True: the conftest's 8-device CPU mesh makes
            # the direct-call default conservative-False
            logits, cache = decode_step(state.params, toks[pos:pos + 1],
                                        jnp.int32(pos), cache, m,
                                        allow_pallas=True)
            outs.append(logits)
        return jnp.stack(outs), cache

    lf, cf = run(True)
    lu, cu = run(False)
    np.testing.assert_allclose(np.asarray(lf), np.asarray(lu), atol=2e-5,
                               rtol=2e-5)
    for key in ("k", "v"):
        np.testing.assert_allclose(np.asarray(cf[key], np.float32),
                                   np.asarray(cu[key], np.float32),
                                   atol=2e-5, rtol=2e-5)


PACKED_CFG = ModelConfig(vocab_size=65, block_size=32, n_layer=2, n_head=2,
                         n_embd=64, dropout=0.0, attn_dropout=0.0,
                         dtype="float32")  # D=32: packed-kernel envelope


def test_packed_cache_layout_trajectory_matches_heads():
    """The (L,B,S,C) packed cache layout must sample the bit-identical
    trajectory of the (L,B,H,S,D) heads layout through the XLA fallback
    path (same math, different carry layout)."""
    import dataclasses
    params = init_params(jax.random.PRNGKey(0), PACKED_CFG)
    prompt = np.array([[1, 5, 9], [3, 3, 3]], np.int32)
    gcfg = GenerateConfig(max_new_tokens=50, temperature=0.9, top_k=8)
    rng = jax.random.PRNGKey(42)
    heads = np.asarray(generate(params, prompt, PACKED_CFG, gcfg, rng=rng))
    pc = dataclasses.replace(PACKED_CFG, decode_cache_layout="packed")
    packed = np.asarray(generate(params, prompt, pc, gcfg, rng=rng))
    np.testing.assert_array_equal(heads, packed)


def test_packed_decode_kernel_engages_and_matches(monkeypatch):
    """With the backend gate open, the packed decode-attention Pallas
    kernel (interpret mode on CPU) must be routed AND reproduce the
    heads-layout trajectory."""
    import dataclasses

    import replicatinggpt_tpu.ops.decode_pallas as dp
    params = init_params(jax.random.PRNGKey(0), PACKED_CFG)
    prompt = np.array([[1, 5, 9], [3, 3, 3]], np.int32)
    gcfg = GenerateConfig(max_new_tokens=50, temperature=0.9, top_k=8)
    rng = jax.random.PRNGKey(42)
    heads = np.asarray(generate(params, prompt, PACKED_CFG, gcfg, rng=rng))

    calls = []
    orig = dp.packed_decode_attention

    def spy(*a, **kw):
        calls.append(1)
        return orig(*a, **kw)

    monkeypatch.setattr(dp, "_packed_attn_backend_ok", lambda: True)
    monkeypatch.setattr(dp, "packed_decode_attention", spy)
    # the backend gate is read at trace time and is NOT part of the jit
    # key (it cannot change in production processes) — drop programs an
    # earlier gate-closed test may have compiled for this same config,
    # and drop the gate-open programs afterwards. (importlib: the package
    # re-exports the `generate` function under the submodule's name)
    import importlib
    G = importlib.import_module("replicatinggpt_tpu.sample.generate")
    G._decode_segment.clear_cache()
    G._refresh_group.clear_cache()
    try:
        pc = dataclasses.replace(PACKED_CFG, decode_cache_layout="packed")
        got = np.asarray(generate(params, prompt, pc, gcfg, rng=rng))
    finally:
        G._decode_segment.clear_cache()
        G._refresh_group.clear_cache()
    assert calls, "packed decode kernel was not routed"
    np.testing.assert_array_equal(heads, got)


def test_packed_layout_chunked_growth_matches_monolithic():
    """Chunked cache growth (attend_granule < S) under the packed layout
    — the grow axis differs from the heads layout (cache_seq_axis) and
    must still produce the monolithic trajectory."""
    import dataclasses
    pc = dataclasses.replace(PACKED_CFG, decode_cache_layout="packed")
    params = init_params(jax.random.PRNGKey(0), pc)
    prompt = np.array([[2, 4], [7, 1]], np.int32)
    rng = jax.random.PRNGKey(9)
    mono = np.asarray(generate(
        params, prompt, pc,
        GenerateConfig(max_new_tokens=60, top_k=5,
                       attend_granule=pc.block_size), rng=rng))
    chunked = np.asarray(generate(
        params, prompt, pc,
        GenerateConfig(max_new_tokens=60, top_k=5, attend_granule=8),
        rng=rng))
    np.testing.assert_array_equal(mono, chunked)


def test_packed_decode_attention_kernel_unit():
    """Direct kernel-vs-reference parity on random inputs: the packed
    kernel's per-head lane-slice math against a plain split-heads
    softmax attention with write-then-attend semantics."""
    from replicatinggpt_tpu.ops.attention import cached_attention
    from replicatinggpt_tpu.ops.decode_pallas import packed_decode_attention
    B, S, H, D = 3, 16, 4, 32
    C = H * D
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, C)), jnp.float32)
    k_new = jnp.asarray(rng.standard_normal((B, C)), jnp.float32)
    v_new = jnp.asarray(rng.standard_normal((B, C)), jnp.float32)
    kc = jnp.asarray(rng.standard_normal((B, S, C)), jnp.float32)
    vc = jnp.asarray(rng.standard_normal((B, S, C)), jnp.float32)
    for pos in (0, 5, S - 1):
        got = packed_decode_attention(q, k_new, v_new, kc, vc,
                                      jnp.int32(pos), n_head=H)
        # reference: write fresh k/v at pos, then attend <= pos
        kc2 = kc.at[:, pos, :].set(k_new)
        vc2 = vc.at[:, pos, :].set(v_new)

        def heads(x):
            return x.reshape(B, -1, H, D).transpose(0, 2, 1, 3)

        ref = cached_attention(heads(q[:, None, :]), heads(kc2),
                               heads(vc2), jnp.int32(pos))
        ref = ref.transpose(0, 2, 1, 3).reshape(B, C)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-6, rtol=2e-6)


@pytest.mark.slow
def test_fused_decode_packed_cache_matches_xla(monkeypatch):
    """The fused all-layers decode kernel on the PACKED (L,1,S,C) cache
    (lane-sliced heads) must match the packed XLA path and the heads
    layout — B=1 keeps its one-launch path under either cache layout."""
    import dataclasses

    import replicatinggpt_tpu.models.gpt as gpt
    from replicatinggpt_tpu.config import get_config
    from replicatinggpt_tpu.models.gpt import decode_step, init_kv_cache
    from replicatinggpt_tpu.train.state import create_train_state

    m = ModelConfig(vocab_size=64, block_size=64, n_layer=2, n_head=2,
                    n_embd=128, dropout=0.0, attn_dropout=0.0,
                    dtype="float32")
    mp = dataclasses.replace(m, decode_cache_layout="packed")
    state = create_train_state(jax.random.PRNGKey(0), m,
                               get_config("test-tiny").train)
    toks = jax.random.randint(jax.random.PRNGKey(1), (6,), 0, m.vocab_size)

    def run(cfg, fused):
        monkeypatch.setattr(gpt, "_fused_decode_backend_ok",
                            lambda: fused)
        cache = init_kv_cache(cfg, 1)
        outs = []
        for pos in range(toks.shape[0]):
            logits, cache = decode_step(state.params, toks[pos:pos + 1],
                                        jnp.int32(pos), cache, cfg,
                                        allow_pallas=True)
            outs.append(logits)
        return np.asarray(jnp.stack(outs)), cache

    heads_ref, _ = run(m, False)
    fused_packed, cf = run(mp, True)
    xla_packed, cu = run(mp, False)
    np.testing.assert_allclose(fused_packed, heads_ref, atol=2e-5,
                               rtol=2e-5)
    np.testing.assert_array_equal(xla_packed, heads_ref)
    # caches agree between the packed arms (same rows, same layout)
    for key in ("k", "v"):
        np.testing.assert_allclose(np.asarray(cf[key]),
                                   np.asarray(cu[key]), atol=2e-6,
                                   rtol=2e-6)
