"""Multi-host glue (parallel/distributed.py), exercised in its
single-process degenerate form (real multi-process needs a pod; the
structural contract — local slices, per-process seeds, global assembly —
is what these tests pin)."""

import jax
import numpy as np
import pytest

from replicatinggpt_tpu.config import MeshConfig
from replicatinggpt_tpu.parallel.distributed import (global_batch,
                                                     initialize,
                                                     is_coordinator,
                                                     local_batch_slice,
                                                     per_process_seed)
from replicatinggpt_tpu.parallel.mesh import make_batch_sharding, make_mesh


def test_initialize_single_process_noop():
    pi, pn = initialize()
    assert (pi, pn) == (0, 1)
    assert is_coordinator()


def test_local_batch_slice_covers_batch():
    s = local_batch_slice(64)
    assert (s.start, s.stop) == (0, 64)


def test_per_process_seed_deterministic():
    assert per_process_seed(1337) == per_process_seed(1337)


def test_global_batch_matches_device_put_single_process():
    mesh = make_mesh(MeshConfig(data=4, seq=2, model=1))
    sharding = make_batch_sharding(mesh)
    x = np.arange(8 * 16, dtype=np.int32).reshape(8, 16)
    arr = global_batch(x, sharding)
    np.testing.assert_array_equal(np.asarray(arr), x)
    assert arr.sharding == sharding
