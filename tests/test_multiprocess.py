"""REAL multi-process distributed execution (the proof the single-process
glue tests in test_distributed.py cannot give): two `jax.distributed`
CPU processes run the full training runner — global-batch assembly,
cross-process DP psum, multi-host superbatch dispatch, and the
checkpoint-boundary stop agreement — and must match a single-process run
on the same global token stream.

The reference has no distributed story at all (SURVEY.md §2.2); these
tests pin the framework's DCN-glue claim with actual multi-process
execution (subprocesses, not a pod — same code path as a v4-32 slice,
gloo instead of DCN underneath).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-process spawns

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "dist_worker.py")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run(nprocs: int, outdir: str, tag: str, extra=()):
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # 1 CPU device per process
    procs, out = [], os.path.join(outdir, f"out_{tag}.json")
    for i in range(nprocs):
        procs.append(subprocess.Popen(
            [sys.executable, WORKER, "--process-id", str(i),
             "--num-processes", str(nprocs), "--port", str(port),
             "--out", out, *extra],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    try:
        logs = [p.communicate(timeout=300)[0] for p in procs]
    finally:
        for p in procs:  # a deadlocked gloo worker must not outlive the test
            if p.poll() is None:
                p.kill()
    for p, l in zip(procs, logs):
        assert p.returncode == 0, f"worker rc={p.returncode}:\n{l[-4000:]}"
    with open(out) as f:
        return json.load(f), logs


@pytest.fixture(scope="module")
def single_process_reference(tmp_path_factory):
    out, _ = _run(1, str(tmp_path_factory.mktemp("ref")), "ref")
    return out


def test_two_process_dp_matches_single_process(single_process_reference,
                                               tmp_path):
    got, _ = _run(2, str(tmp_path), "dp2")
    ref = single_process_reference
    assert got["end_step"] == ref["end_step"] == 20
    # same global token stream + same init => same trained params, up to
    # cross-process reduction-order float drift
    np.testing.assert_allclose(got["param_sq"], ref["param_sq"], rtol=1e-4)


def test_two_process_multistep_dispatch_matches_single_process(
        single_process_reference, tmp_path):
    """steps_per_dispatch>1 across processes: the (K,B,T) superbatch is
    assembled from per-process rows (batch_axis=1) — trained params must
    still match the single-step single-process run."""
    got, _ = _run(2, str(tmp_path), "dp2k5", ["--steps-per-dispatch", "5"])
    ref = single_process_reference
    assert got["end_step"] == ref["end_step"] == 20
    np.testing.assert_allclose(got["param_sq"], ref["param_sq"], rtol=1e-4)


def test_two_process_grad_accum_matches_single_process(tmp_path):
    """Gradient accumulation across processes: each optimizer step's
    (A, B, T) microbatch stack — and the (K, A, B, T) scan-dispatch stack —
    is assembled from per-process rows (batch_axis = ndim-2). Params must
    match a single-process run with the same accumulation settings."""
    ref, _ = _run(1, str(tmp_path), "accref",
                  ["--grad-accum-steps", "2", "--max-iters", "12"])
    got, _ = _run(2, str(tmp_path), "acc2",
                  ["--grad-accum-steps", "2", "--max-iters", "12"])
    gotk, _ = _run(2, str(tmp_path), "acc2k3",
                   ["--grad-accum-steps", "2", "--max-iters", "12",
                    "--steps-per-dispatch", "3"])
    assert got["end_step"] == ref["end_step"] == 12
    np.testing.assert_allclose(got["param_sq"], ref["param_sq"], rtol=1e-4)
    assert gotk["end_step"] == 12
    np.testing.assert_allclose(gotk["param_sq"], ref["param_sq"], rtol=1e-4)


def test_stop_on_noncoordinator_is_ignored(tmp_path):
    """Only the coordinator's flag decides (skewed signal delivery must not
    desynchronize the hosts): a stop_event set on process 1 alone runs to
    completion on both."""
    got, _ = _run(2, str(tmp_path), "stop1",
                  ["--stop-on-proc", "1", "--checkpoint-every", "5",
                   "--checkpoint-dir", str(tmp_path / "ck1")])
    assert got["end_step"] == 20


def test_stop_on_coordinator_stops_both_at_boundary(tmp_path):
    """Coordinator's stop_event: both processes agree at the first
    checkpoint boundary, save there, and exit cleanly (no deadlock in the
    collective save)."""
    got, _ = _run(2, str(tmp_path), "stop0",
                  ["--stop-on-proc", "0", "--checkpoint-every", "5",
                   "--checkpoint-dir", str(tmp_path / "ck0")])
    assert got["end_step"] == 5
    assert 5 in got["checkpoint_steps"]


def test_two_process_checkpoint_resume(tmp_path):
    """Collective checkpoint at step 5 of a 10-step run, then a fresh
    2-process run resumes from it and finishes with the same params as an
    uninterrupted 2-process run."""
    ck = str(tmp_path / "ck")
    full, _ = _run(2, str(tmp_path), "full", ["--max-iters", "10"])
    _run(2, str(tmp_path), "part",
         ["--max-iters", "5", "--checkpoint-every", "5",
          "--checkpoint-dir", ck])
    resumed, _ = _run(2, str(tmp_path), "resumed",
                      ["--max-iters", "10", "--checkpoint-dir", ck,
                       "--resume"])
    assert resumed["end_step"] == 10
    np.testing.assert_allclose(resumed["param_sq"], full["param_sq"],
                               rtol=1e-6)
