"""HF GPT-2 import tests.

Network-free: a randomly initialized local ``GPT2LMHeadModel`` (no download)
provides the state_dict fixture, mirroring how the reference's notebook
inspected HF weight names/shapes as its de-facto test (SURVEY.md §4 item 2).
The decisive check is numerical: our forward on imported weights must match
the HF model's logits — proven live against ``transformers`` where it is
installed, and HERMETICALLY against the committed synthetic golden fixture
(tools/make_hf_fixture.py --synthetic) everywhere, torch or no torch."""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from replicatinggpt_tpu.config import ModelConfig
from replicatinggpt_tpu.interop.hf import (GPT2_SIZES, config_for_model_type,
                                           import_hf_state_dict,
                                           model_config_from_hf)
from replicatinggpt_tpu.models.gpt import forward

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


@pytest.fixture(scope="module")
def hf_model():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    cfg = transformers.GPT2Config(
        vocab_size=97, n_positions=48, n_embd=64, n_layer=3, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    model = transformers.GPT2LMHeadModel(cfg)
    model.eval()
    return model


def test_size_ladder_matches_reference_table():
    # GPT-2.py:140-145
    assert GPT2_SIZES["gpt2"] == (12, 12, 768)
    assert GPT2_SIZES["gpt2-medium"] == (24, 16, 1024)
    assert GPT2_SIZES["gpt2-large"] == (36, 20, 1280)
    assert GPT2_SIZES["gpt2-xl"] == (48, 25, 1600)
    cfg = config_for_model_type("gpt2")
    assert cfg.vocab_size == 50257 and cfg.block_size == 1024


def test_import_shapes(hf_model):
    mcfg = model_config_from_hf(hf_model.config)
    params = import_hf_state_dict(hf_model.state_dict(), mcfg)
    assert params["wte"].shape == (97, 64)
    assert params["blocks"]["qkv_kernel"].shape == (3, 64, 192)
    assert params["blocks"]["mlp_down_kernel"].shape == (3, 256, 64)
    assert "lm_head" not in params  # tied


def test_logits_parity_with_hf(hf_model):
    """Imported weights through our forward == HF forward (f32, CPU)."""
    import torch
    mcfg = model_config_from_hf(hf_model.config)
    mcfg = mcfg.__class__(**{**mcfg.__dict__, "dtype": "float32"})
    params = import_hf_state_dict(hf_model.state_dict(), mcfg)
    params = {k: jnp.asarray(v) if not isinstance(v, dict) else
              {kk: jnp.asarray(vv) for kk, vv in v.items()}
              for k, v in params.items()}
    rng = np.random.default_rng(0)
    x = rng.integers(0, 97, size=(2, 32))
    with torch.no_grad():
        want = hf_model(torch.tensor(x)).logits.numpy()
    got, _ = forward(params, jnp.asarray(x, jnp.int32), mcfg)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-4, rtol=1e-4)


def test_untied_import_copies_head(hf_model):
    mcfg = model_config_from_hf(hf_model.config)
    mcfg = mcfg.__class__(**{**mcfg.__dict__, "tied_head": False})
    params = import_hf_state_dict(hf_model.state_dict(), mcfg)
    np.testing.assert_array_equal(params["lm_head"], params["wte"].T)


def test_synthetic_golden_fixture_hermetic():
    """The committed synthetic fixture (tools/make_hf_fixture.py
    --synthetic) pins the HF-mapping numerics with NO torch/transformers
    at test time: the npz carries a full HF-format state_dict (numpy)
    plus the logits transformers computed from it once on this image.
    import_hf_state_dict + our forward must reproduce them — the same
    Conv1D-layout mapping the real from_pretrained path uses, re-proven
    hermetically on every machine (VERDICT r4 item 5; the REAL-gpt2
    fixture below still needs one networked run, which this zero-egress
    image cannot perform)."""
    fix_path = os.path.join(FIXTURES, "hf_synthetic_golden.npz")
    fix = np.load(fix_path)
    sd = {k[len("sd__"):]: fix[k] for k in fix.files
          if k.startswith("sd__")}
    mcfg = ModelConfig(vocab_size=97, block_size=48, n_layer=3, n_head=4,
                       n_embd=64, dropout=0.0, attn_dropout=0.0,
                       tied_head=True, activation="gelu", dtype="float32")
    params = import_hf_state_dict(sd, mcfg)
    got, _ = forward(params, jnp.asarray(fix["input_ids"], jnp.int32), mcfg)
    np.testing.assert_allclose(np.asarray(got), fix["logits"], atol=2e-4,
                               rtol=1e-4)


def test_golden_fixture_real_gpt2():
    """Fixture-pinned import of the REAL HF gpt2 124M weights
    (VERDICT r2 item 7): tools/make_hf_fixture.py records (input ids,
    logits slice, loss) from a networked environment once; this test
    re-runs the import + forward and must reproduce them bit-tightly,
    independent of transformers' model code. Skips until both the
    fixture and the cached weights exist (this dev image has neither —
    zero egress)."""
    pytest.importorskip("torch")  # from_pretrained needs both
    pytest.importorskip("transformers")
    fix_path = os.path.join(FIXTURES, "hf_gpt2_golden.npz")
    if not os.path.exists(fix_path):
        pytest.skip("golden fixture not generated yet "
                    "(tools/make_hf_fixture.py needs network once)")
    from replicatinggpt_tpu.interop.hf import from_pretrained
    try:
        params, mcfg = from_pretrained("gpt2")
    except OSError as e:
        # transformers raises OSError (incl. its EnvironmentError
        # subclasses) for missing/offline weights — ONLY that skips; any
        # other exception is a real import-path regression and must FAIL
        pytest.skip(f"real gpt2 weights unavailable offline: {e!r}")
    import jax

    from replicatinggpt_tpu.models.gpt import forward
    fix = np.load(fix_path)
    ids = fix["input_ids"]
    logits, loss = forward(params, ids, mcfg, targets=ids)
    logits = np.asarray(jax.device_get(logits), np.float32)
    np.testing.assert_allclose(logits[:, :8, :256], fix["logits_slice"],
                               atol=1e-3, rtol=1e-4)
    np.testing.assert_allclose(float(loss), float(fix["loss"]), rtol=1e-4)
    np.testing.assert_allclose(logits.mean(), float(fix["logits_mean"]),
                               atol=1e-3)
