"""Data pipeline tests: split semantics, both sampling disciplines,
cursor checkpointing, prefetch."""

import numpy as np

from replicatinggpt_tpu.data import (RandomBatcher, SequentialBatcher,
                                     TokenDataset, make_batcher, prefetch)
from replicatinggpt_tpu.tokenizers import CharTokenizer


def test_split_fractions(corpus_text):
    tok = CharTokenizer.from_text(corpus_text)
    ds = TokenDataset.from_text(corpus_text, tok, val_fraction=0.1)
    n = len(ds.train) + len(ds.val)
    # 90/10 split (GPT1.py:68-70)
    assert abs(len(ds.train) / n - 0.9) < 1e-3
    assert ds.vocab_size == 65


def _data(n=1000):
    return np.arange(n, dtype=np.int32)


def test_random_batcher_shapes_and_shift():
    b = RandomBatcher(_data(), batch_size=4, block_size=8, seed=0)
    x, y = b.next_batch()
    assert x.shape == (4, 8) and y.shape == (4, 8)
    # y is x shifted by one (GPT1.py:79-80)
    np.testing.assert_array_equal(y, x + 1)


def test_random_batcher_seeded_reproducible():
    a = RandomBatcher(_data(), 4, 8, seed=7).next_batch()
    b = RandomBatcher(_data(), 4, 8, seed=7).next_batch()
    np.testing.assert_array_equal(a[0], b[0])


def test_sequential_batcher_wraparound():
    data = _data(4 * 8 + 2)  # room for exactly one window, then wrap
    b = SequentialBatcher(data, batch_size=4, block_size=8)
    x1, _ = b.next_batch()
    assert x1[0, 0] == 0
    x2, _ = b.next_batch()  # wraps (GPT-2.py:210-212)
    assert x2[0, 0] == 0


def test_sequential_batcher_contiguous():
    b = SequentialBatcher(_data(), batch_size=2, block_size=5)
    x, y = b.next_batch()
    np.testing.assert_array_equal(x.ravel(), np.arange(10))
    np.testing.assert_array_equal(y.ravel(), np.arange(1, 11))
    x2, _ = b.next_batch()
    assert x2[0, 0] == 10  # cursor advanced by B*T (GPT-2.py:208)


def test_sequential_state_roundtrip():
    b = SequentialBatcher(_data(), 2, 5)
    b.next_batch()
    st = b.state()
    x_expected, _ = b.next_batch()
    b2 = SequentialBatcher(_data(), 2, 5)
    b2.restore(st)
    x_got, _ = b2.next_batch()
    np.testing.assert_array_equal(x_expected, x_got)


def test_random_state_roundtrip():
    b = RandomBatcher(_data(), 2, 5, seed=3)
    b.next_batch()
    st = b.state()
    x_expected, _ = b.next_batch()
    b2 = RandomBatcher(_data(), 2, 5, seed=99)
    b2.restore(st)
    x_got, _ = b2.next_batch()
    np.testing.assert_array_equal(x_expected, x_got)


def test_prefetch_yields_device_arrays():
    import jax
    b = make_batcher("sequential", _data(), 2, 5)
    it = prefetch(iter(b), depth=2)
    x, y = next(it)
    assert isinstance(x, jax.Array)
    assert x.shape == (2, 5)


def test_prefetch_propagates_producer_errors():
    """An exception in the prefetch producer thread must surface in the
    consumer, not leave it blocked forever on the queue."""
    import pytest

    from replicatinggpt_tpu.data.loader import prefetch

    def bad():
        yield (np.zeros((2, 4), np.int32), np.zeros((2, 4), np.int32))
        raise ValueError("producer blew up")

    it = prefetch(bad())
    next(it)
    with pytest.raises(ValueError, match="producer blew up"):
        next(it)
