"""Pipeline parallelism (parallel/pipeline.py) on the virtual 8-device CPU
mesh: the GPipe-style ppermute schedule must reproduce the plain scan-over
-layers forward bit-for-bit (same params, float32)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replicatinggpt_tpu.config import MeshConfig, ModelConfig, TrainConfig
from replicatinggpt_tpu.models.gpt import forward, init_params
from replicatinggpt_tpu.parallel import (make_pipeline_blocks_fn,
                                         select_blocks_fn)
from replicatinggpt_tpu.parallel.mesh import (make_batch_sharding, make_mesh,
                                              shard_train_state)


def _mcfg(**kw):
    base = dict(vocab_size=64, block_size=32, n_layer=4, n_head=4,
                n_embd=64, dropout=0.0, attn_dropout=0.0, dtype="float32")
    base.update(kw)
    return ModelConfig(**base)


@pytest.mark.parametrize("axes,micro", [
    ((1, 1, 1, 4), 4),   # pure PP
    ((2, 1, 1, 4), 2),   # PP x DP
    ((1, 2, 1, 4), 4),   # PP x SP (ring attention inside the region)
    ((1, 1, 2, 4), 4),   # PP x TP (Megatron block inside the region)
    ((2, 1, 2, 2), 2),   # PP x TP x DP
])
@pytest.mark.slow
def test_pipeline_forward_matches_dense(axes, micro):
    data, seq, model, pipe = axes
    mesh_cfg = MeshConfig(data=data, seq=seq, model=model, pipe=pipe,
                          microbatches=micro)
    mesh = make_mesh(mesh_cfg)
    mcfg = _mcfg()
    params = init_params(jax.random.PRNGKey(0), mcfg)
    rng = np.random.default_rng(0)
    idx = jnp.asarray(rng.integers(0, 64, (8, 32), dtype=np.int32))

    want, _ = forward(params, idx, mcfg)
    blocks_fn = make_pipeline_blocks_fn(mesh, mesh_cfg)
    got, _ = forward(params, idx, mcfg, blocks_fn=blocks_fn)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_pipeline_train_step_matches_dense():
    from replicatinggpt_tpu.train.state import create_train_state
    from replicatinggpt_tpu.train.steps import make_train_step

    mcfg = _mcfg()
    tcfg = TrainConfig(batch_size=8, lr=1e-3)
    mesh_cfg = MeshConfig(data=2, seq=1, model=1, pipe=4, microbatches=2)
    mesh = make_mesh(mesh_cfg)

    rng = np.random.default_rng(1)
    x = rng.integers(0, 64, (8, 32), dtype=np.int32)
    y = np.roll(x, -1, axis=1).astype(np.int32)

    state0 = create_train_state(jax.random.PRNGKey(0), mcfg, tcfg)
    step0 = make_train_step(mcfg, tcfg, donate=False)
    _, m0 = step0(state0, (jnp.asarray(x), jnp.asarray(y)))

    blocks_fn = select_blocks_fn(mcfg, mesh_cfg, mesh)
    assert blocks_fn is not None
    state = shard_train_state(
        lambda: create_train_state(jax.random.PRNGKey(0), mcfg, tcfg),
        mesh, mesh_cfg)
    bs = make_batch_sharding(mesh)
    batch = (jax.device_put(x, bs), jax.device_put(y, bs))
    step = make_train_step(mcfg, tcfg, donate=False, blocks_fn=blocks_fn)
    new_state, metrics = step(state, batch)
    loss = float(jax.device_get(metrics["loss"]))
    assert np.isfinite(loss)
    np.testing.assert_allclose(loss, float(m0["loss"]), atol=1e-4, rtol=1e-4)


def test_pipeline_params_sharded_by_stage():
    """Block params carry 'pipe' on their stacked layer dim."""
    from replicatinggpt_tpu.parallel.mesh import state_pspecs
    mcfg = _mcfg()
    params = init_params(jax.random.PRNGKey(0), mcfg)
    mesh_cfg = MeshConfig(pipe=4)
    specs = state_pspecs({"params": params}, mesh_cfg)
    qkv_spec = specs["params"]["blocks"]["qkv_kernel"]
    assert qkv_spec[0] == "pipe", qkv_spec
    assert specs["params"]["wte"][0] != "pipe"


@pytest.mark.slow
def test_pipeline_tp_grads_match_dense():
    """TP-inside-PP backward: psum/identity transposes through the Megatron
    block must give the same parameter gradients as the dense stack."""
    mcfg = _mcfg()
    mesh_cfg = MeshConfig(data=1, seq=1, model=2, pipe=4, microbatches=4)
    mesh = make_mesh(mesh_cfg)
    params = init_params(jax.random.PRNGKey(0), mcfg)
    rng = np.random.default_rng(2)
    idx = jnp.asarray(rng.integers(0, 64, (8, 32), dtype=np.int32))
    tgt = jnp.asarray(np.roll(np.asarray(idx), -1, axis=1))

    def loss_dense(p):
        return forward(p, idx, mcfg, targets=tgt)[1]

    blocks_fn = make_pipeline_blocks_fn(mesh, mesh_cfg)

    def loss_pp(p):
        return forward(p, idx, mcfg, targets=tgt, blocks_fn=blocks_fn)[1]

    gd = jax.grad(loss_dense)(params)
    gp = jax.grad(loss_pp)(params)
    for path_leaf, (pl_, leaf) in zip(
            jax.tree_util.tree_flatten_with_path(gd)[0],
            jax.tree_util.tree_flatten_with_path(gp)[0]):
        np.testing.assert_allclose(
            np.asarray(path_leaf[1]), np.asarray(leaf), atol=2e-4, rtol=2e-4,
            err_msg=jax.tree_util.keystr(pl_))


@pytest.mark.slow
def test_pipeline_tp_falls_back_when_heads_indivisible():
    """n_head % tp != 0: kernels replicate through the region (old
    behavior) instead of mis-sharding heads."""
    mcfg = _mcfg(n_head=3, n_embd=48)
    mesh_cfg = MeshConfig(data=1, seq=1, model=2, pipe=4, microbatches=4)
    mesh = make_mesh(mesh_cfg)
    params = init_params(jax.random.PRNGKey(0), mcfg)
    rng = np.random.default_rng(3)
    idx = jnp.asarray(rng.integers(0, 64, (8, 32), dtype=np.int32))
    want, _ = forward(params, idx, mcfg)
    got, _ = forward(params, idx, mcfg,
                     blocks_fn=make_pipeline_blocks_fn(mesh, mesh_cfg))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4, rtol=1e-4)
