"""Tokenizer tests.

Mirrors the reference's only executed tokenizer check — the round-trip
assert at GPT1.py:32 — and extends it: vocab properties, save/load, byte-BPE
training on the actual corpus.
"""

import numpy as np
import pytest

from replicatinggpt_tpu.tokenizers import (ByteBPETokenizer, CharTokenizer,
                                           get_tokenizer)


def test_char_roundtrip(corpus_text):
    tok = CharTokenizer.from_text(corpus_text)
    # Tiny Shakespeare char vocab is 65 (SURVEY.md §2.0, GPT1.py:57 intent)
    assert tok.vocab_size == 65
    s = "hello world\nFirst Citizen:"
    assert tok.decode(tok.encode(s)) == s


def test_char_save_load(tmp_path, corpus_text):
    tok = CharTokenizer.from_text(corpus_text)
    p = tmp_path / "char.json"
    tok.save(str(p))
    tok2 = CharTokenizer.load(str(p))
    assert tok2.encode("Romeo") == tok.encode("Romeo")


def test_bpe_train_roundtrip(tiny_corpus):
    tok = ByteBPETokenizer.train(tiny_corpus, vocab_size=512)
    assert tok.vocab_size == 512
    s = "First Citizen:\nBefore we proceed any further, hear me speak."
    ids = tok.encode(s)
    assert tok.decode(ids) == s
    # BPE must compress: fewer tokens than bytes
    assert len(ids) < len(s.encode("utf-8"))


def test_bpe_handles_unseen_text(tiny_corpus):
    tok = ByteBPETokenizer.train(tiny_corpus, vocab_size=300)
    s = "zyx 12345 éüß unseen!"
    assert tok.decode(tok.encode(s)) == s


def test_bpe_save_load(tmp_path, tiny_corpus):
    tok = ByteBPETokenizer.train(tiny_corpus, vocab_size=300)
    p = tmp_path / "bpe.json"
    tok.save(str(p))
    tok2 = ByteBPETokenizer.load(str(p))
    s = "Before we proceed"
    assert tok2.encode(s) == tok.encode(s)
    assert tok2.vocab_size == tok.vocab_size


def test_get_tokenizer_specs(tmp_path, tiny_corpus):
    assert get_tokenizer("char", tiny_corpus).kind == "char"
    tok = get_tokenizer("bpe", tiny_corpus, cache_dir=str(tmp_path))
    assert tok.kind == "bpe"
    # second call hits the cache file
    tok2 = get_tokenizer("bpe", tiny_corpus, cache_dir=str(tmp_path))
    assert tok2.encode("hear me") == tok.encode("hear me")
    with pytest.raises(ValueError):
        get_tokenizer("nope", tiny_corpus)


def test_o200k_preset_wiring():
    """The o200k-shakespeare preset carries the reference GPT1.py default
    tokenizer branch with the §8-B1 vocab bug FIXED: the configured vocab
    (200,064 = 128*1563, MXU lane-padded) covers o200k_base's ~200k ids
    instead of the reference's hard-coded 50257 (GPT1.py:29-36)."""
    from replicatinggpt_tpu.config import get_config
    cfg = get_config("o200k-shakespeare")
    assert cfg.tokenizer == "tiktoken:o200k_base"
    assert cfg.model.vocab_size == 200_064
    assert cfg.model.vocab_size % 128 == 0
    # char-GPT training hyperparams otherwise (the GPT1.py script)
    assert cfg.model.block_size == 256 and cfg.train.lr == 2e-4


def test_tiktoken_offline_error_is_actionable():
    """Without cached BPE ranks or network, the tiktoken wrapper must
    fail with the clear actionable error, not a raw urllib trace; where
    ranks ARE cached it must report the true n_vocab (the §8-B1 fix)."""
    pytest.importorskip("tiktoken")
    try:
        tok = get_tokenizer("tiktoken:o200k_base")
    except RuntimeError as e:
        assert "tiktoken" in str(e) and "bpe" in str(e).lower()
    else:
        assert tok.vocab_size > 200_000  # o200k's real id space
        ids = tok.encode("hello world")
        assert tok.decode(ids) == "hello world"
