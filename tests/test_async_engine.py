"""Async engine core (ISSUE 10): multi-token decode windows, donated
device-resident step state, double-buffered dispatch, and the paged
fused-decode kernel — greedy byte-parity with offline generate()
through every async seam (mid-window admission, EOS inside a window,
cancel-during-window, speculative interleave), zero recompiles after
warmup across a replay containing all of the above, and the CPU proxy
for the BENCH_r03 dispatch gap (host overhead per token >= 3x better
at --decode-window 8 vs the blocked k=1 loop)."""

import dataclasses

import jax
import numpy as np
import pytest

from replicatinggpt_tpu.config import ModelConfig
from replicatinggpt_tpu.models.gpt import init_params
from replicatinggpt_tpu.sample import GenerateConfig, generate
from replicatinggpt_tpu.serve import (Engine, EngineConfig, ReplayConfig,
                                      Request, SamplingParams,
                                      compile_counts, run_replay)
from replicatinggpt_tpu.serve.requests import (FINISH_CANCELLED, FINISH_EOS,
                                               FINISH_MAX_TOKENS,
                                               REJECT_BAD_REQUEST)

CFG = ModelConfig(vocab_size=65, block_size=32, n_layer=2, n_head=2,
                  n_embd=32, dropout=0.0, attn_dropout=0.0, dtype="float32")


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _greedy(rid, prompt, max_new=8, eos=None, seed=0):
    return Request(id=rid, prompt=np.asarray(prompt, np.int32),
                   max_new_tokens=max_new,
                   sampling=SamplingParams(greedy=True), rng_seed=seed,
                   eos_token_id=eos)


def _requests(n=5, seed=3, max_new=None):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        P = int(rng.integers(1, CFG.block_size // 2))
        prompt = rng.integers(0, CFG.vocab_size, (P,)).astype(np.int32)
        out.append(_greedy(f"r{i}", prompt,
                           max_new=max_new or int(rng.integers(4, 14))))
    return out


def _offline(params, reqs, cfg=CFG):
    # the engine caps decode at the slot's context room (length_cap);
    # mirror it so the reference compares the same number of tokens
    return {r.id: np.asarray(generate(
        params, r.prompt[None, :], cfg,
        GenerateConfig(max_new_tokens=min(
            r.max_new_tokens, cfg.block_size - int(r.prompt.size) + 1),
            greedy=True)))[0].tolist() for r in reqs}


# ---------------------------------------------------------------------------
# windowed greedy parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("window", [2, 4, 8])
def test_windowed_greedy_parity_vs_offline(params, window):
    """Greedy output through the async window path must be
    byte-identical to offline generate() for every window size — a
    window is k steps of the SAME per-step math, not a different
    decode."""
    reqs = _requests(5)
    want = _offline(params, reqs)
    eng = Engine(params, CFG, EngineConfig(pool_size=3, max_queue=16,
                                           decode_window=window))
    for r in reqs:
        assert eng.submit(r) is None
    got = {r.id: r.tokens for r in eng.drain()}
    assert got == want
    assert eng.idle and eng._inflight is None
    dp = eng.metrics_summary()["dispatch"]
    assert dp["window_k"] == window
    # amortization actually engaged: fewer dispatches than tokens
    assert dp["dispatches"] < eng.metrics.counters["decode_tokens"]


def test_windowed_parity_packed_layout(params):
    """Both cache layouts ride the same window program — packed
    (L, B, S, C) pages must keep parity too."""
    pc = dataclasses.replace(CFG, decode_cache_layout="packed")
    reqs = _requests(4, seed=5)
    want = _offline(params, reqs, cfg=pc)
    eng = Engine(params, pc, EngineConfig(pool_size=2, max_queue=8,
                                          decode_window=4))
    for r in reqs:
        assert eng.submit(r) is None
    got = {r.id: r.tokens for r in eng.drain()}
    assert got == want


def test_windowed_stochastic_parity(params):
    """Sampled streams must also be window-size-invariant: the window
    body advances each slot's RNG exactly as the blocked loop does."""
    rng = np.random.default_rng(9)

    def reqs():
        return [Request(
            id=f"s{i}", prompt=rng.integers(0, 65, (4 + i,)).astype(np.int32),
            max_new_tokens=10,
            sampling=SamplingParams(temperature=0.8, top_k=12),
            rng_seed=100 + i) for i in range(3)]

    outs = []
    for window in (1, 8):
        rng = np.random.default_rng(9)
        eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=8,
                                               decode_window=window))
        for r in reqs():
            assert eng.submit(r) is None
        outs.append({r.id: r.tokens for r in eng.drain()})
    assert outs[0] == outs[1]


def test_mid_window_admission_arrival(params):
    """A request arriving while a window is in flight: the engine
    drains the window at the next step boundary, admits, and parity
    holds for both the running and the newly admitted stream."""
    reqs = _requests(3, seed=7, max_new=20)
    want = _offline(params, reqs)
    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=8,
                                           decode_window=4))
    assert eng.submit(reqs[0]) is None
    out = []
    out.extend(eng.step())            # admission step (blocked k=1)
    out.extend(eng.step())            # steady state: window launched
    assert eng._inflight is not None, "window should be in flight"
    # mid-window arrivals — next step must break the window for them
    assert eng.submit(reqs[1]) is None
    assert eng.submit(reqs[2]) is None
    out.extend(eng.drain())
    got = {r.id: r.tokens for r in out}
    assert got == want


def test_backlog_does_not_break_windows(params):
    """Admission batching: while the pool is FULL, a queued backlog
    must not force the engine back to blocked k=1 steps — arrivals
    wait at window boundaries."""
    reqs = _requests(4, seed=11, max_new=16)
    want = _offline(params, reqs)
    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=8,
                                           decode_window=4))
    for r in reqs:
        assert eng.submit(r) is None
    got = {r.id: r.tokens for r in eng.drain()}
    assert got == want
    dp = eng.metrics_summary()["dispatch"]
    # 4 requests x 16 tokens: a blocked engine would pay ~64 dispatches
    assert dp["dispatches"] < 40, dp


# ---------------------------------------------------------------------------
# EOS inside a window
# ---------------------------------------------------------------------------

def test_eos_inside_window_parity_and_release(params):
    """A request whose eos lands mid-window finishes with reason
    ``eos``, its stream is the offline stream truncated at (and
    including) the eos token, and its slot + pages free at the window
    boundary — identical at every window size."""
    base = _greedy("e0", [3, 1, 4, 1, 5], max_new=14)
    offline = _offline(params, [base])["e0"]
    eos_tok = offline[5]              # mid-stream token becomes the stop
    want = offline[:offline.index(eos_tok) + 1]
    for window in (1, 4, 8):
        eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=4,
                                               decode_window=window))
        req = _greedy("e0", [3, 1, 4, 1, 5], max_new=14, eos=eos_tok)
        assert eng.submit(req) is None
        res = {r.id: r for r in eng.drain()}["e0"]
        assert res.finish_reason == FINISH_EOS
        assert res.tokens == want, (window, res.tokens, want)
        assert res.ok
        assert eng.pool.n_free == 2   # slot + pages released
        assert eng.pool.alloc.pages_in_use == eng.metrics_summary()[
            "pages"]["radix_pages"]


def test_eos_out_of_vocab_rejected(params):
    eng = Engine(params, CFG, EngineConfig(pool_size=1))
    res = eng.submit(_greedy("bad", [1, 2], eos=CFG.vocab_size + 3))
    assert res is not None and res.finish_reason == REJECT_BAD_REQUEST


# ---------------------------------------------------------------------------
# cancel during a window
# ---------------------------------------------------------------------------

def test_cancel_during_window_releases_at_boundary(params):
    """cancel() with a dispatch in flight: the window drains first (its
    tokens ride the terminal result), then slot and pages release — a
    cancelled stream never holds capacity, and never yanks pages out
    from under an in-flight dispatch."""
    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=4,
                                           decode_window=4))
    req = _greedy("c0", [9, 2, 6], max_new=20)
    offline = _offline(params, [req])["c0"]
    assert eng.submit(req) is None
    eng.step()                        # admission (k=1, 1 token)
    eng.step()                        # window 1 launched
    assert eng._inflight is not None
    assert eng.cancel("c0")
    assert eng._inflight is None, "cancel must drain the window"
    assert eng.pool.n_free == 2, "slot + pages freed at the boundary"
    res = {r.id: r for r in eng.drain()}["c0"]
    assert res.finish_reason == FINISH_CANCELLED
    # tokens from the admission step AND the drained window, all
    # byte-identical to the offline prefix
    assert 1 <= len(res.tokens) <= 20
    assert res.tokens == offline[:len(res.tokens)]
    assert eng.idle


def test_cancel_after_window_finished_it(params):
    """A cancel racing a window that already finished the request (its
    eos landed mid-window): the drain surfaces the natural finish;
    cancel reports found. (Budget finishes can't race — the engine
    stops double-buffering once every live budget fits one window.)"""
    prompt = [32, 39, 63, 47]         # greedy stream: 47 x4 then 26...
    base = _offline(params, [_greedy("c1", prompt, max_new=20)])["c1"]
    # a token whose FIRST occurrence is inside the first full window
    # (after the k=1 admission step) — so the eos fires mid-window
    eos_tok = next(base[i] for i in range(1, 5)
                   if base.index(base[i]) == i)
    eng = Engine(params, CFG, EngineConfig(pool_size=1, max_queue=4,
                                           decode_window=4))
    assert eng.submit(_greedy("c1", prompt, max_new=20,
                              eos=eos_tok)) is None
    eng.step()                        # admission
    eng.step()                        # window in flight; eos inside it
    assert eng._inflight is not None
    assert eng.cancel("c1")
    res = {r.id: r for r in eng.drain()}["c1"]
    assert res.finish_reason == FINISH_EOS
    assert res.tokens == base[:base.index(eos_tok) + 1]


# ---------------------------------------------------------------------------
# speculative verify interleaved with windows
# ---------------------------------------------------------------------------

def test_spec_verify_interleaves_with_windows(params):
    """An engine with a drafter attached composes with decode windows:
    verify steps while speculation is active, multi-token windows while
    it is degraded, byte-identical greedy output through a
    disable -> window -> re-enable cycle."""
    from replicatinggpt_tpu.serve.speculative import NGramDrafter
    prompt = np.tile(np.array([7, 3, 7, 3], np.int32), 4)
    req = _greedy("sp0", prompt, max_new=20)
    want = _offline(params, [req])["sp0"]

    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=4,
                                           decode_window=4),
                 drafter=NGramDrafter(k=3))
    assert eng.submit(_greedy("sp0", prompt, max_new=20)) is None
    out = []
    out.extend(eng.step())            # admission
    out.extend(eng.step())            # verify step (spec active)
    assert eng.metrics.counters.get("spec_draft_tokens", 0) > 0
    disp_before = eng.metrics.counters.get("decode_dispatches", 0)
    eng.set_spec_active(False)        # degrade -> window path
    out.extend(eng.step())
    out.extend(eng.step())
    assert eng.metrics.counters["decode_dispatches"] > disp_before, \
        "degraded steps should run decode windows"
    out.extend(eng._drain_pending())  # settle before flipping back
    eng.set_spec_active(True)         # resync drafter from host history
    out.extend(eng.drain())
    got = {r.id: r.tokens for r in out}
    assert got == {"sp0": want}


def test_spec_eos_truncates_verify_window(params):
    """An eos accepted inside a speculative verify window ends the
    stream at the eos token — reason ``eos``, committed suffix past it
    dropped."""
    from replicatinggpt_tpu.serve.speculative import NGramDrafter
    prompt = np.tile(np.array([7, 3, 7, 3], np.int32), 4)
    base = _offline(params, [_greedy("x", prompt, max_new=16)])["x"]
    eos_tok = base[7]
    want = base[:base.index(eos_tok) + 1]
    eng = Engine(params, CFG, EngineConfig(pool_size=1, max_queue=4),
                 drafter=NGramDrafter(k=3))
    assert eng.submit(_greedy("x", prompt, max_new=16,
                              eos=eos_tok)) is None
    res = {r.id: r for r in eng.drain()}["x"]
    assert res.finish_reason == FINISH_EOS
    assert res.tokens == want


# ---------------------------------------------------------------------------
# zero recompiles across the whole async surface
# ---------------------------------------------------------------------------

def test_zero_recompiles_across_async_replay(params):
    """compile_counts stays flat through a scenario containing every
    async seam: mid-window admissions, EOS inside a window, a
    cancel-during-window, and a speculative disable/re-enable — after
    one warmup engine of identical shapes compiled the programs."""
    from replicatinggpt_tpu.serve.speculative import NGramDrafter
    ecfg = EngineConfig(pool_size=2, max_queue=16, decode_window=4)

    def build():
        return Engine(params, CFG, ecfg, drafter=NGramDrafter(k=3))

    def scenario(eng):
        out = []
        prompt = np.tile(np.array([7, 3, 7, 3], np.int32), 2)
        assert eng.submit(_greedy("a", prompt, max_new=24)) is None
        out.extend(eng.step())
        out.extend(eng.step())                 # verify steps
        eng.set_spec_active(False)             # -> windows
        out.extend(eng.step())
        out.extend(eng.step())
        assert eng.submit(_greedy("b", [1, 2, 3], max_new=12,
                                  eos=44)) is None   # mid-window arrival
        out.extend(eng.step())
        assert eng.submit(_greedy("c", [4, 4], max_new=16)) is None
        out.extend(eng.step())
        out.extend(eng.step())
        eng.cancel("a")                        # cancel during window
        out.extend(eng._drain_pending())
        eng.set_spec_active(True)              # re-probe path
        out.extend(eng.drain())
        return {r.id: r.finish_reason for r in out}

    warm = build()
    scenario(warm)
    counts = compile_counts()
    eng = build()
    reasons = scenario(eng)
    assert compile_counts() == counts, "async replay recompiled"
    assert set(reasons) == {"a", "b", "c"}
    assert reasons["a"] == FINISH_CANCELLED


# ---------------------------------------------------------------------------
# the BENCH_r03 CPU proxy: dispatch-split acceptance
# ---------------------------------------------------------------------------

def test_dispatch_split_3x_on_shared_prefix_trace(params):
    """THE acceptance pin: on the shared-prefix trace, host overhead
    per decoded token improves >= 3x at --decode-window 8 vs the
    blocked k=1 loop, with zero recompiles after warmup in both arms
    and >= 3x fewer dispatches per token (deterministic). The timing
    half retries up to 3 trials: a loaded CI machine can only make the
    windowed arm look WORSE (false lows), so one clean trial is the
    evidence — unloaded this measures 3.4-5.5x."""
    rcfg = ReplayConfig(n_requests=12, rate=50_000.0, seed=3,
                        prompt_len_min=6, prompt_len_max=9,
                        shared_prefix_len=5, max_new_tokens=24,
                        greedy=True, prompt_mode="shared_prefix")
    ecfg = EngineConfig(pool_size=4, max_queue=32, page_size=8)
    speedup = 0.0
    for _ in range(3):
        win = run_replay(params, CFG, rcfg,
                         dataclasses.replace(ecfg, decode_window=8))
        blk = run_replay(params, CFG, rcfg, ecfg)
        assert win["recompiles_after_warmup"] == 0
        assert blk["recompiles_after_warmup"] == 0
        assert win["n_completed"] == blk["n_completed"] == 12
        dw, db = win["dispatch"], blk["dispatch"]
        assert dw["window_k"] == 8 and db["window_k"] == 1
        # deterministic half: dispatches per token collapse by ~the
        # window (admission k=1 steps dilute the ideal 8x)
        tok_w = win["counters"]["decode_tokens"]
        tok_b = blk["counters"]["decode_tokens"]
        assert tok_w == tok_b
        assert ((db["dispatches"] / tok_b)
                / (dw["dispatches"] / tok_w)) >= 3.0
        # timing half (the BENCH_r03 CPU proxy): host ms/decoded token
        assert db["host_dispatch_ms_per_token"] > 0
        speedup = max(speedup, db["host_dispatch_ms_per_token"]
                      / dw["host_dispatch_ms_per_token"])
        if speedup >= 3.0:
            break
    assert speedup >= 3.0, (
        f"host overhead per token only improved {speedup:.2f}x across "
        f"3 trials (blocked {db}, windowed {dw})")


def test_windowed_greedy_byte_identical_on_shared_prefix_trace(params):
    """The other half of the acceptance line: the SAME shared-prefix
    request set decoded at window 8 and window 1 produces byte-
    identical greedy streams (run_replay measures; this pins tokens)."""
    rng = np.random.default_rng(3)
    shared = rng.integers(0, CFG.vocab_size, (8,)).astype(np.int32)

    def reqs():
        out = []
        for i in range(8):
            tail = rng.integers(0, CFG.vocab_size,
                                (int(rng.integers(2, 8)),))
            out.append(_greedy(f"p{i}",
                               np.concatenate([shared, tail]),
                               max_new=12))
        return out

    streams = []
    for window in (1, 8):
        rng = np.random.default_rng(3)
        shared = rng.integers(0, CFG.vocab_size, (8,)).astype(np.int32)
        eng = Engine(params, CFG, EngineConfig(pool_size=4, max_queue=32,
                                               page_size=8,
                                               decode_window=window))
        for r in reqs():
            assert eng.submit(r) is None
        streams.append({r.id: r.tokens for r in eng.drain()})
    assert streams[0] == streams[1]


# ---------------------------------------------------------------------------
# fused paged kernel composes with windows
# ---------------------------------------------------------------------------

def test_fused_kernel_with_decode_window(params, monkeypatch):
    """The fused all-layers paged kernel inside the window scan:
    parity with the XLA window path (interpret mode on CPU)."""
    from replicatinggpt_tpu.ops import paged_pallas
    monkeypatch.setattr(paged_pallas, "_paged_attn_backend_ok",
                        lambda: True)
    cfg = ModelConfig(vocab_size=65, block_size=32, n_layer=2, n_head=2,
                      n_embd=64, dropout=0.0, attn_dropout=0.0,
                      dtype="float32", decode_cache_layout="packed")
    p64 = init_params(jax.random.PRNGKey(1), cfg)
    reqs = [_greedy("f0", [3, 1, 4, 1, 5], max_new=6),
            _greedy("f1", [9, 2, 6], max_new=5)]
    want = _offline(p64, reqs, cfg=cfg)
    eng = Engine(p64, cfg, EngineConfig(pool_size=2, max_queue=4,
                                        page_size=8, paged_kernel=True,
                                        decode_window=2))
    assert eng._use_fused
    for r in reqs:
        assert eng.submit(r) is None
    got = {r.id: r.tokens for r in eng.drain()}
    assert got == want
