"""Async engine core (ISSUE 10) + continuous windows (ISSUE 13):
multi-token decode windows, donated device-resident step state,
double-buffered dispatch, the paged fused-decode kernel — and the
continuous-window upgrades: admissions riding MIXED prefill+decode
windows instead of breaking to blocked k=1, deadlines/cancels landing
as on-device lifecycle masks, and the bounded k-autotuner walking
warm bucketed programs. Greedy byte-parity with offline generate()
through every async seam, zero recompiles after warmup across a
replay containing all of the above, the deterministic dispatch-count
amortization pins, and the admission-storm retention acceptance
(>= 90% of idle-trace amortization held through an admission+cancel+
deadline storm)."""

import dataclasses

import jax
import numpy as np
import pytest

from replicatinggpt_tpu.config import ModelConfig
from replicatinggpt_tpu.models.gpt import init_params
from replicatinggpt_tpu.sample import GenerateConfig, generate
from replicatinggpt_tpu.serve import (Engine, EngineConfig, ReplayConfig,
                                      Request, SamplingParams,
                                      compile_counts, run_replay)
from replicatinggpt_tpu.serve.requests import (FINISH_CANCELLED,
                                               FINISH_DEADLINE, FINISH_EOS,
                                               FINISH_MAX_TOKENS,
                                               REJECT_BAD_REQUEST)

CFG = ModelConfig(vocab_size=65, block_size=32, n_layer=2, n_head=2,
                  n_embd=32, dropout=0.0, attn_dropout=0.0, dtype="float32")


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _greedy(rid, prompt, max_new=8, eos=None, seed=0):
    return Request(id=rid, prompt=np.asarray(prompt, np.int32),
                   max_new_tokens=max_new,
                   sampling=SamplingParams(greedy=True), rng_seed=seed,
                   eos_token_id=eos)


def _requests(n=5, seed=3, max_new=None):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        P = int(rng.integers(1, CFG.block_size // 2))
        prompt = rng.integers(0, CFG.vocab_size, (P,)).astype(np.int32)
        out.append(_greedy(f"r{i}", prompt,
                           max_new=max_new or int(rng.integers(4, 14))))
    return out


def _offline(params, reqs, cfg=CFG):
    # the engine caps decode at the slot's context room (length_cap);
    # mirror it so the reference compares the same number of tokens
    return {r.id: np.asarray(generate(
        params, r.prompt[None, :], cfg,
        GenerateConfig(max_new_tokens=min(
            r.max_new_tokens, cfg.block_size - int(r.prompt.size) + 1),
            greedy=True)))[0].tolist() for r in reqs}


# ---------------------------------------------------------------------------
# windowed greedy parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("window", [2, 4, 8])
def test_windowed_greedy_parity_vs_offline(params, window):
    """Greedy output through the async window path must be
    byte-identical to offline generate() for every window size — a
    window is k steps of the SAME per-step math, not a different
    decode."""
    reqs = _requests(5)
    want = _offline(params, reqs)
    eng = Engine(params, CFG, EngineConfig(pool_size=3, max_queue=16,
                                           decode_window=window))
    for r in reqs:
        assert eng.submit(r) is None
    got = {r.id: r.tokens for r in eng.drain()}
    assert got == want
    assert eng.idle and eng._inflight is None
    dp = eng.metrics_summary()["dispatch"]
    assert dp["window_k"] == window
    # amortization actually engaged: fewer dispatches than tokens
    assert dp["dispatches"] < eng.metrics.counters["decode_tokens"]


def test_windowed_parity_packed_layout(params):
    """Both cache layouts ride the same window program — packed
    (L, B, S, C) pages must keep parity too."""
    pc = dataclasses.replace(CFG, decode_cache_layout="packed")
    reqs = _requests(4, seed=5)
    want = _offline(params, reqs, cfg=pc)
    eng = Engine(params, pc, EngineConfig(pool_size=2, max_queue=8,
                                          decode_window=4))
    for r in reqs:
        assert eng.submit(r) is None
    got = {r.id: r.tokens for r in eng.drain()}
    assert got == want


def test_windowed_stochastic_parity(params):
    """Sampled streams must also be window-size-invariant: the window
    body advances each slot's RNG exactly as the blocked loop does."""
    rng = np.random.default_rng(9)

    def reqs():
        return [Request(
            id=f"s{i}", prompt=rng.integers(0, 65, (4 + i,)).astype(np.int32),
            max_new_tokens=10,
            sampling=SamplingParams(temperature=0.8, top_k=12),
            rng_seed=100 + i) for i in range(3)]

    outs = []
    for window in (1, 8):
        rng = np.random.default_rng(9)
        eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=8,
                                               decode_window=window))
        for r in reqs():
            assert eng.submit(r) is None
        outs.append({r.id: r.tokens for r in eng.drain()})
    assert outs[0] == outs[1]


def test_mid_window_admission_arrival(params):
    """A request arriving while a window is in flight: the engine
    admits at the next window BOUNDARY (host bookkeeping while the
    window flies, prefill riding the next mixed dispatch — no window
    break), and parity holds for both the running and the newly
    admitted stream."""
    reqs = _requests(3, seed=7, max_new=20)
    want = _offline(params, reqs)
    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=8,
                                           decode_window=4))
    assert eng.submit(reqs[0]) is None
    out = []
    out.extend(eng.step())            # admission boundary (mixed window)
    out.extend(eng.step())            # steady state: window launched
    assert eng._inflight is not None, "window should be in flight"
    # mid-window arrivals — admitted at the next boundary, windows held
    assert eng.submit(reqs[1]) is None
    assert eng.submit(reqs[2]) is None
    out.extend(eng.drain())
    got = {r.id: r.tokens for r in out}
    assert got == want
    wb = eng.metrics_summary()["window_breaks"]
    assert wb["admit"] == 0, wb


def test_backlog_does_not_break_windows(params):
    """Admission batching: while the pool is FULL, a queued backlog
    must not force the engine back to blocked k=1 steps — arrivals
    wait at window boundaries."""
    reqs = _requests(4, seed=11, max_new=16)
    want = _offline(params, reqs)
    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=8,
                                           decode_window=4))
    for r in reqs:
        assert eng.submit(r) is None
    got = {r.id: r.tokens for r in eng.drain()}
    assert got == want
    dp = eng.metrics_summary()["dispatch"]
    # 4 requests x 16 tokens: a blocked engine would pay ~64 dispatches
    assert dp["dispatches"] < 40, dp


# ---------------------------------------------------------------------------
# EOS inside a window
# ---------------------------------------------------------------------------

def test_eos_inside_window_parity_and_release(params):
    """A request whose eos lands mid-window finishes with reason
    ``eos``, its stream is the offline stream truncated at (and
    including) the eos token, and its slot + pages free at the window
    boundary — identical at every window size."""
    base = _greedy("e0", [3, 1, 4, 1, 5], max_new=14)
    offline = _offline(params, [base])["e0"]
    eos_tok = offline[5]              # mid-stream token becomes the stop
    want = offline[:offline.index(eos_tok) + 1]
    for window in (1, 4, 8):
        eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=4,
                                               decode_window=window))
        req = _greedy("e0", [3, 1, 4, 1, 5], max_new=14, eos=eos_tok)
        assert eng.submit(req) is None
        res = {r.id: r for r in eng.drain()}["e0"]
        assert res.finish_reason == FINISH_EOS
        assert res.tokens == want, (window, res.tokens, want)
        assert res.ok
        assert eng.pool.n_free == 2   # slot + pages released
        assert eng.pool.alloc.pages_in_use == eng.metrics_summary()[
            "pages"]["radix_pages"]


def test_eos_out_of_vocab_rejected(params):
    eng = Engine(params, CFG, EngineConfig(pool_size=1))
    res = eng.submit(_greedy("bad", [1, 2], eos=CFG.vocab_size + 3))
    assert res is not None and res.finish_reason == REJECT_BAD_REQUEST


# ---------------------------------------------------------------------------
# cancel during a window
# ---------------------------------------------------------------------------

def test_cancel_during_window_masks_at_next_dispatch(params):
    """cancel() with a dispatch in flight is a LIFECYCLE MASK, not a
    window break: the call defers (no drain, the in-flight window
    keeps flying), the kill flag rides the NEXT dispatch — after which
    the slot emits nothing — and the terminal result surfaces from the
    next step with the already-committed tokens, slot + pages freed at
    that boundary."""
    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=4,
                                           decode_window=4))
    req = _greedy("c0", [9, 2, 6], max_new=20)
    offline = _offline(params, [req])["c0"]
    assert eng.submit(req) is None
    eng.step()                        # admission boundary (mixed window)
    eng.step()                        # window 2 launched, window 1 drained
    assert eng._inflight is not None
    assert eng.cancel("c0")
    assert eng._inflight is not None, \
        "a masked cancel must NOT drain the in-flight window"
    out = eng.step()                  # kill flag rides this dispatch
    res = {r.id: r for r in out}["c0"]
    assert res.finish_reason == FINISH_CANCELLED
    assert eng.pool.n_free == 2, "slot + pages freed at the boundary"
    # tokens committed before the mask landed, byte-identical to the
    # offline prefix
    assert 1 <= len(res.tokens) <= 20
    assert res.tokens == offline[:len(res.tokens)]
    n_before = len(res.tokens)
    rest = eng.drain()                # the masked window drains empty
    assert eng.idle
    assert not rest and len(res.tokens) == n_before, \
        "a cancelled slot must emit no tokens after the mask lands"
    wb = eng.metrics_summary()["window_breaks"]
    assert wb["cancel"] == 0, wb


def test_deadline_expiry_masks_without_breaking_windows(params):
    """An ACTIVE request passing its deadline is killed through the
    same per-dispatch mask as a cancel — reason ``deadline``, tokens
    produced so far on the terminal result, zero window breaks — with
    the deadline precomputed at admission into the engine's vectorized
    expiry mirror."""
    class Clk:
        t = 0.0

        def __call__(self):
            return self.t

    clk = Clk()
    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=4,
                                           decode_window=4), clock=clk)
    req = _greedy("d0", [9, 2, 6], max_new=24)
    req.deadline = 100.0
    offline = _offline(params, [req])["d0"]
    assert eng.submit(req) is None
    eng.step()
    eng.step()
    assert eng._inflight is not None
    clk.t = 100.0                     # the deadline passes mid-window
    out = eng.step()                  # expiry -> kill flag, no drain-break
    res = {r.id: r for r in out}["d0"]
    assert res.finish_reason == FINISH_DEADLINE
    assert res.tokens == offline[:len(res.tokens)]
    assert eng.pool.n_free == 2
    n_before = len(res.tokens)
    eng.drain()
    assert eng.idle and len(res.tokens) == n_before
    wb = eng.metrics_summary()["window_breaks"]
    assert wb["deadline"] == 0 and wb["cancel"] == 0, wb


def test_cancel_after_window_finished_it(params):
    """A cancel racing a window that already finished the request (its
    eos landed mid-window): the drain surfaces the natural finish;
    cancel reports found. (Budget finishes can't race — the engine
    stops double-buffering once every live budget fits one window.)"""
    prompt = [32, 39, 63, 47]         # greedy stream: 47 x4 then 26...
    base = _offline(params, [_greedy("c1", prompt, max_new=20)])["c1"]
    # a token whose FIRST occurrence is inside the first full window
    # (after the k=1 admission step) — so the eos fires mid-window
    eos_tok = next(base[i] for i in range(1, 5)
                   if base.index(base[i]) == i)
    eng = Engine(params, CFG, EngineConfig(pool_size=1, max_queue=4,
                                           decode_window=4))
    assert eng.submit(_greedy("c1", prompt, max_new=20,
                              eos=eos_tok)) is None
    eng.step()                        # admission
    eng.step()                        # window in flight; eos inside it
    assert eng._inflight is not None
    assert eng.cancel("c1")
    res = {r.id: r for r in eng.drain()}["c1"]
    assert res.finish_reason == FINISH_EOS
    assert res.tokens == base[:base.index(eos_tok) + 1]


# ---------------------------------------------------------------------------
# speculative verify interleaved with windows
# ---------------------------------------------------------------------------

def test_spec_verify_interleaves_with_windows(params):
    """An engine with a drafter attached composes with decode windows:
    verify steps while speculation is active, multi-token windows while
    it is degraded, byte-identical greedy output through a
    disable -> window -> re-enable cycle."""
    from replicatinggpt_tpu.serve.speculative import NGramDrafter
    prompt = np.tile(np.array([7, 3, 7, 3], np.int32), 4)
    req = _greedy("sp0", prompt, max_new=20)
    want = _offline(params, [req])["sp0"]

    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=4,
                                           decode_window=4),
                 drafter=NGramDrafter(k=3))
    assert eng.submit(_greedy("sp0", prompt, max_new=20)) is None
    out = []
    out.extend(eng.step())            # admission
    out.extend(eng.step())            # verify step (spec active)
    assert eng.metrics.counters.get("spec_draft_tokens", 0) > 0
    disp_before = eng.metrics.counters.get("decode_dispatches", 0)
    eng.set_spec_active(False)        # degrade -> window path
    out.extend(eng.step())
    out.extend(eng.step())
    assert eng.metrics.counters["decode_dispatches"] > disp_before, \
        "degraded steps should run decode windows"
    out.extend(eng._drain_pending())  # settle before flipping back
    eng.set_spec_active(True)         # resync drafter from host history
    out.extend(eng.drain())
    got = {r.id: r.tokens for r in out}
    assert got == {"sp0": want}


def test_spec_eos_truncates_verify_window(params):
    """An eos accepted inside a speculative verify window ends the
    stream at the eos token — reason ``eos``, committed suffix past it
    dropped."""
    from replicatinggpt_tpu.serve.speculative import NGramDrafter
    prompt = np.tile(np.array([7, 3, 7, 3], np.int32), 4)
    base = _offline(params, [_greedy("x", prompt, max_new=16)])["x"]
    eos_tok = base[7]
    want = base[:base.index(eos_tok) + 1]
    eng = Engine(params, CFG, EngineConfig(pool_size=1, max_queue=4),
                 drafter=NGramDrafter(k=3))
    assert eng.submit(_greedy("x", prompt, max_new=16,
                              eos=eos_tok)) is None
    res = {r.id: r for r in eng.drain()}["x"]
    assert res.finish_reason == FINISH_EOS
    assert res.tokens == want


# ---------------------------------------------------------------------------
# zero recompiles across the whole async surface
# ---------------------------------------------------------------------------

def test_zero_recompiles_across_async_replay(params):
    """compile_counts stays flat through a scenario containing every
    async seam: mid-window admissions, EOS inside a window, a
    cancel-during-window, and a speculative disable/re-enable — after
    one warmup engine of identical shapes compiled the programs."""
    from replicatinggpt_tpu.serve.speculative import NGramDrafter
    ecfg = EngineConfig(pool_size=2, max_queue=16, decode_window=4)

    def build():
        return Engine(params, CFG, ecfg, drafter=NGramDrafter(k=3))

    def scenario(eng):
        out = []
        prompt = np.tile(np.array([7, 3, 7, 3], np.int32), 2)
        assert eng.submit(_greedy("a", prompt, max_new=24)) is None
        out.extend(eng.step())
        out.extend(eng.step())                 # verify steps
        eng.set_spec_active(False)             # -> windows
        out.extend(eng.step())
        out.extend(eng.step())
        assert eng.submit(_greedy("b", [1, 2, 3], max_new=12,
                                  eos=44)) is None   # mid-window arrival
        out.extend(eng.step())
        assert eng.submit(_greedy("c", [4, 4], max_new=16)) is None
        out.extend(eng.step())
        out.extend(eng.step())
        eng.cancel("a")                        # cancel during window
        out.extend(eng._drain_pending())
        eng.set_spec_active(True)              # re-probe path
        out.extend(eng.drain())
        return {r.id: r.finish_reason for r in out}

    warm = build()
    scenario(warm)
    counts = compile_counts()
    eng = build()
    reasons = scenario(eng)
    assert compile_counts() == counts, "async replay recompiled"
    assert set(reasons) == {"a", "b", "c"}
    assert reasons["a"] == FINISH_CANCELLED


# ---------------------------------------------------------------------------
# the BENCH_r03 CPU proxy: dispatch-split acceptance
# ---------------------------------------------------------------------------

def test_dispatch_split_on_shared_prefix_trace(params):
    """The dispatch-amortization acceptance pin, continuous-window
    edition. The DETERMINISTIC half is the load-bearing one: dispatches
    per decoded token collapse >= 4x at --decode-window 8 vs the
    blocked k=1 loop (admissions now ride mixed windows, so the old
    k=1-admission dilution is gone), with zero recompiles after warmup
    in both arms. The wall-clock half is a regression floor, not a
    multiplier: this PR's launch-input caching removed the
    per-dispatch device_put tax that WAS the 3-5x timing headroom of
    the PR 10 pin (both arms now skip it), and what remains of a CPU
    launch is XLA:CPU executing thunks inline on the dispatching
    thread — device time a TPU launch does not pay, scaling with k by
    construction. So on CPU we pin that the windowed arm's wall-clock
    launch cost per token stays in the same band as blocked (<= 1.6x,
    3 trials, best kept) while the TPU row queued in RESULTS.md
    carries the real timing multiplier."""
    rcfg = ReplayConfig(n_requests=12, rate=50_000.0, seed=3,
                        prompt_len_min=6, prompt_len_max=9,
                        shared_prefix_len=5, max_new_tokens=24,
                        greedy=True, prompt_mode="shared_prefix")
    ecfg = EngineConfig(pool_size=4, max_queue=32, page_size=8)
    ratio = float("inf")
    for _ in range(3):
        win = run_replay(params, CFG, rcfg,
                         dataclasses.replace(ecfg, decode_window=8))
        blk = run_replay(params, CFG, rcfg, ecfg)
        assert win["recompiles_after_warmup"] == 0
        assert blk["recompiles_after_warmup"] == 0
        assert win["n_completed"] == blk["n_completed"] == 12
        dw, db = win["dispatch"], blk["dispatch"]
        assert dw["window_k"] == 8 and db["window_k"] == 1
        # deterministic half: dispatches per token
        tok_w = win["counters"]["decode_tokens"]
        tok_b = blk["counters"]["decode_tokens"]
        assert tok_w == tok_b
        assert ((db["dispatches"] / tok_b)
                / (dw["dispatches"] / tok_w)) >= 4.0
        # continuous windows: the saturating backlog admits at window
        # boundaries without a single break
        assert win["window_breaks"]["admit"] == 0
        # wall-clock floor (see docstring)
        assert db["host_dispatch_ms_per_token"] > 0
        ratio = min(ratio, dw["host_dispatch_ms_per_token"]
                    / db["host_dispatch_ms_per_token"])
        if ratio <= 1.6:
            break
    assert ratio <= 1.6, (
        f"windowed launch cost fell {ratio:.2f}x behind blocked across "
        f"3 trials (blocked {db}, windowed {dw})")


def test_windowed_greedy_byte_identical_on_shared_prefix_trace(params):
    """The other half of the acceptance line: the SAME shared-prefix
    request set decoded at window 8 and window 1 produces byte-
    identical greedy streams (run_replay measures; this pins tokens)."""
    rng = np.random.default_rng(3)
    shared = rng.integers(0, CFG.vocab_size, (8,)).astype(np.int32)

    def reqs():
        out = []
        for i in range(8):
            tail = rng.integers(0, CFG.vocab_size,
                                (int(rng.integers(2, 8)),))
            out.append(_greedy(f"p{i}",
                               np.concatenate([shared, tail]),
                               max_new=12))
        return out

    streams = []
    for window in (1, 8):
        rng = np.random.default_rng(3)
        shared = rng.integers(0, CFG.vocab_size, (8,)).astype(np.int32)
        eng = Engine(params, CFG, EngineConfig(pool_size=4, max_queue=32,
                                               page_size=8,
                                               decode_window=window))
        for r in reqs():
            assert eng.submit(r) is None
        streams.append({r.id: r.tokens for r in eng.drain()})
    assert streams[0] == streams[1]


# ---------------------------------------------------------------------------
# continuous windows: admission storm, retention, k-autotune (ISSUE 13)
# ---------------------------------------------------------------------------

class _VClock:
    """Virtual clock: the storm driver advances it one dt per engine
    step, so admission order, deadline expiry and cancel timing are
    identical run to run (the loadgen StepClock pattern)."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _drive_storm(params, storm, window, dt=0.005, pool=4):
    """Replay an admission_storm() tuple through a fresh engine on a
    virtual clock; returns (engine, {id: RequestResult})."""
    trace, cancels, deadlines = storm
    clk = _VClock()
    eng = Engine(params, CFG,
                 EngineConfig(pool_size=pool, max_queue=128,
                              decode_window=window), clock=clk)
    results = {}
    i = ci = 0
    guard = 0
    while len(results) < len(trace):
        guard += 1
        assert guard < 100_000, "storm replay did not converge"
        now = clk()
        while i < len(trace) and trace[i][0] <= now:
            _, req = trace[i]
            if req.id in deadlines:
                req.deadline = now + deadlines[req.id]
            rej = eng.submit(req)
            if rej is not None:
                results[rej.id] = rej
            i += 1
        while ci < len(cancels) and cancels[ci][0] <= now:
            eng.cancel(cancels[ci][1])
            ci += 1
        if eng.idle:
            if i < len(trace):
                clk.t = max(clk.t + dt, trace[i][0])
                continue
            break
        for r in eng.step():
            results[r.id] = r
        clk.t += dt
    return eng, results


def _storm(n=48, seed=0, **kw):
    from replicatinggpt_tpu.serve.loadgen import (AdmissionStormConfig,
                                                  admission_storm)
    return admission_storm(CFG, AdmissionStormConfig(
        n_requests=n, seed=seed, deadline_s=0.08, cancel_after_s=0.02,
        **kw))


def test_admission_storm_token_identity_and_no_breaks(params):
    """THE satellite pin: across an admission+cancel+deadline storm at
    decode_window > 1, every greedy stream is a byte-prefix of the
    offline stream (cut exactly where its cancel/deadline mask landed),
    fully-completed streams are byte-identical, compile_counts stays
    flat against a warm engine of the same shapes, and NOT ONE window
    break is charged to admit/deadline/cancel — the storm rides the
    continuous-window path end to end."""
    storm = _storm()
    offline = _offline(params, [r for _, r in storm[0]])
    _drive_storm(params, storm, 8)            # warm (construction + drive)
    counts = compile_counts()
    eng, res = _drive_storm(params, storm, 8)
    assert compile_counts() == counts, "storm replay recompiled"
    assert len(res) == len(storm[0])
    finished = {r.finish_reason for r in res.values()}
    assert FINISH_CANCELLED in finished       # the storm really stormed
    assert FINISH_DEADLINE in finished
    for _, req in storm[0]:
        toks = res[req.id].tokens
        assert toks == offline[req.id][:len(toks)], req.id
        if res[req.id].finish_reason == FINISH_MAX_TOKENS:
            assert toks == offline[req.id], req.id
    wb = eng.metrics_summary()["window_breaks"]
    assert wb["admit"] == wb["deadline"] == wb["cancel"] == 0, wb


def test_storm_retains_idle_amortization(params):
    """THE ISSUE 13 acceptance: on the admission-heavy saturating
    trace the dispatch-split retains >= 90% of the idle-trace window
    amortization. Amortization is the deterministic dispatch-count
    split (blocked dispatches-per-token over windowed
    dispatches-per-token, same virtual-clock trace both arms); the
    pre-continuous-windows engine collapses to ~1.0 here by
    construction, because every admission-laden step fell back to
    blocked k=1."""
    storm = _storm()
    idle = (storm[0], [], {})     # same arrivals, no lifecycle churn

    def amortization(tr):
        eng_w, _ = _drive_storm(params, tr, 8)
        eng_b, _ = _drive_storm(params, tr, 1)
        cw, cb = eng_w.metrics.counters, eng_b.metrics.counters
        dpt_w = cw["decode_dispatches"] / cw["decode_tokens"]
        dpt_b = cb["decode_dispatches"] / cb["decode_tokens"]
        return dpt_b / dpt_w

    a_idle = amortization(idle)
    a_storm = amortization(storm)
    assert a_idle >= 4.0, a_idle  # windows genuinely amortize when idle
    assert a_storm >= 0.9 * a_idle, (
        f"storm kept only {a_storm / a_idle:.1%} of the idle-trace "
        f"amortization ({a_storm:.2f}x vs {a_idle:.2f}x)")


def test_autotune_climbs_buckets_zero_recompiles(params):
    """decode_window_auto: the additive-increase policy walks the
    bucketed window sizes (2 -> 4 -> 8 under CPU host-dispatch
    fractions) without a single recompile — every bucket's programs
    compiled at construction — and greedy streams are byte-identical
    to offline through the bucket moves."""
    ecfg = EngineConfig(pool_size=2, max_queue=64, decode_window=8,
                        decode_window_auto=True)
    assert ecfg.window_buckets() == (2, 4, 8)

    def reqs():
        return [_greedy(f"a{i}", [3 + i % 5, 1, 4], max_new=28)
                for i in range(12)]

    want = _offline(params, reqs())
    warm = Engine(params, CFG, ecfg)
    for r in reqs():
        warm.submit(r)
    warm.drain()
    counts = compile_counts()
    eng = Engine(params, CFG, ecfg)
    for r in reqs():
        assert eng.submit(r) is None
    got = {r.id: r.tokens for r in eng.drain()}
    assert got == want, "bucket moves must not change the streams"
    assert compile_counts() == counts, "a bucket move recompiled"
    dp = eng.metrics_summary()["dispatch"]
    assert dp["autotune"] and dp["window_k_max"] == 8
    assert dp["window_k"] in (2, 4, 8)
    assert dp["autotune_increases"] >= 1, dp


def test_spec_transition_mid_prefill_flushes_chunks(params):
    """A speculative re-enable while a windowed admission's in-window
    prefill is still INCOMPLETE (multi-window prefill: small
    prefill_chunk, window smaller than the chunk count) must complete
    the outstanding chunks host-side before the verify path runs —
    verify attends the slot's whole prompt range, so abandoned chunks
    would leave never-written (zero) K/V pages in that range and
    silently corrupt the stream (review-caught). Greedy argmax at
    random init is too flat to catch zero-row dilution, so the
    detector is the invariant itself: after the flip, no chunks
    outstanding and every prompt position's K row physically written —
    plus end-to-end parity."""
    from replicatinggpt_tpu.serve.speculative import NGramDrafter
    prompt = np.tile(np.array([7, 3, 7, 3], np.int32), 5)   # 20 tokens
    req = _greedy("mp0", prompt, max_new=10)
    want = _offline(params, [req])["mp0"]
    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=4,
                                           decode_window=2,
                                           prefill_chunk=4),
                 drafter=NGramDrafter(k=3))
    eng.set_spec_active(False)        # windows engage (pinned degraded)
    assert eng.submit(_greedy("mp0", prompt, max_new=10)) is None
    out = []
    out.extend(eng.step())            # admission boundary: mixed window
                                      # covers 2 of the 5 prompt chunks
    assert eng._pf_left.max() > 0, "prefill must still be outstanding"
    slot = eng.pool.slot_of("mp0")
    eng.set_spec_active(True)         # mid-prefill spec flip
    assert eng._pf_left.max() == 0, \
        "outstanding chunks must flush at the spec flip"
    # every prompt position's K row is physically written (the offset
    # axis is -2 in both cache layouts); position P-1 gets rewritten by
    # the first decode either way, so [0, P) is the invariant range
    k = np.asarray(eng.pool.cache["k"])
    psz = eng.pool.page_size
    tbl = eng.pool.tables[slot]
    for p_abs in range(int(prompt.size)):
        row = np.moveaxis(k[:, tbl[p_abs // psz]], -2, 0)[p_abs % psz]
        assert np.abs(row).sum() > 0, f"prompt position {p_abs} unwritten"
    out.extend(eng.drain())           # verify path decodes the rest
    got = {r.id: r.tokens for r in out}
    assert got == {"mp0": want}


def test_spec_transitions_still_count_window_breaks(params):
    """The one seam that legitimately still breaks windows: a
    speculative mode flip drains the in-flight window and the
    window_breaks{spec} counter records it (the PR's before/after
    observability — lifecycle reasons stay zero, spec does not)."""
    from replicatinggpt_tpu.serve.speculative import NGramDrafter
    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=4,
                                           decode_window=4),
                 drafter=NGramDrafter(k=3))
    eng.set_spec_active(False)
    prompt = np.tile(np.array([7, 3, 7, 3], np.int32), 4)
    assert eng.submit(_greedy("s0", prompt, max_new=20)) is None
    eng.step()
    eng.step()
    assert eng._inflight is not None
    eng.set_spec_active(True)         # drains the window: a spec break
    eng.drain()
    wb = eng.metrics_summary()["window_breaks"]
    assert wb["spec"] >= 1, wb
    assert wb["admit"] == wb["deadline"] == wb["cancel"] == 0, wb


# ---------------------------------------------------------------------------
# fused paged kernel composes with windows
# ---------------------------------------------------------------------------

def test_fused_kernel_with_decode_window(params, monkeypatch):
    """The fused all-layers paged kernel inside the window scan:
    parity with the XLA window path (interpret mode on CPU)."""
    from replicatinggpt_tpu.ops import paged_pallas
    monkeypatch.setattr(paged_pallas, "_paged_attn_backend_ok",
                        lambda: True)
    cfg = ModelConfig(vocab_size=65, block_size=32, n_layer=2, n_head=2,
                      n_embd=64, dropout=0.0, attn_dropout=0.0,
                      dtype="float32", decode_cache_layout="packed")
    p64 = init_params(jax.random.PRNGKey(1), cfg)
    reqs = [_greedy("f0", [3, 1, 4, 1, 5], max_new=6),
            _greedy("f1", [9, 2, 6], max_new=5)]
    want = _offline(p64, reqs, cfg=cfg)
    eng = Engine(p64, cfg, EngineConfig(pool_size=2, max_queue=4,
                                        page_size=8, paged_kernel=True,
                                        decode_window=2))
    assert eng._use_fused
    for r in reqs:
        assert eng.submit(r) is None
    got = {r.id: r.tokens for r in eng.drain()}
    assert got == want
