"""Out-of-process fleet tests (serve/rpc.py + serve/worker.py +
faults/procsup.py): the RPC framing/codecs and ack-redelivery protocol,
the journal's cross-process exclusivity + fsync knobs, the worker
dispatch table, the supervisor's restart-budget/quarantine policy —
and, under ``-m "multiproc and slow"``, the pinned acceptance soaks:
a greedy stream token-identical across a REAL ``kill -9`` of a worker
process mid-decode, a rolling restart of every worker with zero
dropped requests and ``/readyz`` flipping 503 -> 200, cross-process
duplicate-id dedupe through a restart, and SIGSTOP (proc_hang) chaos.

The fast tier spawns at most ONE worker subprocess (the smoke); the
unit tests fake the engine/process ends of the protocol."""

import asyncio
import importlib.util
import json
import os
import pathlib
import signal
import sys
import time

import numpy as np
import pytest

from replicatinggpt_tpu.config import get_config
from replicatinggpt_tpu.faults import Fault, FaultPlan, installed
from replicatinggpt_tpu.faults.fleet import (FLEET_STEP, KIND_PROC_HANG,
                                             KIND_PROC_KILL)
from replicatinggpt_tpu.faults.netchaos import (NET_CALL, FaultyTransport,
                                                KIND_NET_CORRUPT,
                                                KIND_NET_DELAY,
                                                KIND_NET_DROP,
                                                KIND_NET_DUP,
                                                KIND_NET_PARTITION,
                                                KIND_NET_REORDER,
                                                KIND_NET_TRICKLE,
                                                net_site)
from replicatinggpt_tpu.faults.procsup import (BACKOFF, QUARANTINED,
                                               ProcSupervisor, RUNNING,
                                               SupervisorConfig,
                                               WorkerSpec,
                                               make_worker_specs,
                                               spawn_fleet)
from replicatinggpt_tpu.serve import (JournalBusyError, RequestJournal,
                                      RouterConfig)
from replicatinggpt_tpu.serve.requests import (FINISH_CANCELLED,
                                               REJECT_BAD_REQUEST,
                                               Request, RequestResult,
                                               SamplingParams)
from replicatinggpt_tpu.serve.rpc import (HEADER_BYTES,
                                          REJECT_REPLICA_DOWN, RpcClient,
                                          RpcDown, RpcError,
                                          RpcProtocolError, RpcTimeout,
                                          crc_ok, decode_header,
                                          encode_frame,
                                          request_from_wire,
                                          request_to_wire,
                                          result_from_wire,
                                          result_to_wire,
                                          serve_connection)
from replicatinggpt_tpu.serve.worker import (IDEMPOTENT_VERBS,
                                             REPLY_CACHE_SIZE,
                                             WorkerServer)

pytestmark = [pytest.mark.fleet, pytest.mark.multiproc]

REPO = pathlib.Path(__file__).resolve().parents[1]

CFG = get_config("test-tiny").model


def _offline(prompt, n):
    """Greedy reference through the same params every test-tiny worker
    builds (create_train_state is deterministic in the preset seed)."""
    import jax

    from replicatinggpt_tpu.sample import GenerateConfig, generate
    from replicatinggpt_tpu.train.state import create_train_state
    tcfg = get_config("test-tiny")
    state = create_train_state(jax.random.PRNGKey(tcfg.train.seed),
                               tcfg.model, tcfg.train)
    return np.asarray(generate(
        state.params, np.asarray(prompt, np.int32)[None, :], tcfg.model,
        GenerateConfig(max_new_tokens=n, greedy=True)))[0].tolist()


def _reqs(n, seed=7, max_new=8, prompt_len=4):
    rng = np.random.default_rng(seed)
    return [Request(
        id=f"m{seed}_{i}",
        prompt=rng.integers(1, CFG.vocab_size - 1,
                            (prompt_len,)).astype(np.int32),
        max_new_tokens=max_new, sampling=SamplingParams(greedy=True),
        rng_seed=seed * 1000 + i) for i in range(n)]


def _spawn(tmp_path, n_workers, rcfg=None, scfg=None, telemetry=None):
    jdir = str(tmp_path / "journals")
    specs = make_worker_specs(n_workers, jdir, ["--preset", "test-tiny"],
                              ["--pool-size", "2", "--max-queue", "16"])
    rcfg = rcfg or RouterConfig(n_replicas=n_workers, journal_dir=jdir,
                                step_timeout_s=5.0)
    scfg = scfg or SupervisorConfig(backoff_s=0.2, probe_every=4,
                                    probe_timeout_s=1.0)
    return spawn_fleet(specs, rcfg, scfg, telemetry=telemetry)


def _drain_streaming(router, sup, ids, budget_s=240.0):
    """Step the fleet (ticking the supervisor) while consuming the
    delivery ledger every step; returns (results, streams)."""
    results, streams = {}, {i: [] for i in ids}
    deadline = time.monotonic() + budget_s
    while not router.idle:
        assert time.monotonic() < deadline, (
            f"fleet did not drain: done={sorted(results)} "
            f"router={router.events[-6:]} sup={sup.events[-6:]}")
        for res in router.step():
            results[res.id] = res
        for rid in streams:
            streams[rid].extend(router.take_new_tokens(rid))
        sup.tick()
    return results, streams


def _trace_check():
    spec = importlib.util.spec_from_file_location(
        "trace_check", REPO / "tools" / "trace_check.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# RPC protocol units (no subprocess)
# ---------------------------------------------------------------------------

def test_rpc_framing_and_bounds():
    frame = encode_frame({"op": "health", "x": 1})
    n, crc = decode_header(frame[:HEADER_BYTES])
    body = frame[HEADER_BYTES:]
    assert n == len(body)
    assert crc_ok(body, crc)
    assert json.loads(body) == {"op": "health", "x": 1}
    # a single flipped body byte must fail the checksum, not decode
    assert not crc_ok(bytes([body[0] ^ 0xFF]) + body[1:], crc)
    # a corrupt length prefix must not allocate gigabytes
    with pytest.raises(ValueError, match="frame too large"):
        decode_header((1 << 30).to_bytes(4, "big") + b"\x00" * 4)
    with pytest.raises(ValueError, match="frame too large"):
        encode_frame({"blob": "x" * (17 << 20)})


def test_rpc_wire_codecs_roundtrip():
    req = Request(id="w1", prompt=np.asarray([3, 1, 4], np.int32),
                  max_new_tokens=7,
                  sampling=SamplingParams(temperature=0.5, top_k=3,
                                          top_p=0.9, greedy=False),
                  deadline=105.0, rng_seed=42)
    doc = json.loads(json.dumps(request_to_wire(req, now=100.0)))
    back = request_from_wire(doc, now=200.0)
    assert back.id == "w1" and back.prompt.tolist() == [3, 1, 4]
    assert back.max_new_tokens == 7 and back.rng_seed == 42
    assert back.sampling == req.sampling
    # deadlines cross as REMAINING seconds, rebased on the far clock
    assert back.deadline == pytest.approx(205.0)
    assert request_from_wire(
        json.loads(json.dumps(request_to_wire(
            Request(id="w2", prompt=np.asarray([1], np.int32),
                    max_new_tokens=1,
                    sampling=SamplingParams(greedy=True)), 5.0))),
        9.0).deadline is None
    res = RequestResult(id="w1", tokens=[1, 2, 3],
                        finish_reason="max_tokens", queue_wait_s=0.1,
                        ttft_s=0.2, decode_tokens_per_s=30.0,
                        total_s=0.5)
    back = result_from_wire(json.loads(json.dumps(result_to_wire(res))))
    assert (back.id, back.tokens, back.finish_reason) == \
        ("w1", [1, 2, 3], "max_tokens")
    assert back.ttft_s == pytest.approx(0.2)


def test_rpc_client_server_roundtrip_over_socket():
    """RpcClient against a real asyncio serve_connection loop: ok
    responses, dispatch exceptions as framed RpcError (NOT a dropped
    socket), reconnect after server close raises RpcDown."""
    calls = []

    def dispatch(doc):
        calls.append(doc["op"])
        if doc["op"] == "boom":
            raise RuntimeError("engine exploded")
        return {"echo": doc.get("x")}

    async def main():
        server = await asyncio.start_server(
            lambda r, w: serve_connection(r, w, dispatch),
            "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        loop = asyncio.get_running_loop()

        def client_side():
            c = RpcClient("127.0.0.1", port, timeout_s=5.0)
            assert c.call("ping", x=3)["echo"] == 3
            with pytest.raises(RpcError, match="engine exploded"):
                c.call("boom")
            # the connection survives a dispatch error (framed, not cut)
            assert c.call("ping", x=4)["echo"] == 4
            return c

        c = await loop.run_in_executor(None, client_side)
        server.close()
        await server.wait_closed()
        c.close()

        def after_close():
            # reconnect against the closed listener: RpcDown, not hang
            with pytest.raises(RpcDown):
                c.call("ping", x=5)

        await loop.run_in_executor(None, after_close)

    asyncio.run(main())
    assert calls[:3] == ["ping", "boom", "ping"]


def test_recv_exact_eof_classification():
    """EOF position decides the failure class: a peer that closes
    BETWEEN frames (read the request, never answered) is a dead/
    restarting worker — RpcDown, retry elsewhere. A peer that closes
    MID-frame (partial header or partial body) tore a frame — that is
    a protocol failure (RpcProtocolError), and the retry-once path
    must reconnect with the SAME idem key rather than re-route."""
    mode = {"m": "idle_eof"}

    async def handler(reader, writer):
        try:
            header = await reader.readexactly(HEADER_BYTES)
            n, _ = decode_header(header)
            await reader.readexactly(n)
        except asyncio.IncompleteReadError:
            return
        m = mode["m"]
        if m == "torn_header":
            writer.write(b"\x00\x00\x00")           # 3 of 8 header bytes
            await writer.drain()
        elif m == "torn_body":
            frame = encode_frame({"ok": True})
            writer.write(frame[:HEADER_BYTES + 2])  # full header, 2 of n
            await writer.drain()
        writer.close()                              # idle_eof: reply-less

    async def main():
        server = await asyncio.start_server(handler, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        loop = asyncio.get_running_loop()

        def client_side():
            c = RpcClient("127.0.0.1", port, timeout_s=5.0)
            with pytest.raises(RpcDown, match="connection closed"):
                c.call("ping")
            c.close()
            mode["m"] = "torn_header"
            with pytest.raises(RpcProtocolError, match="mid-frame"):
                c.call("ping")
            c.close()
            mode["m"] = "torn_body"
            with pytest.raises(RpcProtocolError, match="mid-frame"):
                c.call("ping")
            c.close()

        await loop.run_in_executor(None, client_side)
        server.close()
        await server.wait_closed()

    asyncio.run(main())


# ---------------------------------------------------------------------------
# journal durability satellites
# ---------------------------------------------------------------------------

def test_journal_lock_excludes_second_writer(tmp_path):
    """Exclusive flock at open: two processes (or two opens — flock is
    per open-file-description) can never append to one journal; the
    lock dies with its holder, so close() frees it."""
    path = str(tmp_path / "j.jsonl")
    j1 = RequestJournal(path, lock=True)
    with pytest.raises(JournalBusyError):
        RequestJournal(path, lock=True)
    # readers never lock: unfinished() works against a held journal
    j1.record_submit(_reqs(1)[0])
    assert len(RequestJournal.unfinished(path)) == 1
    j1.close()
    j2 = RequestJournal(path, lock=True)   # freed with the holder
    j2.close()


def test_journal_fsync_finish_knob(tmp_path, monkeypatch):
    """fsync_finish fsyncs finish records only: a lost finish would
    re-deliver a request the client saw complete, a lost submit only
    loses an un-started request the router retries."""
    synced = []
    real_fsync = os.fsync

    def counting_fsync(fd):
        synced.append(fd)
        return real_fsync(fd)

    monkeypatch.setattr(os, "fsync", counting_fsync)
    j = RequestJournal(str(tmp_path / "f.jsonl"), fsync_finish=True)
    j.record_submit(_reqs(1)[0])
    assert not synced                      # submits: flush-only
    j.record_finish(_reqs(1)[0].id, "max_tokens")
    assert len(synced) == 1                # finishes: fsynced
    j.close()
    off = RequestJournal(str(tmp_path / "g.jsonl"), fsync_finish=False)
    off.record_finish("x", "max_tokens")
    assert len(synced) == 1                # knob off: no fsync
    off.close()


def test_journal_torn_tail_contract_repinned(tmp_path):
    """The reader contract under the new writer knobs is unchanged:
    a torn final line (crash mid-append) is skipped, never raises, and
    the intact prefix replays."""
    path = str(tmp_path / "torn.jsonl")
    j = RequestJournal(path, fsync_finish=True)
    a, b = _reqs(2, seed=9)
    j.record_submit(a)
    j.record_submit(b)
    j.record_finish(a.id, "max_tokens")
    j.close()
    with open(path, "a") as f:
        f.write('{"ev": "finish", "id": "m9_1", "rea')   # torn tail
    pending = RequestJournal.unfinished(path)
    assert [r.id for r in pending] == [b.id]


def test_journal_torn_tail_with_duplicated_finish_lines(tmp_path):
    """A retried/duplicated finish append (the crash window between
    record_finish and the ack that would have suppressed the retry)
    plus a torn tail in ONE file: the reader must survive both — each
    duplicated finish counts once (last reason wins), the torn line is
    skipped, and the journal_drain view the router reconciles from
    lists every finished id exactly once."""
    path = str(tmp_path / "dupfin.jsonl")
    j = RequestJournal(path)
    a, b, c = _reqs(3, seed=13)
    for q in (a, b, c):
        j.record_submit(q)
    j.record_finish(a.id, "max_tokens")
    j.record_finish(a.id, "max_tokens")      # exact duplicate line
    j.record_finish(b.id, "max_tokens")
    j.record_finish(b.id, "cancelled")       # duplicate, new reason
    j.close()
    with open(path, "a") as f:
        f.write('{"ev": "finish", "id": "' + c.id + '", "rea')
    pending = RequestJournal.unfinished(path)
    assert [r.id for r in pending] == [c.id]     # dups never resurrect
    # the RPC-visible view: one finished record per id, last reason
    w = WorkerServer(_FakeEngine(), journal=RequestJournal(path))
    resp = w.dispatch({"op": "journal_drain", "cursor": 0})
    assert resp["eof"]
    finished = [r for r in resp["records"] if r["kind"] == "finished"]
    assert sorted((r["id"], r["reason"]) for r in finished) == \
        sorted([(a.id, "max_tokens"), (b.id, "cancelled")])
    unfinished = [r for r in resp["records"]
                  if r["kind"] == "unfinished"]
    assert [r["req"]["id"] for r in unfinished] == [c.id]
    w.journal.close()


# ---------------------------------------------------------------------------
# worker dispatch units (fake engine, no subprocess)
# ---------------------------------------------------------------------------

class _FakeAlloc:
    pages_in_use = 0
    prefix_hit_tokens = 0
    prompt_tokens = 0


class _FakePool:
    alloc = _FakeAlloc()

    def cached_prefix_tokens(self, prompt):
        return 0


class _FakeMetrics:
    counters = {"requests_admitted": 1}


class _FakeEngine:
    """The minimal host API WorkerServer drives."""

    class cfg:
        vocab_size = CFG.vocab_size

    def __init__(self, capacity=8):
        self.pool = _FakePool()
        self.metrics = _FakeMetrics()
        self.n_steps = 0
        self._active = np.zeros((2,), bool)
        self._inflight = {}
        self._finish_next = []
        self.cancelled = []
        self.journal = None
        self.capacity = capacity

    @property
    def idle(self):
        return not self._inflight

    class scheduler:
        depth = 0

    def submit(self, req):
        if req.id in self._inflight:
            return RequestResult(id=req.id, tokens=[],
                                 finish_reason=REJECT_BAD_REQUEST)
        if len(self._inflight) >= self.capacity:
            return RequestResult(id=req.id, tokens=[],
                                 finish_reason="rejected_queue_full")
        self._inflight[req.id] = req
        return None

    def step(self):
        self.n_steps += 1
        out = []
        for rid in self._finish_next:
            self._inflight.pop(rid, None)
            out.append(RequestResult(id=rid, tokens=[1, 2],
                                     finish_reason="max_tokens"))
        self._finish_next = []
        return out

    def cancel(self, rid, migrated=False):
        self.cancelled.append((rid, migrated))
        return self._inflight.pop(rid, None) is not None

    def in_flight_ids(self):
        return list(self._inflight)

    def partial_tokens(self, rid):
        return [7] if rid in self._inflight else None


def test_worker_step_redelivers_finishes_until_acked():
    """A finish stays in every step response until the router acks it —
    a response lost to a timeout or a router crash must not lose it."""
    eng = _FakeEngine()
    w = WorkerServer(eng, journal=None)
    q = _reqs(1, seed=3)[0]
    assert w.dispatch({"op": "submit",
                       "req": request_to_wire(q, 0.0)})["accepted"]
    eng._finish_next = [q.id]
    r1 = w.dispatch({"op": "step", "acks": []})
    assert [d["id"] for d in r1["finished"]] == [q.id]
    r2 = w.dispatch({"op": "step", "acks": []})   # redelivered
    assert [d["id"] for d in r2["finished"]] == [q.id]
    r3 = w.dispatch({"op": "step", "acks": [q.id]})   # acked -> pruned
    assert r3["finished"] == []
    assert r3["idle"] is True


def test_worker_drain_refuses_submits_and_journals_pending(tmp_path):
    """The rolling-restart drain: submits refuse REJECT_REPLICA_DOWN
    (non-deterministic verdict — the router tries elsewhere), in-flight
    work cancels migrated, and replay-pending requests journal a finish
    so the NEXT incarnation never resurrects them."""
    path = str(tmp_path / "w.jsonl")
    a, b = _reqs(2, seed=4)
    pre = RequestJournal(path)
    pre.record_submit(a)
    pre.record_submit(b)
    pre.close()
    # capacity 1: replay admits a, leaves b replay-pending
    eng = _FakeEngine(capacity=1)
    journal = RequestJournal(path, lock=True)
    eng.journal = journal
    w = WorkerServer(eng, journal=journal)
    n = w.replay_journal(path)
    assert n == 2 and sorted(w._in_flight_ids()) == sorted([a.id, b.id])
    assert [r.id for r in w._replay_pending] == [b.id]
    resp = w.dispatch({"op": "drain"})
    assert sorted(resp["cancelled"]) == sorted([a.id, b.id])
    assert (a.id, True) in eng.cancelled       # migrated cancel
    rej = w.dispatch({"op": "submit",
                      "req": request_to_wire(_reqs(1, seed=5)[0], 0.0)})
    assert not rej["accepted"]
    assert rej["rejection"]["finish_reason"] == REJECT_REPLICA_DOWN
    journal.close()
    # the drain journaled b's (replay-pending) finish — a future replay
    # resurrects only a, whose finish the REAL engine would have
    # journaled inside cancel(migrated=True) (pinned in test_fleet)
    assert [r.id for r in RequestJournal.unfinished(path)] == [a.id]


def test_worker_cancel_of_replay_pending_journals_finish(tmp_path):
    path = str(tmp_path / "c.jsonl")
    q = _reqs(1, seed=6)[0]
    pre = RequestJournal(path)
    pre.record_submit(q)
    pre.close()
    eng = _FakeEngine(capacity=0)          # everything replay-pends
    journal = RequestJournal(path, lock=True)
    w = WorkerServer(eng, journal=journal)
    w.replay_journal(path)
    assert [r.id for r in w._replay_pending] == [q.id]
    resp = w.dispatch({"op": "cancel", "id": q.id, "migrated": True})
    assert resp["found"]
    journal.close()
    assert RequestJournal.unfinished(path) == []


# ---------------------------------------------------------------------------
# idempotent dispatch + generation fence (fake engine, no subprocess)
# ---------------------------------------------------------------------------

def test_worker_reply_cache_suppresses_duplicates():
    """The worker-side half of exactly-once under duplication: a
    mutating frame replayed with the same idem key answers from the
    reply cache (marked idem_hit, engine untouched); a FRESH key is a
    new logical attempt and re-executes; the cache is bounded FIFO."""
    eng = _FakeEngine()
    w = WorkerServer(eng, journal=None)
    assert "submit" in IDEMPOTENT_VERBS
    q = _reqs(1, seed=62)[0]
    doc = {"op": "submit", "req": request_to_wire(q, 0.0),
           "idem": "k1"}
    d1 = w.dispatch(dict(doc))
    assert d1["accepted"] and "idem_hit" not in d1
    d2 = w.dispatch(dict(doc))                   # duplicated frame
    assert d2["accepted"] and d2["idem_hit"] is True
    assert list(eng._inflight) == [q.id]         # executed exactly once
    # a fresh key re-executes: the ENGINE's in-flight dedupe answers
    d3 = w.dispatch({**doc, "idem": "k2"})
    assert not d3["accepted"]
    assert d3["rejection"]["finish_reason"] == REJECT_BAD_REQUEST
    # bounded cache: REPLY_CACHE_SIZE newer entries evict k1 — a
    # duplicate THAT stale is a bug, not a retry, and re-executes
    for i in range(REPLY_CACHE_SIZE):
        w.dispatch({**doc, "idem": f"evict.{i}"})
    assert "k1" not in w._replies
    assert len(w._replies) == REPLY_CACHE_SIZE


def test_worker_generation_fence():
    """A frame stamped with another incarnation's gen is talking to
    the wrong process: typed RpcProtocolError carrying the 'stale
    generation' marker (the router's cue to renegotiate the attach),
    never execution. Matching or absent gens pass; gen=-1 disables
    the fence (direct-embedding tests)."""
    w = WorkerServer(_FakeEngine(), journal=None)
    w.gen = 7
    with pytest.raises(RpcProtocolError, match="stale generation 6"):
        w.dispatch({"op": "step", "acks": [], "gen": 6})
    assert w.dispatch({"op": "step", "acks": [], "gen": 7})["idle"]
    assert w.dispatch({"op": "step", "acks": []})["idle"]   # unstamped
    w.gen = -1                                   # unfenced worker
    assert w.dispatch({"op": "step", "acks": [], "gen": 3})["idle"]


# ---------------------------------------------------------------------------
# netchaos transport faults (fake engine over a real socket)
# ---------------------------------------------------------------------------

class _ChaosObserver:
    """Stands in for RemoteReplica's observer hooks: collects the
    responses the chaos layer swallowed and the partition edges."""

    def __init__(self):
        self.responses = []
        self.partitions = []

    def net_chaos_response(self, resp):
        self.responses.append(resp)

    def net_chaos_partition(self, active):
        self.partitions.append(active)


def _serve_fake_worker(w):
    """Serve ``w.dispatch`` on a real socket from a daemon asyncio
    thread; returns (port, stop)."""
    import threading
    ready = {}
    started = threading.Event()

    async def main():
        stop = asyncio.Event()
        server = await asyncio.start_server(
            lambda r, wr: serve_connection(r, wr, w.dispatch),
            "127.0.0.1", 0)
        ready["port"] = server.sockets[0].getsockname()[1]
        ready["stop"] = stop
        ready["loop"] = asyncio.get_running_loop()
        started.set()
        await stop.wait()
        server.close()
        await server.wait_closed()

    t = threading.Thread(target=lambda: asyncio.run(main()),
                         daemon=True)
    t.start()
    assert started.wait(10)

    def shutdown():
        ready["loop"].call_soon_threadsafe(ready["stop"].set)
        t.join(10)

    return ready["port"], shutdown


def test_netchaos_transport_fault_ladder():
    """Every netchaos kind end to end against a real worker socket:
    dup answers from the reply cache, reorder replays the previous
    idempotent frame (discarded response still observed), delay and
    trickle are harmless, drop raises the maybe-executed RpcTimeout,
    a two-way partition raises RpcDown without touching the wire, a
    one-way partition EXECUTES but loses the response, and the first
    clean call after is the heal edge."""
    eng = _FakeEngine(capacity=16)
    w = WorkerServer(eng, journal=None)
    port, shutdown = _serve_fake_worker(w)
    obs = _ChaosObserver()
    ft = FaultyTransport(RpcClient("127.0.0.1", port, timeout_s=5.0),
                         src="router", dst="worker0", observer=obs)
    reqs = _reqs(8, seed=61)
    sub = [{"req": request_to_wire(q, 0.0), "idem": f"lad.{i}"}
           for i, q in enumerate(reqs)]
    site = net_site("router", "worker0", "submit")
    try:
        # no plan installed: the fast path never counts an ordinal
        assert ft.call("step", acks=[])["idle"]
        assert ft._counts == {}
        plan = FaultPlan(
            Fault(site=site, kind=KIND_NET_DUP, at=0),
            Fault(site=site, kind=KIND_NET_REORDER, at=1),
            Fault(site=site, kind=KIND_NET_DELAY, at=2, arg=0.01),
            Fault(site=site, kind=KIND_NET_TRICKLE, at=3, arg=5,
                  arg2=0.001),
            Fault(site=site, kind=KIND_NET_DROP, at=4),
            Fault(site=site, kind=KIND_NET_PARTITION, at=5, arg2=0),
            Fault(site=site, kind=KIND_NET_PARTITION, at=6, arg2=1),
        )
        with installed(plan):
            # idx 0 dup: caller gets the SECOND response — the cache hit
            r0 = ft.call("submit", **sub[0])
            assert r0["accepted"] and r0["idem_hit"] is True
            assert ft.dups_injected == 1
            assert list(eng._inflight) == [reqs[0].id]
            # idx 1 reorder: lad.0 replayed first (stale dup, observed
            # + discarded), then lad.1 proceeds normally
            r1 = ft.call("submit", **sub[1])
            assert r1["accepted"] and "idem_hit" not in r1
            assert ft.dups_injected == 2
            assert obs.responses[-1]["idem_hit"] is True
            # idx 2 delay / idx 3 trickle: harmless, seams restored
            assert ft.call("submit", **sub[2])["accepted"]
            assert ft.call("submit", **sub[3])["accepted"]
            assert ft.client.send_chunking is None
            # idx 4 drop: nothing on the wire, maybe-executed timeout
            with pytest.raises(RpcTimeout, match="dropped"):
                ft.call("submit", **sub[4])
            assert reqs[4].id not in eng._inflight
            # idx 5 two-way partition: frame never leaves this host
            with pytest.raises(RpcDown, match="partitioned"):
                ft.call("submit", **sub[5])
            assert reqs[5].id not in eng._inflight
            assert obs.partitions == [True]
            # idx 6 one-way partition: EXECUTED, response lost but
            # observed (dup-suppression accounting stays exact)
            with pytest.raises(RpcTimeout, match="one-way"):
                ft.call("submit", **sub[6])
            assert reqs[6].id in eng._inflight
            assert obs.responses[-1]["accepted"]
            # idx 7 clean: the heal edge
            assert ft.call("submit", **sub[7])["accepted"]
            assert obs.partitions == [True, False]
            assert not ft.partitioned
        assert ft.dups_injected == 2
    finally:
        ft.close()
        shutdown()


def test_netchaos_corrupt_frame_typed_reject_and_idem_retry():
    """net_corrupt flips one seeded body byte: the worker's checksum
    rejects the frame with a TYPED protocol error (never a mis-decoded
    request — the engine must not see it), the frame_filter seam is
    restored, and the retry with the SAME idem key executes fresh
    (the poisoned frame never reached dispatch, so there is nothing
    in the reply cache)."""
    eng = _FakeEngine()
    w = WorkerServer(eng, journal=None)
    port, shutdown = _serve_fake_worker(w)
    ft = FaultyTransport(RpcClient("127.0.0.1", port, timeout_s=5.0),
                         src="router", dst="worker0")
    q = _reqs(1, seed=63)[0]
    kw = {"req": request_to_wire(q, 0.0), "idem": "c0"}
    try:
        # the catch-all site spelling must route to this link too
        with installed(FaultPlan(Fault(site=NET_CALL,
                                       kind=KIND_NET_CORRUPT, at=0,
                                       times=1))):
            with pytest.raises(RpcProtocolError, match="checksum"):
                ft.call("submit", **kw)
            assert ft.client.frame_filter is None
            assert eng._inflight == {}           # never dispatched
            ft.close()                           # poisoned stream
            retry = ft.call("submit", **kw)      # same idem key
        assert retry["accepted"] and "idem_hit" not in retry
        assert list(eng._inflight) == [q.id]
        assert ft.dups_injected == 0             # corruption != dup
    finally:
        ft.close()
        shutdown()


# ---------------------------------------------------------------------------
# re-registration backoff (full jitter + episode idem keys)
# ---------------------------------------------------------------------------

class _RecordingRng:
    """Deterministic stand-in for the jitter rng: records each
    uniform(a, b) bound and returns 0 (no actual sleeping)."""

    def __init__(self):
        self.bounds = []

    def uniform(self, a, b):
        self.bounds.append((a, b))
        return 0.0


class _StubWorkerLoop:
    """The two attributes _reregister_loop reads off the worker."""

    def __init__(self):
        self.stop_event = asyncio.Event()
        self.last_contact = time.monotonic() - 100.0


def test_reregister_backoff_full_jitter_bounds():
    """The backoff draws uniform(0, min(cap, base * 2^attempt)) — FULL
    jitter, so a fleet-wide partition heal cannot thundering-herd the
    router. Against a dead address the bounds double then clamp at the
    cap; the low bound is always 0."""
    from replicatinggpt_tpu.serve.worker import _reregister_loop

    async def main():
        w = _StubWorkerLoop()
        rng = _RecordingRng()
        task = asyncio.ensure_future(_reregister_loop(
            w, "127.0.0.1:1",              # nothing listens on port 1
            {"worker_idx": 0, "gen": 0},
            idle_s=0.05, backoff_s=0.5, backoff_cap_s=2.0, rng=rng))
        deadline = time.monotonic() + 30.0
        while len(rng.bounds) < 5:
            assert time.monotonic() < deadline, rng.bounds
            await asyncio.sleep(0.001)
        w.stop_event.set()
        await asyncio.wait_for(task, 10.0)
        return rng.bounds

    bounds = asyncio.run(main())
    # attempt increments BEFORE the draw: first failure already doubles
    assert bounds[:4] == [(0.0, 1.0), (0.0, 2.0), (0.0, 2.0),
                          (0.0, 2.0)]


def test_reregister_episode_idem_refresh(monkeypatch):
    """One silence episode is one logical registration: retries within
    an episode reuse its idem key (a listener that executed the attach
    but lost the response answers from its reply cache), and a NEW
    episode mints a fresh key (a new logical attach must execute)."""
    import replicatinggpt_tpu.serve.worker as worker_mod
    seen = []
    fail = {"next": True}

    async def fake_attempt(addr, doc):
        seen.append(doc["idem"])
        if fail["next"]:
            fail["next"] = False
            raise ConnectionError("refused")
        return {"ok": True}

    monkeypatch.setattr(worker_mod, "_register_attempt", fake_attempt)

    async def main():
        w = _StubWorkerLoop()
        task = asyncio.ensure_future(worker_mod._reregister_loop(
            w, "127.0.0.1:1", {"worker_idx": 1, "gen": 4},
            idle_s=0.05, backoff_s=0.001, backoff_cap_s=0.002,
            rng=_RecordingRng()))
        deadline = time.monotonic() + 30.0
        while len(seen) < 2:               # episode 1: fail, then ok
            assert time.monotonic() < deadline, seen
            await asyncio.sleep(0.001)
        fail["next"] = True                # re-arm for episode 2
        w.last_contact = time.monotonic() - 100.0   # silence again
        while len(seen) < 4:               # episode 2: fail, then ok
            assert time.monotonic() < deadline, seen
            await asyncio.sleep(0.001)
        w.stop_event.set()
        await asyncio.wait_for(task, 10.0)

    asyncio.run(main())
    assert seen[:4] == ["reg.1.4.re1", "reg.1.4.re1",
                        "reg.1.4.re2", "reg.1.4.re2"]


# ---------------------------------------------------------------------------
# supervisor policy units (fake worker processes)
# ---------------------------------------------------------------------------

class _StubReplica:
    alive = True
    wedged = False
    draining = False
    restarts = 0


class _StubRouter:
    """Records the supervisor's calls; replicas are always 'alive' so
    the zombie-escalation path stays quiet."""

    def __init__(self, n):
        self.replicas = [_StubReplica() for _ in range(n)]
        self.supervisor = None
        self.abandoned = []
        self.downs = []
        from replicatinggpt_tpu.utils.telemetry import NULL
        self.tel = NULL

    def mark_down(self, idx, reason=""):
        self.downs.append(idx)

    def abandon_replica(self, idx):
        self.abandoned.append(idx)

    def _event(self, msg):
        pass


def test_supervisor_restart_budget_ends_in_quarantine(tmp_path):
    """A worker that dies on every spawn burns its crash budget through
    exponential backoff and lands QUARANTINED, with its journal
    requeued onto survivors (abandon_replica)."""
    spec = WorkerSpec(
        idx=0, cmd=[sys.executable, "-c", "import sys; sys.exit(3)"],
        journal_path=str(tmp_path / "q.jsonl"))
    sup = ProcSupervisor([spec], SupervisorConfig(
        restart_budget=2, backoff_s=0.01, backoff_mult=2.0,
        probe_every=0))
    router = _StubRouter(1)
    sup.attach_router(router)
    assert router.supervisor is sup
    sup.start_all(wait=False)
    deadline = time.monotonic() + 30
    while sup.handles[0].state != QUARANTINED:
        assert time.monotonic() < deadline, sup.events
        sup.tick()
        time.sleep(0.005)
    h = sup.handles[0]
    assert h.crash_restarts == 3           # budget 2 -> third crash quarantines
    assert router.abandoned == [0]
    assert router.downs                    # each death marked down
    assert any("quarantined" in e for e in sup.events)
    # reviving is False once nothing is coming back
    assert not sup.reviving


def test_supervisor_reviving_reflects_backoff_and_intentional_stop(
        tmp_path):
    spec = WorkerSpec(
        idx=0, cmd=[sys.executable, "-c", "import sys; sys.exit(1)"],
        journal_path=str(tmp_path / "r.jsonl"))
    sup = ProcSupervisor([spec], SupervisorConfig(
        restart_budget=5, backoff_s=30.0, probe_every=0))
    sup.attach_router(_StubRouter(1))
    sup.start_all(wait=False)
    assert sup.reviving                    # SPAWNING counts
    deadline = time.monotonic() + 30
    while sup.handles[0].state != BACKOFF:
        assert time.monotonic() < deadline
        sup.tick()
        time.sleep(0.005)
    assert sup.reviving                    # BACKOFF counts
    sup.handles[0].state = RUNNING
    assert not sup.reviving
    sup.handles[0].intentional_stop = True   # rolling-restart window
    assert sup.reviving
    sup.stop_all()


# ---------------------------------------------------------------------------
# router guards for maybe-executed submits (no subprocess)
# ---------------------------------------------------------------------------

def _tiny_router(n=2):
    import jax

    from replicatinggpt_tpu.models.gpt import init_params
    from replicatinggpt_tpu.serve import EngineConfig, Router
    params = init_params(jax.random.PRNGKey(0), CFG)
    return Router(params, CFG, RouterConfig(n_replicas=n),
                  EngineConfig(pool_size=2, max_queue=8))


def test_submit_timeout_falls_through_and_ghost_finish_swallowed():
    """A submit RPC that TIMES OUT may still execute on the hung
    worker. The router routes the id to the next candidate
    (REJECT_REPLICA_TIMEOUT is retryable), and when the maybe-executed
    copy's finish later arrives from the wrong replica it is swallowed
    by the replica-aware stale guard — the live copy's ledger entry
    and stream are untouched."""
    from replicatinggpt_tpu.serve.router import REJECT_REPLICA_TIMEOUT
    r = _tiny_router(2)
    try:
        q = _reqs(1, seed=51, max_new=4)[0]
        r.replicas[0].submit = lambda req: RequestResult(
            id=req.id, tokens=[],
            finish_reason=REJECT_REPLICA_TIMEOUT)
        # route: replica 0 "times out", replica 1 accepts
        assert r.submit(q) is None
        assert r._inflight[q.id].replica == 1
        assert r.metrics.counters["fleet_route_fallbacks"] == 1
        # the maybe-executed copy finishes on replica 0 later:
        # swallowed, the live entry on replica 1 untouched
        ghost = RequestResult(id=q.id, tokens=[9, 9],
                              finish_reason="max_tokens")
        assert r._on_finish(ghost, 0, r.clock()) is None
        assert r.metrics.counters["fleet_stale_finishes"] == 1
        assert q.id in r._inflight and q.id not in r.results
        r.drain()
        assert r.results[q.id].finish_reason == "max_tokens"
        # after the live copy delivered, a straggler duplicate from
        # the hung replica is a ghost — swallowed, result intact
        assert r._on_finish(ghost, 0, r.clock()) is None
        assert r.results[q.id].finish_reason == "max_tokens"
    finally:
        r.close()


def test_finish_from_wrong_replica_is_swallowed():
    """The ledger is replica-keyed: a finish arriving from a replica
    the id is NOT routed to (timed-out submit that executed anyway, a
    pre-migration straggler) must not pop the live copy's entry or
    surface a result."""
    r = _tiny_router(2)
    try:
        q = _reqs(1, seed=52, max_new=4)[0]
        assert r.submit(q) is None
        owner = r._inflight[q.id].replica
        stale = RequestResult(id=q.id, tokens=[1],
                              finish_reason="cancelled")
        assert r._on_finish(stale, 1 - owner, r.clock()) is None
        assert r.metrics.counters["fleet_stale_finishes"] == 1
        assert r._inflight[q.id].replica == owner
        r.drain()
        assert r.results[q.id].finish_reason == "max_tokens"
    finally:
        r.close()


def test_config_override_args_round_trips_model_config():
    """`serve --multiproc` must spawn workers serving the SAME model
    the operator asked for: every add_config_flags model override set
    on the parent's args must survive the trip through
    config_override_args -> a fresh parser -> config_from_args."""
    import argparse

    from replicatinggpt_tpu.config import (add_config_flags,
                                           config_from_args,
                                           config_override_args)

    def parse(argv):
        p = argparse.ArgumentParser()
        add_config_flags(p)
        return p.parse_args(argv)

    argv = ["--preset", "test-tiny", "--n-layer", "3", "--n-head", "4",
            "--n-embd", "64", "--block-size", "48", "--vocab-size",
            "80", "--dropout", "0.1", "--dtype", "bfloat16",
            "--attention", "einsum", "--decode-cache-layout", "packed",
            "--remat"]
    parent = parse(argv)
    forwarded = parse(["--preset", parent.preset]
                      + config_override_args(parent))
    assert config_from_args(forwarded).model == \
        config_from_args(parent).model
    # unset overrides forward nothing (workers keep preset defaults)
    assert config_override_args(parse(["--preset", "test-tiny"])) == []


# ---------------------------------------------------------------------------
# tier-1 subprocess smoke (one real worker process)
# ---------------------------------------------------------------------------

def test_worker_process_smoke_parity(tmp_path):
    """One real serve-worker subprocess behind the router: greedy
    parity vs offline generate, the cross-process journal flock (a
    second writer in THIS process gets JournalBusyError while the
    worker lives), the RPC registration handshake (no ready files
    anywhere — the workdir is the worker's PRIVATE dir), and a clean
    shutdown that frees the lock and leaves submit+finish records."""
    router, sup = _spawn(tmp_path, 1)
    try:
        h = sup.handles[0]
        # registration attached the router: pid/gen/host flowed over
        # the RPC handshake, not a filesystem artifact
        rep = router.replicas[0]
        assert rep.pid == h.pid and rep.gen == 0
        assert h.state == "running"
        assert sup.expect_shape_hash     # pinned by the registration
        # no ready files exist anywhere in the worker's private dir
        assert not [p for p in pathlib.Path(h.spec.workdir).iterdir()
                    if "ready" in p.name]
        # the worker holds the exclusive flock on its journal
        with pytest.raises(JournalBusyError):
            RequestJournal(h.spec.journal_path, lock=True)
        reqs = _reqs(3, seed=11, max_new=6)
        for q in reqs:
            assert router.submit(q) is None
        results, streams = _drain_streaming(router, sup,
                                            [q.id for q in reqs])
        assert len(results) == 3
        for q in reqs:
            want = _offline(q.prompt, 6)
            assert results[q.id].tokens == want
            assert streams[q.id] == want
        # health carries the worker's identity + engine counters
        health = router.replicas[0].health()
        assert health["pid"] == h.pid and health["warmed"]
    finally:
        sup.stop_all()
        router.close()
    # lock freed with the process; journal holds the full history
    j = RequestJournal(sup.handles[0].spec.journal_path, lock=True)
    j.close()
    recs = pathlib.Path(
        sup.handles[0].spec.journal_path).read_text()
    assert '"ev": "submit"' in recs and '"ev": "finish"' in recs


def test_step_rpc_round_trips_amortized_by_decode_window(tmp_path):
    """The worker's step RPC returns the FULL token window per call
    (and journals/redelivers finishes once per window, not per token):
    with --decode-window 16 forwarded to the worker, step-RPC round
    trips per generated token drop >= 4x vs the k=1 identity — a
    blocked worker with ONE active slot needs at least one step RPC
    per token by construction, so <= 0.25 RPCs/token IS the >= 4x
    drop. Greedy stream stays byte-identical to offline generate."""
    jdir = str(tmp_path / "journals")
    specs = make_worker_specs(
        1, jdir, ["--preset", "test-tiny"],
        ["--pool-size", "2", "--max-queue", "16",
         "--decode-window", "16"])
    router, sup = spawn_fleet(
        specs, RouterConfig(n_replicas=1, journal_dir=jdir,
                            step_timeout_s=30.0),
        SupervisorConfig(backoff_s=0.2, probe_every=10_000,
                         probe_timeout_s=5.0))
    try:
        rep = router.replicas[0]
        n_steps = {"step": 0}
        orig = rep._call

        def counted(op, **kw):
            if op == "step":
                n_steps["step"] += 1
            return orig(op, **kw)

        rep._call = counted
        req = Request(id="w0",
                      prompt=np.asarray([32, 39, 63], np.int32),
                      max_new_tokens=28,
                      sampling=SamplingParams(greedy=True))
        assert router.submit(req) is None
        results, streams = _drain_streaming(router, sup, ["w0"])
        assert results["w0"].tokens == _offline(req.prompt, 28)
        assert streams["w0"] == results["w0"].tokens
        per_token = n_steps["step"] / 28
        assert per_token <= 0.25, (
            f"{n_steps['step']} step RPCs for 28 tokens "
            f"({per_token:.3f}/token) — window not amortizing the RPC "
            f"cadence")
    finally:
        sup.stop_all()
        router.close()


# ---------------------------------------------------------------------------
# pinned acceptance soaks (slow tier: -m "multiproc and slow")
# ---------------------------------------------------------------------------

@pytest.mark.chaos
@pytest.mark.slow
def test_sigkill_mid_decode_exactly_once_streams(tmp_path):
    """THE pinned property: a REAL ``kill -9`` of a worker process
    mid-decode costs nothing — the supervisor restarts it, the worker
    replays its journal, the router reconciles via the delivery
    ledger, and every greedy stream is token-identical to an
    uninterrupted run with zero drops and zero duplicates. A SIGSTOP
    (proc_hang) lands on the other worker mid-recovery for good
    measure, both through the standard FaultPlan seam. The
    router-emitted worker-track trace must validate."""
    from replicatinggpt_tpu.utils.telemetry import Telemetry
    tel = Telemetry()
    router, sup = _spawn(tmp_path, 2, telemetry=tel)
    try:
        reqs = _reqs(4, seed=21, max_new=24)
        plan = FaultPlan(
            Fault(site=FLEET_STEP, kind=KIND_PROC_KILL, at=4, arg=0),
            Fault(site=FLEET_STEP, kind=KIND_PROC_HANG, at=8,
                  arg=3, arg2=1))
        with installed(plan):
            for q in reqs:
                assert router.submit(q) is None
            results, streams = _drain_streaming(router, sup,
                                                [q.id for q in reqs])
        assert ("fleet/step", KIND_PROC_KILL, 4) in plan.fired
        assert ("fleet/step", KIND_PROC_HANG, 8) in plan.fired
        assert len(results) == 4
        for q in reqs:
            want = _offline(q.prompt, 24)
            assert results[q.id].finish_reason == "max_tokens"
            assert streams[q.id] == want, (
                f"{q.id}: stream diverged across SIGKILL "
                f"(drop/duplicate): {streams[q.id]} != {want}")
        assert sup.handles[0].crash_restarts == 1
        assert router.metrics.counters["fleet_replica_downs"] >= 1
        assert any("CHAOS proc_kill" in e for e in sup.events)
        assert any("CHAOS proc_hang" in e for e in sup.events)
    finally:
        sup.stop_all()
        router.close()
    trace = tmp_path / "multiproc_trace.json"
    tel.export_chrome_trace(str(trace))
    tel.close()
    errors = _trace_check().check_trace(str(trace), min_requests=4)
    assert errors == []


@pytest.mark.chaos
@pytest.mark.slow
def test_rolling_restart_zero_drops_and_readyz_flip(tmp_path):
    """THE other pinned property: a rolling restart of EVERY worker
    (here: a single-worker fleet — the hardest case, with a
    zero-routable window) completes with zero dropped requests,
    token-identical streams, and ``readyz`` flipping not-ready ->
    ready; the requeue ladder holds its retry budget through the
    window instead of exhausting against a fleet mid-recovery."""
    router, sup = _spawn(tmp_path, 1)
    try:
        assert router.readyz()["ok"]
        reqs = _reqs(4, seed=31, max_new=20)
        for q in reqs:
            assert router.submit(q) is None
        results, streams = {}, {q.id: [] for q in reqs}
        for _ in range(3):                 # tokens flowing first
            for res in router.step():
                results[res.id] = res
            for rid in streams:
                streams[rid].extend(router.take_new_tokens(rid))
            sup.tick()
        sup.start_rolling_restart()
        saw_not_ready = 0
        deadline = time.monotonic() + 240
        while not router.idle or sup.rolling_active:
            assert time.monotonic() < deadline, (
                sup.events[-6:], router.events[-6:])
            for res in router.step():
                results[res.id] = res
            for rid in streams:
                streams[rid].extend(router.take_new_tokens(rid))
            sup.tick()
            if not router.readyz()["ok"]:
                saw_not_ready += 1
        assert saw_not_ready > 0, \
            "readyz never reported 503 during the zero-worker window"
        assert router.readyz()["ok"], "readyz must flip back to 200"
        h = sup.handles[0]
        assert h.gen == 1 and h.crash_restarts == 0   # free restart
        assert len(results) == 4, "rolling restart dropped requests"
        for q in reqs:
            want = _offline(q.prompt, 20)
            assert results[q.id].finish_reason == "max_tokens"
            assert streams[q.id] == want
        assert any("rolling restart complete" in e for e in sup.events)
    finally:
        sup.stop_all()
        router.close()


@pytest.mark.chaos
@pytest.mark.slow
def test_duplicate_id_during_restart_never_double_decoded(tmp_path):
    """Cross-process mirror of the PR-8 in-process pin: an id whose
    worker was SIGKILLed is STILL in flight fleet-wide while the
    restart runs — a duplicate submit (client retry) is rejected, and
    after the restart the original delivers exactly once."""
    router, sup = _spawn(tmp_path, 2)
    try:
        q = _reqs(1, seed=41, max_new=20)[0]
        assert router.submit(q) is None
        streams = {q.id: []}
        results = {}
        # let tokens flow, then kill the owning worker
        deadline = time.monotonic() + 60
        while not streams[q.id]:
            assert time.monotonic() < deadline
            for res in router.step():
                results[res.id] = res
            streams[q.id].extend(router.take_new_tokens(q.id))
            sup.tick()
        owner = router._inflight[q.id].replica
        os.kill(sup.handles[owner].pid, signal.SIGKILL)
        # the duplicate arrives while the worker is dead/restarting
        dup = router.submit(Request(
            id=q.id, prompt=q.prompt, max_new_tokens=20,
            sampling=SamplingParams(greedy=True), rng_seed=q.rng_seed))
        assert dup is not None
        assert dup.finish_reason == REJECT_BAD_REQUEST
        assert router.metrics.counters["fleet_dedup_rejects"] == 1
        more, streams2 = _drain_streaming(router, sup, [q.id])
        results.update(more)
        streams[q.id].extend(streams2[q.id])
        want = _offline(q.prompt, 20)
        assert results[q.id].tokens == want
        assert streams[q.id] == want       # exactly once, no double decode
    finally:
        sup.stop_all()
        router.close()


@pytest.mark.slow
def test_bench_fleet_multiproc_emits_tagged_artifact(tmp_path, capsys):
    """`bench.py --mode fleet --multiproc --fleet-kill-at` end to end:
    the artifact is tagged multiproc + proc_kill and carries the
    per-worker pid/gen/restart counts, requeue latency, and fleet
    TTFT the tooling satellite names — and the REAL SIGKILL mid-run
    still completes every turn."""
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)
    bench._EMITTED = False     # emit() is first-caller-wins per process;
    #                            another bench test may have consumed it
    args = bench.main.__globals__["argparse"].Namespace(
        preset="test-tiny", serve_pool=4, serve_rate=200.0,
        serve_max_new_tokens=6, serve_page_size=4, serve_n_pages=0,
        fleet_replicas=2, fleet_sessions=5, fleet_turns=2,
        fleet_prefix_groups=2, fleet_prefix_len=8, fleet_kill_at=8,
        fleet_journal_dir=str(tmp_path), trace_out=None,
        metrics_timeline=None, metrics_out=None, multiproc=True,
        fleet_load_step=False, fleet_host_loss=False, net_chaos=False)
    bench.bench_fleet(args)
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("{")]
    assert lines, "bench_fleet emitted no artifact JSON"
    doc = json.loads(lines[-1])
    assert doc["metric"] == "fleet_replay_aggregate_tokens_per_sec"
    assert doc["value"] > 0
    assert doc["multiproc"] is True
    assert doc["chaos"] == "proc_kill"
    assert doc["n_completed"] == doc["n_requests"] == 10
    assert {"fleet_ttft_p50_ms", "fleet_ttft_p99_ms",
            "requeue_latency_p50_ms",
            "requeue_latency_p99_ms"} <= set(doc)
    workers = {w["worker"]: w for w in doc["workers"]}
    assert workers[0]["crash_restarts"] == 1     # the real SIGKILL
    assert workers[0]["gen"] == 1
    assert workers[1]["crash_restarts"] == 0
    assert all(isinstance(w["pid"], int) for w in doc["workers"])


@pytest.mark.chaos
@pytest.mark.slow
def test_bench_fleet_net_chaos_emits_tagged_artifact(tmp_path, capsys):
    """`bench.py --mode fleet --multiproc --net-chaos` end to end: the
    wire-fault ladder (dup/reorder/delay/drop/one-way-partition) runs
    against REAL worker processes mid-replay, every turn still
    completes, and the artifact is tagged net_chaos with the
    protocol-hardening counters in its router block."""
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)
    bench._EMITTED = False
    args = bench.main.__globals__["argparse"].Namespace(
        preset="test-tiny", serve_pool=4, serve_rate=200.0,
        serve_max_new_tokens=6, serve_page_size=4, serve_n_pages=0,
        fleet_replicas=2, fleet_sessions=5, fleet_turns=2,
        fleet_prefix_groups=2, fleet_prefix_len=8, fleet_kill_at=-1,
        fleet_journal_dir=str(tmp_path), trace_out=None,
        metrics_timeline=None, metrics_out=None, multiproc=True,
        fleet_load_step=False, fleet_host_loss=False, net_chaos=True)
    bench.bench_fleet(args)
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("{")]
    assert lines, "bench_fleet emitted no artifact JSON"
    doc = json.loads(lines[-1])
    assert doc["chaos"] == "net_chaos"
    assert doc["n_completed"] == doc["n_requests"] == 10
    # the hardened protocol absorbed the ladder: every injected
    # duplicate that reached a worker answered from its reply cache
    assert doc["router"].get("rpc_dup_suppressed", 0) >= 1
    assert doc["value"] > 0


@pytest.mark.chaos
@pytest.mark.slow
def test_sse_stream_token_identical_across_sigkill(tmp_path):
    """The acceptance pin at the FRONT DOOR: a greedy SSE stream over
    real HTTP is token-identical with zero drops/duplicates across a
    real SIGKILL of the worker process mid-decode — the client sees
    one uninterrupted stream and one done event."""
    from replicatinggpt_tpu.serve.http import ServeApp
    router, sup = _spawn(tmp_path, 1)
    app = ServeApp(router, supervisor=sup, idle_timeout_s=0)

    async def main():
        host, port = await app.start()
        try:
            r, w = await asyncio.open_connection(host, port)
            payload = json.dumps({"id": "sse1", "prompt": [1, 2, 3],
                                  "max_new_tokens": 24,
                                  "greedy": True}).encode()
            w.write(b"POST /v1/submit HTTP/1.1\r\nHost: t\r\n"
                    + f"Content-Length: {len(payload)}\r\n\r\n".encode()
                    + payload)
            await w.drain()
            data = await r.read()
            assert b" 200 " in data.split(b"\r\n", 1)[0]
            w.close()
            await w.wait_closed()

            r, w = await asyncio.open_connection(host, port)
            w.write(b"GET /v1/stream/sse1 HTTP/1.1\r\nHost: t\r\n\r\n")
            await w.drain()
            # kill the worker once tokens are flowing
            buf = b""
            while buf.count(b"\ndata: ") < 3:
                chunk = await asyncio.wait_for(r.read(4096), timeout=60)
                assert chunk, f"stream closed early: {buf!r}"
                buf += chunk
            os.kill(sup.handles[0].pid, signal.SIGKILL)
            while b"event: done" not in buf:
                chunk = await asyncio.wait_for(r.read(4096),
                                               timeout=240)
                assert chunk, f"stream closed early: {buf!r}"
                buf += chunk
            w.close()
            await w.wait_closed()
            return buf
        finally:
            await app.stop()

    buf = asyncio.run(main())
    events = []
    for block in buf.partition(b"\r\n\r\n")[2].decode().split("\n\n"):
        ev, dat = "message", None
        for line in block.splitlines():
            if line.startswith("event: "):
                ev = line[len("event: "):]
            elif line.startswith("data: "):
                dat = json.loads(line[len("data: "):])
        if dat is not None:
            events.append((ev, dat))
    toks = [d["token"] for ev, d in events if ev == "message"]
    done = [d for ev, d in events if ev == "done"]
    want = _offline([1, 2, 3], 24)
    assert toks == want, (
        f"SSE stream diverged across SIGKILL: {toks} != {want}")
    assert len(done) == 1
    assert done[0]["finish_reason"] == "max_tokens"
    assert done[0]["n_tokens"] == 24
    assert sup.handles[0].crash_restarts == 1
    sup.stop_all()
    router.close()
