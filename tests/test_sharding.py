"""Distributed tests on a virtual 8-device CPU mesh (conftest forces
--xla_force_host_platform_device_count=8).

Covers the SURVEY.md §2.1 strategy table: DP, FSDP (ZeRO-3 param+opt
sharding), TP (Megatron column/row), and their composition — all via GSPMD
shardings, no hand-written collectives.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from replicatinggpt_tpu.config import MeshConfig, ModelConfig, get_config
from replicatinggpt_tpu.models.gpt import forward, init_params
from replicatinggpt_tpu.parallel.mesh import (make_batch_sharding, make_mesh,
                                              state_pspecs,
                                              shard_train_state)
from replicatinggpt_tpu.train.state import create_train_state
from replicatinggpt_tpu.train.steps import make_train_step

TINY = ModelConfig(vocab_size=64, block_size=32, n_layer=2, n_head=2,
                   n_embd=32, dropout=0.0, attn_dropout=0.0, dtype="float32")


def _state_fn(mcfg, tcfg):
    return lambda: create_train_state(jax.random.PRNGKey(0), mcfg, tcfg)


def _find_adam(state):
    """Locate ScaleByAdamState anywhere in optax's nested chain tuples."""
    if type(state).__name__ == "ScaleByAdamState":
        return state
    if isinstance(state, (tuple, list)):
        for s in state:
            r = _find_adam(s)
            if r is not None:
                return r
    return None


@pytest.fixture(scope="module")
def tcfg():
    return get_config("test-tiny").train


def _batch(mcfg, B=8, seed=0):
    x = jax.random.randint(jax.random.PRNGKey(seed), (B, mcfg.block_size), 0,
                           mcfg.vocab_size)
    return x, x


def test_requires_eight_devices():
    assert len(jax.devices()) == 8, "conftest must fake 8 CPU devices"


def test_mesh_construction():
    mesh = make_mesh(MeshConfig(data=2, seq=2, model=2))
    assert mesh.shape == {"data": 2, "seq": 2, "model": 2, "pipe": 1}
    assert make_batch_sharding(mesh).spec == P("data", "seq")


def test_tp_specs_follow_megatron_pattern(tcfg):
    specs = state_pspecs(jax.eval_shape(_state_fn(TINY, tcfg)),
                         MeshConfig(data=1, seq=1, model=2))
    p = specs.params
    assert p["blocks"]["qkv_kernel"] == P(None, None, "model")
    assert p["blocks"]["attn_out_kernel"] == P(None, "model", None)
    assert p["blocks"]["mlp_up_kernel"] == P(None, None, "model")
    assert p["blocks"]["mlp_down_kernel"] == P(None, "model", None)
    assert p["blocks"]["ln1_scale"] == P(None, None)
    assert p["wte"] == P("model", None)  # 64 % 2 == 0 → vocab-parallel
    # Adam moments mirror param specs through the tree path
    adam = _find_adam(specs.opt_state)
    assert adam.mu["blocks"]["qkv_kernel"] == P(None, None, "model")


def test_tp_indivisible_dims_stay_replicated(tcfg):
    odd = dataclasses.replace(TINY, vocab_size=65)  # 65 % 2 != 0
    specs = state_pspecs(jax.eval_shape(_state_fn(odd, tcfg)),
                         MeshConfig(model=2))
    assert specs.params["wte"] == P(None, None)


def test_fsdp_shards_params_and_moments(tcfg):
    specs = state_pspecs(jax.eval_shape(_state_fn(TINY, tcfg)),
                         MeshConfig(data=8, fsdp=True))
    p = specs.params
    # largest dim of (L=2, C=32, 3C=96) divisible by 8 → last dim
    assert "data" in tuple(p["blocks"]["qkv_kernel"])
    assert "data" in tuple(p["wte"])
    adam = _find_adam(specs.opt_state)
    assert "data" in tuple(adam.mu["blocks"]["qkv_kernel"])


def test_dp_training_matches_single_device(tcfg):
    """8-way DP must be numerically equivalent to single-device training
    (same global batch, same init)."""
    tcfg = dataclasses.replace(tcfg, lr=1e-3)
    batch = _batch(TINY, B=8)
    # single device
    state1 = _state_fn(TINY, tcfg)()
    step1 = make_train_step(TINY, tcfg, donate=False)
    losses1 = []
    for _ in range(3):
        state1, m = step1(state1, batch)
        losses1.append(float(m["loss"]))
    # 8-way DP
    mesh = make_mesh(MeshConfig(data=8))
    state8 = shard_train_state(_state_fn(TINY, tcfg), mesh,
                               MeshConfig(data=8))
    bs = make_batch_sharding(mesh)
    batch8 = tuple(jax.device_put(np.asarray(b), bs) for b in batch)
    step8 = make_train_step(TINY, tcfg, donate=False)
    losses8 = []
    for _ in range(3):
        state8, m = step8(state8, batch8)
        losses8.append(float(m["loss"]))
    np.testing.assert_allclose(losses1, losses8, rtol=2e-4)


def test_tp_forward_matches_unsharded(tcfg):
    mesh = make_mesh(MeshConfig(data=2, seq=1, model=2))
    mesh_cfg = MeshConfig(data=2, seq=1, model=2)
    params = init_params(jax.random.PRNGKey(0), TINY)
    specs = state_pspecs(params, mesh_cfg)
    sharded = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)
    x, _ = _batch(TINY, B=4)
    ref, _ = forward(params, x, TINY)
    xb = jax.device_put(np.asarray(x), NamedSharding(mesh, P("data", None)))
    got, _ = jax.jit(lambda p, i: forward(p, i, TINY))(sharded, xb)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got), atol=2e-4)


@pytest.mark.slow
def test_fsdp_training_matches_single_device(tcfg):
    tcfg = dataclasses.replace(tcfg, lr=1e-3)
    batch = _batch(TINY, B=8)
    state1 = _state_fn(TINY, tcfg)()
    step = make_train_step(TINY, tcfg, donate=False)
    state1, m1 = step(state1, batch)
    mesh = make_mesh(MeshConfig(data=8, fsdp=True))
    mesh_cfg = MeshConfig(data=8, fsdp=True)
    state8 = shard_train_state(_state_fn(TINY, tcfg), mesh, mesh_cfg)
    bs = make_batch_sharding(mesh)
    batch8 = tuple(jax.device_put(np.asarray(b), bs) for b in batch)
    state8, m8 = step(state8, batch8)
    np.testing.assert_allclose(float(m1["loss"]), float(m8["loss"]),
                               rtol=2e-4)
    # params stayed sharded after the step (no silent gather-to-replicated)
    qkv = state8.params["blocks"]["qkv_kernel"]
    assert "data" in tuple(qkv.sharding.spec)


@pytest.mark.slow
def test_runner_with_mesh(tcfg):
    """End-to-end runner on a 4-way DP mesh."""
    cfg = get_config("test-tiny")
    cfg = cfg.replace(
        train=dataclasses.replace(cfg.train, max_iters=5, eval_interval=0,
                                  eval_iters=2, log_interval=0,
                                  batch_size=8),
        mesh=MeshConfig(data=4),
        dataset="datasets/shakespeare.txt")
    from replicatinggpt_tpu.train.runner import train
    mesh = make_mesh(cfg.mesh)
    res = train(cfg, mesh=mesh)
    assert np.isfinite(res.final_eval["val"])


@pytest.mark.slow
def test_mesh_scan_dispatch_matches_single_steps(tcfg):
    """K-step scan over a P(None,'data','seq')-sharded superbatch must
    produce the same per-step losses as K single-step dispatches on the
    same mesh (the steps_per_dispatch>1 path for sharded runs)."""
    from replicatinggpt_tpu.parallel.mesh import make_superbatch_sharding
    from replicatinggpt_tpu.train.steps import make_train_scan
    tcfg = dataclasses.replace(tcfg, lr=1e-3)
    mesh_cfg = MeshConfig(data=4, seq=2)
    mesh = make_mesh(mesh_cfg)
    K = 4
    rng = np.random.default_rng(0)
    batches = [rng.integers(0, TINY.vocab_size, (8, TINY.block_size),
                            dtype=np.int32) for _ in range(K)]
    bs = make_batch_sharding(mesh)
    ss = make_superbatch_sharding(mesh)
    s1 = shard_train_state(_state_fn(TINY, tcfg), mesh, mesh_cfg)
    step = make_train_step(TINY, tcfg, donate=False)
    losses1 = []
    for b in batches:
        xb = jax.device_put(b, bs)
        s1, m = step(s1, (xb, xb))
        losses1.append(float(m["loss"]))
    s2 = shard_train_state(_state_fn(TINY, tcfg), mesh, mesh_cfg)
    scan = make_train_scan(TINY, tcfg, K, donate=False)
    stacked = jax.device_put(np.stack(batches), ss)
    assert stacked.sharding.spec == P(None, "data", "seq")
    s2, m = scan(s2, (stacked, stacked))
    np.testing.assert_allclose(losses1, np.asarray(m["loss"]), rtol=2e-4)
    # params stayed in their sharded layout through the scan dispatch
    assert (s2.params["blocks"]["qkv_kernel"].sharding.spec
            == s1.params["blocks"]["qkv_kernel"].sharding.spec)


@pytest.mark.slow
def test_runner_mesh_multi_step_dispatch_matches_single(tcfg):
    """End-to-end: the runner with steps_per_dispatch>1 on a DP mesh walks
    the same eval-loss trajectory as single-step dispatch (identical token
    stream, chunk schedule respecting the eval cadence)."""
    from replicatinggpt_tpu.train.runner import train
    cfg = get_config("test-tiny")
    base = cfg.replace(
        train=dataclasses.replace(cfg.train, max_iters=8, eval_interval=4,
                                  eval_iters=2, log_interval=0, batch_size=8,
                                  steps_per_dispatch=1),
        mesh=MeshConfig(data=4),
        dataset="datasets/shakespeare.txt")
    mesh = make_mesh(base.mesh)
    r1 = train(base, mesh=mesh)
    multi = base.replace(
        train=dataclasses.replace(base.train, steps_per_dispatch=3))
    r2 = train(multi, mesh=mesh)
    h1 = np.asarray([[tr, va] for _, tr, va in r1.history])
    h2 = np.asarray([[tr, va] for _, tr, va in r2.history])
    assert h1.shape == h2.shape
    np.testing.assert_allclose(h1, h2, rtol=2e-4)


@pytest.mark.slow
def test_runner_gates_flash_auto_on_mesh(tcfg):
    """'auto' must not resolve to the Pallas flash kernel inside a sharded
    jit program (no GSPMD partitioning rule) — the runner rewrites it to
    'einsum' on mesh runs without a seq-parallel attention wrapper."""
    import io

    from replicatinggpt_tpu.train.runner import train
    from replicatinggpt_tpu.utils.logging import StepLogger

    cfg = get_config("test-tiny")
    cfg = cfg.replace(
        train=dataclasses.replace(cfg.train, max_iters=2, eval_interval=0,
                                  eval_iters=1, log_interval=0,
                                  batch_size=8),
        mesh=MeshConfig(data=4),
        dataset="datasets/shakespeare.txt")
    assert cfg.model.attention_impl == "auto"
    stream = io.StringIO()
    mesh = make_mesh(cfg.mesh)
    train(cfg, mesh=mesh, logger=StepLogger(stream=stream))
    assert "'auto' -> 'einsum'" in stream.getvalue()


@pytest.mark.slow
def test_grad_accum_on_mesh_matches_unsharded(tcfg):
    """Gradient accumulation on a (data, seq) mesh — (A, b, T) microbatch
    stack sharded P(None,'data','seq') — must match the unsharded step
    bit-for-bit in loss and stay in the sharded layout."""
    from replicatinggpt_tpu.parallel.mesh import make_superbatch_sharding
    t = dataclasses.replace(tcfg, lr=1e-3, batch_size=8, grad_accum_steps=2)
    A = 2
    rng = np.random.default_rng(3)
    x = rng.integers(0, TINY.vocab_size, (A, 8, TINY.block_size),
                     dtype=np.int32)
    step = make_train_step(TINY, t, donate=False)

    s_un = create_train_state(jax.random.PRNGKey(0), TINY, t)
    s_un, m_un = step(s_un, (x, x))

    mesh_cfg = MeshConfig(data=4, seq=2, fsdp=True)
    mesh = make_mesh(mesh_cfg)
    ss = make_superbatch_sharding(mesh)
    xb = jax.device_put(x, ss)
    assert xb.sharding.spec == P(None, "data", "seq")
    s_sh = shard_train_state(_state_fn(TINY, t), mesh, mesh_cfg)
    s_sh, m_sh = step(s_sh, (xb, xb))

    np.testing.assert_allclose(float(m_un["loss"]), float(m_sh["loss"]),
                               rtol=2e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(jax.device_get(b)), rtol=1e-4,
            atol=1e-5),
        s_un.params, s_sh.params)


# ---------------------------------------------------------------------------
# batch/head shard_map flash wrapper (parallel/sharded_flash.py) — the
# DP/FSDP/TP mesh path that keeps the Pallas kernel instead of degrading
# to dense einsum (VERDICT r2 item 1)
# ---------------------------------------------------------------------------

def _wrapper_qkv(B=8, H=4, T=256, D=32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, H, T, D), jnp.float32) for k in ks)


@pytest.mark.slow
def test_sharded_flash_wrapper_matches_einsum_interpret(monkeypatch):
    """The shard_map wrapper running the *actual Pallas kernel* (interpret
    mode on CPU) over a (data=4, model=2) mesh must match the unsharded
    einsum core in outputs AND grads."""
    from replicatinggpt_tpu.ops import flash_attention as fa
    from replicatinggpt_tpu.ops.attention import full_causal_attention
    from replicatinggpt_tpu.parallel.sharded_flash import \
        sharded_flash_attention

    monkeypatch.setattr(fa, "_pallas_supported", lambda q: True)
    mesh = make_mesh(MeshConfig(data=4, seq=1, model=2))
    q, k, v = _wrapper_qkv()

    def loss_wrapped(q, k, v):
        out = sharded_flash_attention(q, k, v, mesh=mesh, impl="flash")
        return jnp.sum(out ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(full_causal_attention(q, k, v, impl="einsum") ** 2)

    ref_out = full_causal_attention(q, k, v, impl="einsum")
    got_out = sharded_flash_attention(q, k, v, mesh=mesh, impl="flash")
    np.testing.assert_allclose(np.asarray(got_out), np.asarray(ref_out),
                               atol=2e-5, rtol=2e-5)
    gw = jax.grad(loss_wrapped, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gw, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5,
                                   rtol=5e-5)


def test_sharded_flash_wrapper_dropout_streams_decorrelate(monkeypatch):
    """With attention dropout on, each (data, model) shard must draw an
    independent mask stream (fold_in of the device indices): a replicated
    batch row on different 'data' shards gets different masks."""
    from replicatinggpt_tpu.ops import flash_attention as fa
    from replicatinggpt_tpu.parallel.sharded_flash import \
        sharded_flash_attention

    monkeypatch.setattr(fa, "_pallas_supported", lambda q: False)
    mesh = make_mesh(MeshConfig(data=4, seq=1, model=2))
    q, k, v = _wrapper_qkv(B=4, H=2, T=64, D=16)
    # identical rows across the batch: without per-shard folding, the
    # dropout pattern would repeat across 'data' shards
    q = jnp.broadcast_to(q[:1], q.shape)
    k = jnp.broadcast_to(k[:1], k.shape)
    v = jnp.broadcast_to(v[:1], v.shape)
    out = sharded_flash_attention(q, k, v, mesh=mesh, impl="einsum",
                                  dropout_rate=0.5,
                                  rng=jax.random.PRNGKey(7), train=True)
    out = np.asarray(out)
    assert not np.allclose(out[0], out[1]), \
        "data shards 0 and 1 drew identical dropout masks"


@pytest.mark.slow
def test_dp_training_with_flash_wrapper_matches_single_device(tcfg):
    """DP training through the shard_map wrapper (explicit 'flash'; the
    local core resolves to SDPA on CPU) must match single-device training
    on the same global batch."""
    mcfg = dataclasses.replace(TINY, attention_impl="flash")
    t = dataclasses.replace(tcfg, lr=1e-3)
    batch = _batch(mcfg, B=8)
    state1 = _state_fn(mcfg, t)()
    step1 = make_train_step(mcfg, t, donate=False)
    losses1 = []
    for _ in range(3):
        state1, m = step1(state1, batch)
        losses1.append(float(m["loss"]))

    from replicatinggpt_tpu.parallel import select_attention_fn
    mesh_cfg = MeshConfig(data=8)
    mesh = make_mesh(mesh_cfg)
    attn_fn = select_attention_fn(mcfg, mesh_cfg, mesh)
    assert attn_fn is not None, "explicit 'flash' must select the wrapper"
    state8 = shard_train_state(_state_fn(mcfg, t), mesh, mesh_cfg)
    bs = make_batch_sharding(mesh)
    batch8 = tuple(jax.device_put(np.asarray(b), bs) for b in batch)
    step8 = make_train_step(mcfg, t, donate=False, attention_fn=attn_fn)
    losses8 = []
    for _ in range(3):
        state8, m = step8(state8, batch8)
        losses8.append(float(m["loss"]))
    np.testing.assert_allclose(losses1, losses8, rtol=2e-4)


def test_select_attention_fn_policy_no_seq_axis():
    """Wrapper selection policy on meshes without a seq axis: explicit
    'flash' always wraps (the wrapper self-guards indivisible dims);
    'auto' wraps only on TPU (einsum under GSPMD is the CPU answer);
    explicit 'einsum' never wraps."""
    from replicatinggpt_tpu.parallel import select_attention_fn
    mesh_cfg = MeshConfig(data=4, seq=1, model=2)
    mesh = make_mesh(mesh_cfg)
    flash = dataclasses.replace(TINY, attention_impl="flash")
    assert select_attention_fn(flash, mesh_cfg, mesh) is not None
    # 'auto' on this CPU backend: no wrapper (einsum under GSPMD)
    auto = dataclasses.replace(TINY, attention_impl="auto")
    assert select_attention_fn(auto, mesh_cfg, mesh) is None
    einsum = dataclasses.replace(TINY, attention_impl="einsum")
    assert select_attention_fn(einsum, mesh_cfg, mesh) is None
    # explicit 'flash' with n_head=3 indivisible by model=2 still wraps
    # (the wrapper drops the head axis from its specs, never dense einsum)
    bad = dataclasses.replace(TINY, n_head=3, n_embd=33,
                              attention_impl="flash")
    assert select_attention_fn(bad, mesh_cfg, mesh) is not None
    # explicit 'flash' on a seq-sharded mesh routes to a flash-capable
    # seq-parallel core (never dense einsum — the O(T^2) memory the user
    # opted out of)
    seq_cfg = MeshConfig(data=2, seq=2, model=2)
    assert select_attention_fn(flash, seq_cfg, make_mesh(seq_cfg)) \
        is not None


def test_sharded_flash_wrapper_self_guards_indivisible_dims():
    """shard_map requires even division; the wrapper must drop an
    indivisible axis from its specs (gather instead of crash) and fall
    back to plain einsum when nothing divides — matching the GSPMD
    envelope it replaced."""
    from replicatinggpt_tpu.ops.attention import full_causal_attention
    from replicatinggpt_tpu.parallel.sharded_flash import \
        sharded_flash_attention

    mesh = make_mesh(MeshConfig(data=4, seq=1, model=2))
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    # B=6 does not divide data=4 -> heads-only sharding
    q, k, v = (jax.random.normal(kk, (6, 4, 64, 16), jnp.float32)
               for kk in ks)
    ref = full_causal_attention(q, k, v, impl="einsum")
    got = sharded_flash_attention(q, k, v, mesh=mesh, impl="einsum")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # B=6, H=3: neither axis divides -> plain einsum fallback
    q3, k3, v3 = (t[:, :3] for t in (q, k, v))
    ref3 = full_causal_attention(q3, k3, v3, impl="einsum")
    got3 = sharded_flash_attention(q3, k3, v3, mesh=mesh, impl="einsum")
    np.testing.assert_allclose(np.asarray(got3), np.asarray(ref3),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_mesh_packed_qkv_hook_matches_single_device(monkeypatch):
    """On a DP/FSDP mesh the wrapper's packed_qkv hook must route the
    fused (B,T,3C) projection through the packed-heads kernel (interpret
    mode here) and match single-device training numerics."""
    import replicatinggpt_tpu.ops.flash_attention as fa

    monkeypatch.setattr(fa, "_packed_backend_ok", lambda: True)
    mcfg = dataclasses.replace(TINY, block_size=256, n_head=4, n_embd=128,
                               attention_impl="flash")
    tcfg = dataclasses.replace(get_config("test-tiny").train, lr=1e-3)
    batch = _batch(mcfg, B=8)
    # single device: the packed kernel also engages locally off-mesh only
    # on TPU, so the reference here is the plain split-heads path
    state1 = _state_fn(mcfg, tcfg)()
    step1 = make_train_step(mcfg, tcfg, donate=False)
    state1, m1 = step1(state1, batch)

    from replicatinggpt_tpu.parallel import select_attention_fn
    mesh_cfg = MeshConfig(data=8, fsdp=True)
    mesh = make_mesh(mesh_cfg)
    attn_fn = select_attention_fn(mcfg, mesh_cfg, mesh)
    assert attn_fn is not None and hasattr(attn_fn, "packed_qkv")
    # the hook must actually fire (not fall back to the split path)
    import jax.numpy as jnp2
    probe = attn_fn.packed_qkv(
        jnp.zeros((8, 256, 3 * 128), jnp2.float32), 4)
    assert probe is not None, "packed hook declined in-envelope shapes"

    state8 = shard_train_state(_state_fn(mcfg, tcfg), mesh, mesh_cfg)
    bs = make_batch_sharding(mesh)
    batch8 = tuple(jax.device_put(np.asarray(b), bs) for b in batch)
    step8 = make_train_step(mcfg, tcfg, donate=False, attention_fn=attn_fn)
    state8, m8 = step8(state8, batch8)
    np.testing.assert_allclose(float(m1["loss"]), float(m8["loss"]),
                               rtol=2e-4)


def test_mesh_packed_qkv_hook_absent_with_tp():
    """Meshes that shard heads ('model' > 1) must not carry the packed
    hook — head strips would not be local."""
    from replicatinggpt_tpu.parallel.sharded_flash import \
        make_sharded_flash_attention_fn
    mesh = make_mesh(MeshConfig(data=4, seq=1, model=2))
    fn = make_sharded_flash_attention_fn(mesh)
    assert not hasattr(fn, "packed_qkv")


def test_dp_training_with_chunked_ce_matches_single_device(tcfg):
    """loss_chunk under 8-way DP: the chunked-CE reshape folds the
    dp-sharded batch axis into the scan axis, and GSPMD must still
    produce the single-device numbers (it may pay collectives — the
    hardware A/B prices that; this pins correctness)."""
    tcfg = dataclasses.replace(tcfg, lr=1e-3)
    mcfg = dataclasses.replace(TINY, loss_chunk=32)  # B*T=256 -> 8 chunks
    batch = _batch(mcfg, B=8)
    state1 = _state_fn(mcfg, tcfg)()
    step1 = make_train_step(mcfg, tcfg, donate=False)
    losses1 = []
    for _ in range(3):
        state1, m = step1(state1, batch)
        losses1.append(float(m["loss"]))
    # unchunked single-device oracle: same numbers (order-of-sum only)
    state0 = _state_fn(TINY, tcfg)()
    step0 = make_train_step(TINY, tcfg, donate=False)
    _, m0 = step0(state0, batch)
    np.testing.assert_allclose(losses1[0], float(m0["loss"]), rtol=1e-5)
    mesh = make_mesh(MeshConfig(data=8))
    state8 = shard_train_state(_state_fn(mcfg, tcfg), mesh,
                               MeshConfig(data=8))
    bs = make_batch_sharding(mesh)
    batch8 = tuple(jax.device_put(np.asarray(b), bs) for b in batch)
    step8 = make_train_step(mcfg, tcfg, donate=False)
    losses8 = []
    for _ in range(3):
        state8, m = step8(state8, batch8)
        losses8.append(float(m["loss"]))
    np.testing.assert_allclose(losses1, losses8, rtol=2e-4)
