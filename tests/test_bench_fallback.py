"""bench.py probe-exhaustion -> JAX_PLATFORMS=cpu fallback (satellite).

BENCH_r05 shipped a ZERO-VALUED error artifact from exactly this path:
the accelerator probe exhausted its retries and the artifact carried
value 0.0 instead of a tagged CPU measurement. The existing tagging
test (tests/test_speculative.py) stubs the bench mode out, so it cannot
catch a fallback that tags correctly but then fails to MEASURE — this
one runs the real (tiny) serve bench end to end through the stubbed
probe and pins both halves: ``backend: cpu-fallback`` on the artifact
AND a non-zero metric."""

import json
import sys

import jax
import pytest


def test_probe_exhaustion_falls_back_to_real_cpu_measurement(
        monkeypatch, capsys):
    import bench

    monkeypatch.setattr(bench, "_EMITTED", False)
    monkeypatch.setattr(bench, "_EMIT_TAGS", {})
    probed = []

    def fake_probe(platform, tries, wait_s):
        probed.append(platform)
        if platform != "cpu":
            raise RuntimeError(
                "backend unavailable after 5 probes: wedged tunnel")

    monkeypatch.setattr(bench, "probe_backend", fake_probe)
    monkeypatch.setattr(bench, "start_watchdog", lambda *a, **k: None)
    monkeypatch.setattr(sys, "argv", [
        "bench.py", "--mode", "serve", "--platform", "tpu",
        "--preset", "test-tiny", "--serve-requests", "8",
        "--serve-rate", "2000", "--serve-pool", "4",
        "--serve-max-new-tokens", "4", "--skip-baseline"])
    prev_prng = jax.config.jax_default_prng_impl
    prev_platforms = jax.config.jax_platforms
    try:
        bench.main()
    finally:
        # bench.main flips global jax config; tests share the process
        jax.config.update("jax_default_prng_impl", prev_prng)
        jax.config.update("jax_platforms", prev_platforms)
    line = capsys.readouterr().out.strip().splitlines()[-1]
    payload = json.loads(line)
    assert probed == ["tpu", "cpu"]
    assert payload["backend"] == "cpu-fallback"
    assert "wedged tunnel" in payload["backend_error"]
    assert "error" not in payload
    # the half BENCH_r05 lost: a REAL measurement, not a zeroed artifact
    assert payload["metric"] == "serve_replay_aggregate_tokens_per_sec"
    assert payload["value"] > 0
    assert payload["n_completed"] == 8
    assert payload["recompiles_after_warmup"] == 0
    # the paged-pool block rides every serve artifact
    for key in ("pages_in_use", "page_utilization", "prefix_hit_rate",
                "evictions", "cow_copies"):
        assert key in payload, key


def test_probe_failure_on_cpu_too_still_emits_error_artifact(
        monkeypatch, capsys):
    """If even the CPU probe fails, the honest outcome is the error
    artifact — the fallback must not loop or crash without emitting."""
    import bench

    monkeypatch.setattr(bench, "_EMITTED", False)
    monkeypatch.setattr(bench, "_EMIT_TAGS", {})

    def fake_probe(platform, tries, wait_s):
        raise RuntimeError("no backend at all")

    monkeypatch.setattr(bench, "probe_backend", fake_probe)
    monkeypatch.setattr(bench, "start_watchdog", lambda *a, **k: None)
    monkeypatch.setattr(sys, "argv",
                        ["bench.py", "--mode", "serve", "--platform", "tpu"])
    with pytest.raises(SystemExit):
        bench.main()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    payload = json.loads(line)
    assert payload["value"] == 0.0
    assert "no backend at all" in payload["error"]
