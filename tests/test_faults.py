"""Chaos tests: every injected fault class must be *survived*, not just
detected.

Each test installs a deterministic FaultPlan (faults/inject.py) and
asserts the matching recovery contract from docs/robustness.md:

- corrupt / truncated / partial checkpoints -> named CorruptCheckpointError
  + restore_latest fallback to the newest verified step;
- transient save/restore I/O -> exponential-backoff retries succeed;
- NaN / loss-spike -> supervised rollback; a transient fault resumes
  BITWISE identical to an uninterrupted run; a persistent one advances
  the data cursor, then dies after K rollbacks;
- SIGTERM mid-step -> graceful checkpoint, bitwise-identical resume;
- stalled engine steps -> watchdog counts, requests still finish;
- drafter accept-rate collapse -> speculative auto-disable + re-probe,
  greedy output parity across every transition, zero recompiles;
- sustained overload -> load shedding with every request accounted for;
- engine crash -> journal requeue; every admitted request is served.

Fast deterministic tests run in tier-1 (`-m chaos` selects them); the
replay soak is additionally marked slow.
"""

import dataclasses
import signal

import jax
import numpy as np
import pytest

from replicatinggpt_tpu.config import ModelConfig, get_config
from replicatinggpt_tpu.faults import (Fault, FaultPlan, ResilienceConfig,
                                       SupervisionConfig,
                                       SupervisionExhausted, installed,
                                       supervised_train)
from replicatinggpt_tpu.faults.watchdog import (LoadShedder, SpecHealth,
                                                StepWatchdog)
from replicatinggpt_tpu.models.gpt import init_params
from replicatinggpt_tpu.sample import GenerateConfig, generate
from replicatinggpt_tpu.serve import (Engine, EngineConfig, NGramDrafter,
                                      Request, RequestJournal,
                                      SamplingParams, compile_counts)
from replicatinggpt_tpu.serve.requests import (FINISH_DEADLINE, FINISH_SHED,
                                               FINISH_MAX_TOKENS)
from replicatinggpt_tpu.train.checkpoint import (CheckpointManager,
                                                 CorruptCheckpointError)
from replicatinggpt_tpu.train.runner import train
from replicatinggpt_tpu.train.state import create_train_state

CFG = ModelConfig(vocab_size=65, block_size=32, n_layer=2, n_head=2,
                  n_embd=32, dropout=0.0, attn_dropout=0.0, dtype="float32")


@pytest.fixture(scope="module")
def params():
    return init_params(jax.random.PRNGKey(0), CFG)


def _train_cfg(max_iters=8, checkpoint_every=4):
    cfg = get_config("test-tiny")
    return cfg.replace(
        train=dataclasses.replace(cfg.train, max_iters=max_iters,
                                  eval_interval=0, eval_iters=2,
                                  log_interval=0, batch_size=8,
                                  sampling="sequential",
                                  checkpoint_every=checkpoint_every),
        dataset="datasets/shakespeare.txt")


@pytest.fixture(scope="module")
def full_run8():
    """Uninterrupted 8-step run — the bitwise oracle for every
    rollback/resume test in this module."""
    return train(_train_cfg())


def _trees_equal(a, b):
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def _offline_greedy(params, req):
    """The request's NEW tokens under offline generate (greedy)."""
    return np.asarray(generate(
        params, req.prompt[None, :], CFG,
        GenerateConfig(max_new_tokens=req.max_new_tokens,
                       greedy=True)))[0].tolist()


# ---------------------------------------------------------------------------
# FaultPlan mechanics
# ---------------------------------------------------------------------------

def test_fault_plan_is_deterministic_and_one_shot():
    plan = FaultPlan(Fault(site="a", kind="x", at=2),
                     Fault(site="b", kind="y", at=0, times=2))
    # index-keyed: fires at index 2 exactly once, even if the caller
    # replays index 2 (the rollback-replay contract)
    assert plan.fire("a", index=0) is None
    assert plan.fire("a", index=2).kind == "x"
    assert plan.fire("a", index=2) is None      # one-shot across replay
    # counter-keyed: first two calls fire, later ones don't
    assert plan.fire("b").kind == "y"
    assert plan.fire("b").kind == "y"
    assert plan.fire("b") is None
    assert plan.count("a", "x") == 1 and plan.count("b") == 2
    # seeded payload RNG is stable per (seed, site)
    a = FaultPlan(seed=7).rng("s").integers(0, 100, 4)
    b = FaultPlan(seed=7).rng("s").integers(0, 100, 4)
    np.testing.assert_array_equal(a, b)


def test_no_plan_seams_are_noops():
    from replicatinggpt_tpu.faults import active, clear, fire
    clear()
    assert active() is None and fire("anything") is None


# ---------------------------------------------------------------------------
# checkpoint: transient I/O, corruption, fallback
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_transient_save_and_restore_io_retries(tmp_path):
    cfg = get_config("test-tiny")
    state = create_train_state(jax.random.PRNGKey(0), cfg.model, cfg.train)
    ck = CheckpointManager(str(tmp_path / "ck"))
    with installed(FaultPlan(Fault(site="ckpt/save", kind="io", times=2))):
        assert ck.save(state, wait=True) == 0
    assert ck.recovery["save_retries"] == 2
    with installed(FaultPlan(Fault(site="ckpt/restore", kind="io",
                                   times=2))):
        restored = ck.restore_latest(state)
    assert restored is not None
    assert ck.recovery["restore_retries"] == 2
    assert ck.recovery["ckpt_fallbacks"] == 0
    _trees_equal(state, restored)
    ck.close()


@pytest.mark.chaos
def test_persistent_restore_failure_raises_not_none(tmp_path):
    """Checkpoints that EXIST but cannot be restored must raise — a
    None return would read as 'fresh run' and silently restart from
    step 0, destroying the run the caller asked to continue. None is
    reserved for a genuinely empty directory."""
    cfg = get_config("test-tiny")
    state = create_train_state(jax.random.PRNGKey(0), cfg.model, cfg.train)
    ck = CheckpointManager(str(tmp_path / "ck"), retries=1)
    assert ck.restore_latest(state) is None       # empty dir: fresh run
    ck.save(state, wait=True)
    with installed(FaultPlan(Fault(site="ckpt/restore", kind="io",
                                   times=99))):
        with pytest.raises(CorruptCheckpointError,
                           match="no restorable checkpoint"):
            ck.restore_latest(state)
    assert ck.recovery["ckpt_fallbacks"] == 1
    ck.close()


@pytest.mark.chaos
@pytest.mark.parametrize("kind", ["corrupt", "truncate"])
def test_corrupt_checkpoint_named_and_fallen_past(tmp_path, kind):
    """Silent bit rot / a partial write in the NEWEST step: restore(step)
    raises an explicit 'step N is corrupt' error, restore_latest falls
    back to the previous verified step."""
    from replicatinggpt_tpu.train.steps import make_train_step
    cfg = get_config("test-tiny")
    m, t = cfg.model, cfg.train
    state = create_train_state(jax.random.PRNGKey(0), m, t)
    step = make_train_step(m, t, donate=False)
    x = jax.random.randint(jax.random.PRNGKey(1), (4, m.block_size), 0,
                           m.vocab_size)
    ck = CheckpointManager(str(tmp_path / "ck"))
    ck.save(state, wait=True)                       # good step 0
    state1, _ = step(state, (x, x))
    with installed(FaultPlan(Fault(site="ckpt/finalize", kind=kind,
                                   at=1))):
        ck.save(state1, wait=True)                  # corrupted step 1
    with pytest.raises(CorruptCheckpointError, match="step 1 is corrupt"):
        ck.restore(1, state)
    restored = ck.restore_latest(state)
    assert restored is not None and int(restored.step) == 0
    assert ck.recovery["ckpt_fallbacks"] == 1
    _trees_equal(state, restored)
    ck.close()


@pytest.mark.chaos
def test_nan_poisoned_checkpoint_rejected_at_restore(tmp_path):
    """A checkpoint whose params were already non-finite at save time
    must never be a rollback target — the manifest's finite bit rejects
    it and restore_latest falls back."""
    cfg = get_config("test-tiny")
    state = create_train_state(jax.random.PRNGKey(0), cfg.model, cfg.train)
    ck = CheckpointManager(str(tmp_path / "ck"))
    ck.save(state, wait=True)
    leaves, treedef = jax.tree_util.tree_flatten(state.params)
    leaves[0] = leaves[0] * float("nan")
    poisoned = state._replace(
        params=jax.tree_util.tree_unflatten(treedef, leaves),
        step=state.step + 1)
    ck.save(poisoned, wait=True)
    with pytest.raises(CorruptCheckpointError, match="non-finite"):
        ck.restore(1, state)
    restored = ck.restore_latest(state)
    assert int(restored.step) == 0
    assert ck.recovery["ckpt_fallbacks"] == 1
    ck.close()


# ---------------------------------------------------------------------------
# train: NaN/spike rollback, data-cursor advance, SIGTERM
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_nan_rollback_resumes_bitwise_identical(tmp_path, full_run8):
    """One-shot state corruption at step 6: the supervisor detects the
    non-finite loss, rolls back to the step-4 checkpoint, and the
    replayed tail is BITWISE identical to an uninterrupted run (state,
    cursor, and step-keyed RNG all restore exactly)."""
    ck = CheckpointManager(str(tmp_path / "ck"))
    with installed(FaultPlan(Fault(site="train/step", kind="nan_params",
                                   at=6))) as plan:
        out = supervised_train(
            _train_cfg(), checkpoint_manager=ck,
            supervision=SupervisionConfig(check_every=1), max_rollbacks=3)
    assert plan.count("train/step", "nan_params") == 1
    assert out.counters.get("rollbacks") == 1
    assert out.counters.get("data_skips") is None   # transient: no skip
    assert int(jax.device_get(out.result.state.step)) == 8
    _trees_equal(full_run8.state.params, out.result.state.params)
    ck.close()


@pytest.mark.chaos
def test_loss_spike_rolls_back(tmp_path, full_run8):
    """An injected 1000x spike in the observed loss (params untouched)
    trips the EMA budget and rolls back; the replay is clean so the
    final state is again bitwise the uninterrupted run."""
    ck = CheckpointManager(str(tmp_path / "ck"))
    with installed(FaultPlan(Fault(site="train/loss", kind="spike", at=6,
                                   arg=1000.0))):
        out = supervised_train(
            _train_cfg(), checkpoint_manager=ck,
            supervision=SupervisionConfig(check_every=1, spike_factor=10.0,
                                          warmup_checks=2),
            max_rollbacks=3)
    assert out.counters.get("rollbacks") == 1
    _trees_equal(full_run8.state.params, out.result.state.params)
    ck.close()


@pytest.mark.chaos
def test_repeat_failure_advances_data_cursor_then_recovers(tmp_path):
    """The same step failing twice implicates the data window: the
    supervisor advances the cursor past it on the next attempt. With
    the fault exhausted after two firings, attempt 3 completes."""
    ck = CheckpointManager(str(tmp_path / "ck"))
    with installed(FaultPlan(Fault(site="train/loss", kind="nan", at=5,
                                   times=2))):
        out = supervised_train(
            _train_cfg(), checkpoint_manager=ck,
            supervision=SupervisionConfig(check_every=1), max_rollbacks=3)
    assert out.counters.get("rollbacks") == 2
    assert out.counters.get("data_skips") == 1
    assert int(jax.device_get(out.result.state.step)) == 8
    ck.close()


@pytest.mark.chaos
def test_supervision_dies_after_k_failed_rollbacks(tmp_path):
    ck = CheckpointManager(str(tmp_path / "ck"))
    with installed(FaultPlan(Fault(site="train/loss", kind="nan", at=5,
                                   times=99))):
        with pytest.raises(SupervisionExhausted):
            supervised_train(
                _train_cfg(), checkpoint_manager=ck,
                supervision=SupervisionConfig(check_every=1),
                max_rollbacks=2)
    ck.close()


@pytest.mark.chaos
def test_sigterm_mid_step_checkpoints_then_resumes_bitwise(tmp_path,
                                                           full_run8):
    """Injected SIGTERM at step 5 goes through a real signal handler
    (wired exactly like the CLI's): the loop checkpoints and exits
    cleanly; resuming trains to 8 bitwise-identical to uninterrupted."""
    import threading
    stop = threading.Event()
    prev = signal.signal(signal.SIGTERM, lambda s, f: stop.set())
    ck = CheckpointManager(str(tmp_path / "ck"))
    try:
        with installed(FaultPlan(Fault(site="train/step", kind="sigterm",
                                       at=5))):
            res = train(_train_cfg(), checkpoint_manager=ck,
                        stop_event=stop)
    finally:
        signal.signal(signal.SIGTERM, prev)
    stopped = int(jax.device_get(res.state.step))
    assert stopped == 5
    ck.wait()
    assert ck.latest_step() == 5
    resumed = train(_train_cfg(), checkpoint_manager=ck, resume=True)
    assert int(jax.device_get(resumed.state.step)) == 8
    _trees_equal(full_run8.state.params, resumed.state.params)
    ck.close()


# ---------------------------------------------------------------------------
# serve: expired deadlines, watchdog, collapse, shedding, journal
# ---------------------------------------------------------------------------

def _req(rid, prompt, max_new, seed=0):
    return Request(id=rid, prompt=np.asarray(prompt, np.int32),
                   max_new_tokens=max_new,
                   sampling=SamplingParams(greedy=True), rng_seed=seed)


def test_submit_rejects_already_expired_deadline(params):
    eng = Engine(params, CFG, EngineConfig(pool_size=1, max_queue=4))
    r = _req("dead", [1, 2], 4)
    r.deadline = eng.clock() - 1.0          # expired before submit
    res = eng.submit(r)
    assert res is not None and res.finish_reason == FINISH_DEADLINE
    assert eng.metrics.counters["finished_deadline"] == 1
    assert len(eng.scheduler) == 0          # never queued
    # a live deadline still queues
    r2 = _req("alive", [1, 2], 2)
    r2.deadline = eng.clock() + 60.0
    assert eng.submit(r2) is None
    out = {x.id: x for x in eng.drain()}
    assert out["alive"].finish_reason == FINISH_MAX_TOKENS


@pytest.mark.chaos
def test_watchdog_counts_stall_and_requests_finish(params):
    rcfg = ResilienceConfig(stall_factor=2.0, stall_floor_s=0.02,
                            stall_min_steps=5)
    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=8),
                 rcfg=rcfg)
    for i in range(3):
        assert eng.submit(_req(f"r{i}", [1 + i, 2, 3], 12)) is None
    with installed(FaultPlan(Fault(site="serve/step", kind="delay", at=8,
                                   arg=0.3))):
        out = eng.drain()
    assert len(out) == 3
    assert all(r.finish_reason == FINISH_MAX_TOKENS for r in out)
    assert eng.metrics.counters.get("watchdog_stalls", 0) >= 1
    assert any("stall" in e for e in eng.events)


@pytest.mark.chaos
def test_accept_collapse_disables_then_reprobes_with_parity(params):
    """Drafter corruption collapses the accept rate: the engine
    auto-disables speculation (plain decode keeps serving), re-probes
    after the cooldown, resyncs the stateful drafter's cache over the
    tokens committed while degraded, finds it healthy again (with
    draft params == target params the accept rate is exactly 1.0, so
    any resync bug would re-collapse it), and the greedy token streams
    match offline generate across every transition — with ZERO
    compiles beyond the warmed program set."""
    from replicatinggpt_tpu.serve import ModelDrafter
    ecfg = EngineConfig(pool_size=2, max_queue=8)
    rcfg = ResilienceConfig(spec_disable_threshold=0.4, spec_window=3,
                            spec_reprobe_after=4)

    def drafter():
        return ModelDrafter(params, CFG, k=2, pool_size=2)

    # warm both steady-state paths (spec verify + degraded decode) the
    # way replay warmup does, then pin the compile counts
    w = Engine(params, CFG, ecfg, drafter=drafter())
    assert w.submit(_req("w0", [3, 4, 3, 4, 3, 4], 4)) is None
    w.drain()
    w.set_spec_active(False)
    assert w.submit(_req("w1", [3, 4, 3, 4, 3, 4], 4)) is None
    w.drain()
    warm = compile_counts()

    eng = Engine(params, CFG, ecfg, drafter=drafter(), rcfg=rcfg)
    reqs = [_req("a", [5, 6, 5, 6, 5, 6], 24),
            _req("b", [7, 8, 7, 8, 7, 8], 24)]
    for r in reqs:
        assert eng.submit(r) is None
    with installed(FaultPlan(Fault(site="spec/draft", kind="collapse",
                                   times=3))):
        out = {r.id: r for r in eng.drain()}
    c = eng.metrics.counters
    assert c.get("spec_disables", 0) == 1, eng.events
    assert c.get("spec_reprobes", 0) == 1, eng.events
    assert eng.spec_active                  # probe found it healthy
    for r in reqs:
        assert out[r.id].finish_reason == FINISH_MAX_TOKENS
        assert out[r.id].tokens == _offline_greedy(params, r)
    assert compile_counts() == warm         # degraded transitions free


@pytest.mark.chaos
def test_load_shedding_under_sustained_overload(params):
    rcfg = ResilienceConfig(shed_watermark=0.25, shed_patience=2)
    eng = Engine(params, CFG, EngineConfig(pool_size=1, max_queue=16),
                 rcfg=rcfg)
    n = 12
    for i in range(n):
        assert eng.submit(_req(f"r{i}", [1 + (i % 7), 2], 6,
                               seed=i)) is None
    out = eng.drain()
    assert len(out) == n                    # every request accounted for
    shed = [r for r in out if r.finish_reason == FINISH_SHED]
    done = [r for r in out if r.finish_reason == FINISH_MAX_TOKENS]
    assert len(shed) == eng.metrics.counters["shed_requests"] > 0
    assert len(shed) + len(done) == n
    assert all(not r.tokens for r in shed)  # shed before any work


@pytest.mark.chaos
def test_journal_requeues_inflight_requests_after_crash(params, tmp_path):
    """Crash mid-flight: a fresh engine requeues the journal's
    accepted-but-unfinished requests and serves them to completion,
    greedy-identical to offline generate (per-request seeds make
    regeneration exact)."""
    path = str(tmp_path / "journal.jsonl")
    jr = RequestJournal(path)
    eng = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=8),
                 journal=jr)
    reqs = [_req(f"r{i}", [2 + i, 3, 4], 3 if i < 2 else 10, seed=i)
            for i in range(6)]
    for r in reqs:
        assert eng.submit(r) is None
    finished_before = set()
    for _ in range(4):                      # run partway, then "crash"
        for r in eng.step():
            finished_before.add(r.id)
    del eng                                 # no drain, no goodbye
    jr.close()

    pending = RequestJournal.unfinished(path)
    assert {r.id for r in pending} == {r.id for r in reqs} - finished_before
    assert pending, "test must crash with work in flight"

    jr2 = RequestJournal(path)
    eng2 = Engine(params, CFG, EngineConfig(pool_size=2, max_queue=8),
                 journal=jr2)
    for r in pending:
        assert eng2.submit(r) is None
    out = {r.id: r for r in eng2.drain()}
    for r in pending:
        assert out[r.id].finish_reason == FINISH_MAX_TOKENS
        orig = next(q for q in reqs if q.id == r.id)
        assert out[r.id].tokens == _offline_greedy(params, orig)
    # the journal now shows nothing outstanding
    jr2.close()
    assert RequestJournal.unfinished(path) == []


@pytest.mark.chaos
def test_journal_tolerates_torn_tail_record(params, tmp_path):
    path = str(tmp_path / "j.jsonl")
    jr = RequestJournal(path)
    jr.record_submit(_req("whole", [1, 2], 4))
    jr.close()
    with open(path, "a") as f:
        f.write('{"ev": "submit", "id": "torn", "pro')   # crash mid-write
    pending = RequestJournal.unfinished(path)
    assert [r.id for r in pending] == ["whole"]


@pytest.mark.chaos
def test_operator_spec_pin_sticks(params):
    """set_spec_active(False) is an operator pin: the auto-re-probe
    policy must NOT undo it (only auto-disables are re-probeable)."""
    rcfg = ResilienceConfig(spec_disable_threshold=0.4, spec_window=3,
                            spec_reprobe_after=1)
    eng = Engine(params, CFG, EngineConfig(pool_size=1, max_queue=4),
                 drafter=NGramDrafter(k=2), rcfg=rcfg)
    eng.set_spec_active(False)
    assert eng.submit(_req("a", [5, 6, 5, 6], 6)) is None
    out = eng.drain()
    assert out[0].finish_reason == FINISH_MAX_TOKENS
    assert not eng.spec_active                  # pin survived the run
    assert eng.metrics.counters.get("spec_reprobes", 0) == 0
    eng.set_spec_active(True)                   # lifting the pin works
    assert eng.spec_active


# ---------------------------------------------------------------------------
# policy units (host-only, no device)
# ---------------------------------------------------------------------------

def test_step_watchdog_budget():
    cfg = ResilienceConfig(stall_factor=3.0, stall_floor_s=0.0,
                           stall_min_steps=4)
    wd = StepWatchdog(cfg)
    for _ in range(8):
        assert not wd.observe(0.010)
    assert wd.observe(0.050)                # 5x the p99
    assert not wd.observe(0.011)


def test_spec_health_disable_reprobe_backoff():
    cfg = ResilienceConfig(spec_disable_threshold=0.5, spec_window=3,
                           spec_reprobe_after=2, spec_reprobe_backoff=2.0)
    h = SpecHealth(cfg)
    assert not h.observe(3, 3)              # window not full yet
    assert not h.observe(3, 3)
    assert not h.observe(3, 3)              # healthy at rate 1.0
    for _ in range(3):
        bad = h.observe(3, 0)
    assert bad
    h.on_disable()
    assert not h.tick_disabled() and h.tick_disabled()   # 2-step cooldown
    h.on_disable()                          # failed probe: backoff 2x
    assert [h.tick_disabled() for _ in range(4)] == [False] * 3 + [True]
    h.on_reenable()                         # healthy probe resets it
    h.on_disable()
    assert [h.tick_disabled() for _ in range(2)] == [False, True]


def test_load_shedder_patience_and_amount():
    cfg = ResilienceConfig(shed_watermark=0.5, shed_patience=2)
    sh = LoadShedder(cfg)
    assert sh.observe(9, 16) == 0           # over, but patience 1/2
    assert sh.observe(9, 16) == 1           # sustained: down to 8
    assert sh.observe(4, 16) == 0           # back under: streak resets
    assert sh.observe(9, 16) == 0


# ---------------------------------------------------------------------------
# soak: replay with overlapping fault classes (slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
@pytest.mark.slow
def test_replay_soak_with_overlapping_faults(params, tmp_path):
    """A 48-request replay with stalls + drafter collapse + shedding all
    enabled: every request gets a terminal result, the engine ends
    healthy, and the steady state stays at zero recompiles after a
    both-path warmup."""
    from replicatinggpt_tpu.serve import ReplayConfig, run_replay
    jr = RequestJournal(str(tmp_path / "soak.jsonl"))
    rcfg = ReplayConfig(n_requests=48, rate=500.0, seed=3,
                        prompt_len_max=CFG.block_size // 2,
                        max_new_tokens=12, greedy=True,
                        prompt_mode="repeat", spec="ngram", spec_k=3)
    resilience = ResilienceConfig(stall_factor=4.0, stall_floor_s=0.05,
                                  stall_min_steps=10,
                                  spec_disable_threshold=0.3,
                                  spec_window=4, spec_reprobe_after=8,
                                  shed_watermark=0.9, shed_patience=8)
    with installed(FaultPlan(
            Fault(site="serve/step", kind="delay", at=20, arg=0.2),
            Fault(site="spec/draft", kind="collapse", at=5, times=4))):
        summary = run_replay(params, CFG, rcfg,
                             EngineConfig(pool_size=4, max_queue=96),
                             resilience=resilience, journal=jr)
    assert summary["recompiles_after_warmup"] == 0
    rec = summary["recovery"]
    assert rec["spec_disables"] >= 1
    c = summary["counters"]
    terminal = sum(v for k, v in c.items() if k.startswith("finished_")) \
        + sum(v for k, v in c.items() if k.startswith("rejected_"))
    assert terminal == 48
    jr.close()
    assert RequestJournal.unfinished(str(tmp_path / "soak.jsonl")) == []
