#!/usr/bin/env python
"""Exploration walkthrough — the TPU-native equivalent of the reference's
``GPTNotebook2.ipynb`` (its only test artifact, SURVEY.md §2.0 C22).

The notebook's three exercises, re-done against this framework, offline:

1. cells 0-2 — inspect the GPT-2 parameter inventory (names + shapes).
   The notebook downloads HF gpt2 and prints ``state_dict`` entries; here
   the same inventory comes from the framework's own pytree layout for the
   124M config, alongside the HF name each tensor imports from
   (interop/hf.py mapping of GPT-2.py:132-177). With network access,
   ``python -m replicatinggpt_tpu import-hf --model-type gpt2`` does the
   real import.
2. cell 3 — seeded generation smoke test (the notebook uses HF
   ``pipeline('text-generation')`` + ``set_seed(42)``): a seeded sample
   from a framework model.
3. cells 4-6 — tokenize 1000 characters of the corpus and reshape a
   24-token prefix to (8, 3) batches.

Run: python examples/explore_gpt2.py  (CPU-safe, ~30 s)
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np


def section(title):
    print(f"\n=== {title} " + "=" * max(0, 60 - len(title)))


# --- 1. parameter inventory (notebook cells 0-2) ---------------------------
section("GPT-2 124M parameter inventory")
from replicatinggpt_tpu.interop.hf import config_for_model_type
from replicatinggpt_tpu.models.gpt import init_params, param_count

cfg = config_for_model_type("gpt2")
params = jax.eval_shape(lambda k: init_params(k, cfg), jax.random.PRNGKey(0))
flat, _ = jax.tree_util.tree_flatten_with_path(params)
for path, leaf in flat:
    name = "/".join(str(p.key) for p in path if hasattr(p, "key"))
    print(f"{name:<28} {tuple(leaf.shape)}")
print(f"total params: {param_count(params):,} "
      f"(the notebook's gpt2 state_dict counts 124M)")
print("per-layer tensors carry a leading (n_layer,) axis — the lax.scan "
      "layout; HF Conv1D weights import untransposed (interop/hf.py)")

# --- 2. seeded generation smoke test (notebook cell 3) ---------------------
section("seeded generation smoke test")
from replicatinggpt_tpu.config import get_config
from replicatinggpt_tpu.data.dataset import load_corpus
from replicatinggpt_tpu.sample import GenerateConfig, generate
from replicatinggpt_tpu.tokenizers import get_tokenizer

tiny = get_config("test-tiny")
text = load_corpus(os.path.join(os.path.dirname(__file__), "..",
                                tiny.dataset))
tok = get_tokenizer("char", corpus_text=text)
mcfg = tiny.model
params = init_params(jax.random.PRNGKey(0), mcfg)
prompt = jnp.asarray(np.array([tok.encode("ROMEO:")], np.int32))
toks = generate(params, prompt, mcfg,
                GenerateConfig(max_new_tokens=40, top_k=50),
                rng=jax.random.PRNGKey(42))  # the notebook's set_seed(42)
print("prompt 'ROMEO:' ->", repr(tok.decode(np.asarray(toks)[0].tolist())))
print("(untrained weights: expect noise; train with "
      "`python -m replicatinggpt_tpu train --preset char-gpt`)")

# --- 3. tokenize + reshape (notebook cells 4-6) ----------------------------
section("tokenize 1000 chars, reshape 24 tokens to (8, 3)")
bpe = get_tokenizer("bpe", corpus_text=text,
                    cache_dir=os.path.join(os.path.dirname(__file__), "..",
                                           "datasets"))
ids = bpe.encode(text[:1000])
print(f"1000 chars -> {len(ids)} BPE tokens (vocab {bpe.vocab_size})")
buf = np.asarray(ids[:24], np.int32).reshape(8, 3)
print("first 24 tokens as an (8, 3) batch:\n", buf)
print("decoded row 0:", repr(bpe.decode(buf[0].tolist())))
