"""Train state: params + optimizer state + step + RNG, as one pytree.

The reference keeps optimizer state implicitly inside torch.optim.AdamW
(GPT1.py:218) and loses it at checkpoint time (only model state_dict saved,
GPT1.py:239-241). Here the full state is one pytree — jit-donated through
the train step, sharded by the same partition rules as params, and
checkpointed whole (SURVEY.md §5 checkpoint row).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from ..config import ModelConfig, TrainConfig
from ..models.gpt import init_params


class TrainState(NamedTuple):
    step: jnp.ndarray          # scalar int32
    params: Any                # model parameter pytree
    opt_state: Any             # optax state
    rng: jax.Array             # threaded PRNG key (dropout)


def lr_schedule_fn(tcfg: TrainConfig):
    if tcfg.lr_schedule == "constant" and tcfg.warmup_iters == 0:
        return tcfg.lr
    if tcfg.lr_schedule == "cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=tcfg.lr,
            warmup_steps=max(tcfg.warmup_iters, 1),
            decay_steps=max(tcfg.max_iters, tcfg.warmup_iters + 1),
            end_value=tcfg.min_lr)
    return optax.linear_schedule(0.0, tcfg.lr, max(tcfg.warmup_iters, 1))


def make_optimizer(tcfg: TrainConfig) -> optax.GradientTransformation:
    """AdamW matching the reference's optimizer choice (GPT1.py:218,
    GPT-2.py:221), with optional global-norm clipping and LR schedule."""
    chain = []
    if tcfg.grad_clip and tcfg.grad_clip > 0:
        chain.append(optax.clip_by_global_norm(tcfg.grad_clip))
    chain.append(optax.adamw(
        learning_rate=lr_schedule_fn(tcfg),
        b1=tcfg.betas[0], b2=tcfg.betas[1],
        weight_decay=tcfg.weight_decay))
    return optax.chain(*chain)


def create_train_state(rng: jax.Array, mcfg: ModelConfig, tcfg: TrainConfig
                       ) -> TrainState:
    p_rng, d_rng = jax.random.split(rng)
    params = init_params(p_rng, mcfg)
    opt_state = make_optimizer(tcfg).init(params)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                      opt_state=opt_state, rng=d_rng)
