"""Checkpoint save/restore: full training state, resumable, sharding-aware.

The reference saves only the final model state_dict (GPT1.py:239-241) and
has no load path at all (SURVEY.md §5) — a crash loses the run. Here a
checkpoint is the complete resume state named in SURVEY.md §5:

    {params, optimizer state, step, dropout RNG key, data-loader cursor}

backed by orbax (async-capable, sharded-array aware: each host writes its
own shards; restore can re-lay-out onto any mesh via abstract targets).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from .state import TrainState


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 create=True))

    def save(self, state: TrainState, batcher: Any = None,
             wait: bool = False) -> int:
        step = int(jax.device_get(state.step))
        args = {"state": ocp.args.StandardSave(state)}
        if batcher is not None:
            args["data"] = ocp.args.JsonSave(batcher.state())
        self.mngr.save(step, args=ocp.args.Composite(**args))
        if wait:
            self.mngr.wait_until_finished()
        return step

    def latest_step(self) -> Optional[int]:
        return self.mngr.latest_step()

    def restore(self, step: int, state_template: TrainState,
                batcher: Any = None,
                shardings: Any = None) -> TrainState:
        """Restore into the template's structure. ``shardings`` (optional
        pytree of NamedSharding matching the state) re-lays-out arrays onto
        a mesh at load time — resume on a different topology than the save.
        """
        target = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            state_template)
        if shardings is not None:
            target = jax.tree_util.tree_map(
                lambda t, s: jax.ShapeDtypeStruct(t.shape, t.dtype,
                                                  sharding=s),
                target, shardings)
        args = {"state": ocp.args.StandardRestore(target)}
        if batcher is not None:
            args["data"] = ocp.args.JsonRestore()
        out = self.mngr.restore(step, args=ocp.args.Composite(**args))
        if batcher is not None and out.get("data") is not None:
            batcher.restore(out["data"])
        return out["state"]

    def restore_latest(self, state_template: TrainState, batcher: Any = None,
                       shardings: Any = None) -> Optional[TrainState]:
        step = self.latest_step()
        if step is None:
            return None
        return self.restore(step, state_template, batcher, shardings)

    def wait(self) -> None:
        self.mngr.wait_until_finished()

    def close(self) -> None:
        self.mngr.close()
