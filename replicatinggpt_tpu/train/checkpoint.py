"""Checkpoint save/restore: full training state, resumable, sharding-aware,
and *verifiable* — a corrupt or partial checkpoint is detected, named, and
skipped, not restored.

The reference saves only the final model state_dict (GPT1.py:239-241) and
has no load path at all (SURVEY.md §5) — a crash loses the run. Here a
checkpoint is the complete resume state named in SURVEY.md §5:

    {params, optimizer state, step, dropout RNG key, data-loader cursor}

backed by orbax (async-capable, sharded-array aware: each host writes its
own shards; restore can re-lay-out onto any mesh via abstract targets).

Robustness layer (PR 4, docs/robustness.md):

- **Integrity manifest.** Every save writes ``manifest-<step>.json``
  (atomic tmp+rename finalize) holding a per-array crc32 + dtype/shape +
  finiteness bit, computed from the exact state handed to orbax. Restore
  recomputes and compares: silent bit rot — which orbax cannot see —
  surfaces as :class:`CorruptCheckpointError` naming the step and array
  instead of a training run that quietly diverges. A checkpoint whose
  params were already non-finite at save time is rejected the same way,
  so a NaN-poisoned save can never be a rollback target.
- **Fallback restore.** ``restore_latest`` walks steps newest-first and
  falls back past corrupt/partial ones (counted in
  ``recovery['ckpt_fallbacks']``), returning the newest checkpoint that
  verifies.
- **Transient-I/O retries.** Save and restore retry OSError with
  exponential backoff (``retries``/``backoff_s``) before giving up —
  the blip-prone storage of preemptible TPU pods must not kill a run
  that a 100 ms retry would have saved.

Fault seams (``faults/inject.py``): ``ckpt/save`` and ``ckpt/restore``
(transient ``io``), ``ckpt/finalize`` (``corrupt``/``truncate``/
``drop_manifest`` after a completed save) — no-ops unless a chaos test
installs a plan.
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import Any, Dict, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from ..faults.inject import corrupt_step_dir, fire
from .state import TrainState


class CorruptCheckpointError(RuntimeError):
    """A checkpoint step failed integrity verification (checksum
    mismatch, non-finite params, unreadable metadata, or a restore
    error on bytes that should have been valid)."""


def _state_fingerprint(state: Any) -> Dict[str, Dict[str, Any]]:
    """Per-array integrity record of a state pytree: crc32 over the
    logical array bytes (host fetch — sharding-independent), dtype,
    shape, and an ``finite`` bit for float leaves. The manifest is the
    save-time fingerprint; restore recomputes and diffs."""
    out: Dict[str, Dict[str, Any]] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        # the fingerprint IS a host fetch by design (checksums need the
        # bytes); it runs once per save/restore, never in the step loop
        a = np.asarray(jax.device_get(leaf))  # graftlint: disable=GL004
        finite = True
        if np.issubdtype(a.dtype, np.floating):
            finite = bool(np.isfinite(a.astype(np.float32)).all())
        out[jax.tree_util.keystr(path)] = {
            "crc32": zlib.crc32(np.ascontiguousarray(a).tobytes()),
            "dtype": str(a.dtype),
            "shape": list(a.shape),
            "finite": finite,
        }
    return out


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3,
                 retries: int = 2, backoff_s: float = 0.05,
                 integrity: bool = True):
        self.directory = os.path.abspath(directory)
        self.retries = retries
        self.backoff_s = backoff_s
        # full-fidelity manifests need the whole logical array on one
        # host; multi-process runs skip them (each host sees only its
        # shards) — orbax's own per-shard atomicity still applies
        self.integrity = integrity and jax.process_count() == 1
        #: recovery bookkeeping the supervisor merges into its Metrics
        self.recovery: Dict[str, int] = {
            "ckpt_fallbacks": 0, "save_retries": 0, "restore_retries": 0}
        self.mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 create=True))

    # ------------------------------------------------------------ helpers

    def _with_retries(self, fn, what: str, counter: str):
        """Run ``fn`` retrying transient OSErrors with exponential
        backoff; the last failure propagates."""
        for attempt in range(self.retries + 1):
            try:
                return fn()
            except OSError as e:
                if attempt == self.retries:
                    raise
                self.recovery[counter] += 1
                delay = self.backoff_s * (2 ** attempt)
                print(f"checkpoint {what}: transient I/O failure "
                      f"({e}); retry {attempt + 1}/{self.retries} "
                      f"in {delay:.2f}s", flush=True)
                time.sleep(delay)

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self.directory, f"manifest-{step}.json")

    def _write_manifest(self, step: int, state: TrainState) -> None:
        man = {"step": step, "arrays": _state_fingerprint(state)}
        tmp = self._manifest_path(step) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(man, f)
            f.flush()
            os.fsync(f.fileno())
        # atomic finalize: the manifest appears all-or-nothing, so a
        # crash mid-write can never leave a half-readable fingerprint
        os.replace(tmp, self._manifest_path(step))

    def _load_manifest(self, step: int) -> Optional[dict]:
        path = self._manifest_path(step)
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise CorruptCheckpointError(
                f"checkpoint step {step} is corrupt: unreadable integrity "
                f"manifest ({e})") from e

    def _prune_manifests(self) -> None:
        keep = {f"manifest-{s}.json" for s in self.mngr.all_steps()}
        try:
            for name in os.listdir(self.directory):
                if (name.startswith("manifest-") and name.endswith(".json")
                        and name not in keep):
                    os.unlink(os.path.join(self.directory, name))
        except OSError:
            print("checkpoint: manifest pruning failed (non-fatal); "
                  "stale manifests may accumulate", flush=True)

    def _verify(self, step: int, state: TrainState) -> None:
        """Diff the restored state against the save-time manifest."""
        man = self._load_manifest(step)
        if man is None:
            # legacy checkpoint (pre-manifest, or multi-host save):
            # nothing to verify against — restore proceeds unchecked
            return
        saved = man["arrays"]
        for key, rec in saved.items():
            if not rec.get("finite", True):
                raise CorruptCheckpointError(
                    f"checkpoint step {step} is corrupt: array {key} was "
                    f"non-finite at save time (NaN-poisoned state is not "
                    f"a valid rollback target)")
        live = _state_fingerprint(state)
        if set(live) != set(saved):
            raise CorruptCheckpointError(
                f"checkpoint step {step} is corrupt: manifest lists "
                f"{len(saved)} arrays, restore produced {len(live)}")
        for key, rec in saved.items():
            if live[key]["crc32"] != rec["crc32"]:
                raise CorruptCheckpointError(
                    f"checkpoint step {step} is corrupt: array {key} "
                    f"checksum mismatch (bit rot or partial write)")

    # ---------------------------------------------------------------- API

    def save(self, state: TrainState, batcher: Any = None,
             wait: bool = False) -> int:
        step = int(jax.device_get(state.step))
        # idempotent per step: callers overlap (periodic save + graceful
        # stop + end-of-run can all land on one step), and orbax raises
        # StepAlreadyExistsError on a duplicate. A duplicate may still be
        # in flight from the original async save — a wait=True caller is
        # asking for durability, so block on it either way.
        if step in self.mngr.all_steps():
            if wait:
                self.mngr.wait_until_finished()
            return step
        args = {"state": ocp.args.StandardSave(state)}
        if batcher is not None:
            args["data"] = ocp.args.JsonSave(batcher.state())

        def _do_save():
            f = fire("ckpt/save", index=step)
            if f is not None and f.kind == "io":
                raise OSError(f"injected transient save failure "
                              f"(step {step})")
            self.mngr.save(step, args=ocp.args.Composite(**args))

        self._with_retries(_do_save, "save", "save_retries")
        if self.integrity:
            # fingerprint the exact state handed to orbax (host fetch;
            # blocks on the state being ready — the robustness overhead
            # the BENCH artifacts track)
            self._write_manifest(step, state)
            self._prune_manifests()
        f = fire("ckpt/finalize", index=step)
        if f is not None:
            # chaos only: corrupt the finalized step the way real bit
            # rot / a crashed writer would (wait first so the files
            # exist — injected corruption must hit durable bytes)
            self.mngr.wait_until_finished()
            if f.kind == "drop_manifest":
                try:
                    os.unlink(self._manifest_path(step))
                except OSError:
                    pass
            else:
                from ..faults.inject import active
                plan = active()
                rng = (plan.rng("ckpt/finalize") if plan is not None
                       else np.random.default_rng(0))
                corrupt_step_dir(self.directory, step, f.kind, rng)
        if wait:
            self.mngr.wait_until_finished()
        return step

    def latest_step(self) -> Optional[int]:
        return self.mngr.latest_step()

    def all_steps(self):
        return self.mngr.all_steps()

    def restore(self, step: int, state_template: TrainState,
                batcher: Any = None,
                shardings: Any = None) -> TrainState:
        """Restore into the template's structure. ``shardings`` (optional
        pytree of NamedSharding matching the state) re-lays-out arrays onto
        a mesh at load time — resume on a different topology than the save.

        Raises :class:`CorruptCheckpointError` when the step fails
        integrity verification (use :meth:`restore_latest` to fall back
        past corrupt steps automatically), ``ValueError`` for a PRNG-impl
        mismatch, and the underlying ``OSError`` when transient-I/O
        retries are exhausted.
        """
        # PRNG impls have different key shapes (threefry (2,), rbg (4,)):
        # a checkpoint written under one impl cannot be resumed under
        # another, and the StandardRestore shape error is cryptic — check
        # the stored rng shape up front and say what actually went wrong
        try:
            # item_metadata warns (absl) about items it lacks restore
            # handlers for; it's only being used here to read shapes
            import logging
            absl_log = logging.getLogger("absl")
            prev_level = absl_log.level
            absl_log.setLevel(logging.ERROR)
            try:
                md = self.mngr.item_metadata(step)
            finally:
                absl_log.setLevel(prev_level)
        except (KeyError, TypeError, AttributeError, OSError, ValueError) as e:
            # metadata that ERRORS on read is a corrupt/partial step —
            # name it instead of silently skipping the RNG-impl check
            # and failing later inside orbax (the pre-PR-4 bare except
            # did exactly that)
            raise CorruptCheckpointError(
                f"checkpoint step {step} is corrupt: state metadata "
                f"unreadable ({type(e).__name__}: {e})") from e
        saved_rng = None
        if md is not None:
            # md can legitimately be None (a fresh manager instance
            # reading steps another instance wrote exposes no item
            # metadata) and its item structure varies across orbax
            # versions — when the rng record is simply absent the check
            # is unavailable, not failed; restore + manifest
            # verification still gate the actual payload
            try:
                state_md = md["state"]
                saved_rng = None if state_md is None else state_md["rng"]
            except (KeyError, TypeError, AttributeError):
                saved_rng = None
        if (saved_rng is not None and hasattr(saved_rng, "shape")
                and tuple(saved_rng.shape)
                != tuple(state_template.rng.shape)):
            raise ValueError(
                f"checkpoint step {step} stores an rng key of shape "
                f"{tuple(saved_rng.shape)} but this run uses "
                f"{tuple(state_template.rng.shape)} — it was written under "
                f"a different PRNG impl; rerun with the matching --rng-impl")
        target = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            state_template)
        if shardings is not None:
            target = jax.tree_util.tree_map(
                lambda t, s: jax.ShapeDtypeStruct(t.shape, t.dtype,
                                                  sharding=s),
                target, shardings)
        args = {"state": ocp.args.StandardRestore(target)}
        if batcher is not None:
            args["data"] = ocp.args.JsonRestore()

        def _do_restore():
            f = fire("ckpt/restore", index=step)
            if f is not None and f.kind == "io":
                raise OSError(f"injected transient restore failure "
                              f"(step {step})")
            return self.mngr.restore(step, args=ocp.args.Composite(**args))

        try:
            out = self._with_retries(_do_restore, "restore",
                                     "restore_retries")
        except OSError:
            raise               # transient path exhausted — caller's call
        except Exception as e:
            # orbax failing on a step whose metadata read fine means the
            # array payload is partial/corrupt — classify it so
            # restore_latest can fall back past it
            raise CorruptCheckpointError(
                f"checkpoint step {step} is corrupt: restore failed "
                f"({type(e).__name__}: {e})") from e
        if self.integrity:
            self._verify(step, out["state"])
        if batcher is not None and out.get("data") is not None:
            batcher.restore(out["data"])
        return out["state"]

    def restore_latest(self, state_template: TrainState, batcher: Any = None,
                       shardings: Any = None) -> Optional[TrainState]:
        """Newest checkpoint that passes integrity verification, falling
        back past corrupt/partial steps (``recovery['ckpt_fallbacks']``
        counts the skips). None means NO checkpoints exist (a fresh
        run); checkpoints that exist but ALL fail verification raise
        :class:`CorruptCheckpointError` — a resume caller treating that
        as "no checkpoint" would silently restart from step 0 and
        destroy the run it was asked to continue."""
        steps = sorted(self.mngr.all_steps(), reverse=True)
        if not steps:
            return None
        last_err: Optional[Exception] = None
        for i, step in enumerate(steps):
            try:
                return self.restore(step, state_template, batcher, shardings)
            except (CorruptCheckpointError, OSError) as e:
                last_err = e
                self.recovery["ckpt_fallbacks"] += 1
                nxt = steps[i + 1] if i + 1 < len(steps) else None
                print(f"checkpoint restore_latest: {e} — "
                      + (f"falling back to step {nxt}" if nxt is not None
                         else "no earlier step to fall back to"),
                      flush=True)
        raise CorruptCheckpointError(
            f"no restorable checkpoint under {self.directory}: all "
            f"{len(steps)} step(s) {steps} failed verification "
            f"(last: {last_err})") from last_err

    def wait(self) -> None:
        self.mngr.wait_until_finished()

    def close(self) -> None:
        self.mngr.close()
