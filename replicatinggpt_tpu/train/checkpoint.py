"""Checkpoint save/restore: full training state, resumable, sharding-aware.

The reference saves only the final model state_dict (GPT1.py:239-241) and
has no load path at all (SURVEY.md §5) — a crash loses the run. Here a
checkpoint is the complete resume state named in SURVEY.md §5:

    {params, optimizer state, step, dropout RNG key, data-loader cursor}

backed by orbax (async-capable, sharded-array aware: each host writes its
own shards; restore can re-lay-out onto any mesh via abstract targets).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from .state import TrainState


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 create=True))

    def save(self, state: TrainState, batcher: Any = None,
             wait: bool = False) -> int:
        step = int(jax.device_get(state.step))
        # idempotent per step: callers overlap (periodic save + graceful
        # stop + end-of-run can all land on one step), and orbax raises
        # StepAlreadyExistsError on a duplicate. A duplicate may still be
        # in flight from the original async save — a wait=True caller is
        # asking for durability, so block on it either way.
        if step in self.mngr.all_steps():
            if wait:
                self.mngr.wait_until_finished()
            return step
        args = {"state": ocp.args.StandardSave(state)}
        if batcher is not None:
            args["data"] = ocp.args.JsonSave(batcher.state())
        self.mngr.save(step, args=ocp.args.Composite(**args))
        if wait:
            self.mngr.wait_until_finished()
        return step

    def latest_step(self) -> Optional[int]:
        return self.mngr.latest_step()

    def restore(self, step: int, state_template: TrainState,
                batcher: Any = None,
                shardings: Any = None) -> TrainState:
        """Restore into the template's structure. ``shardings`` (optional
        pytree of NamedSharding matching the state) re-lays-out arrays onto
        a mesh at load time — resume on a different topology than the save.
        """
        # PRNG impls have different key shapes (threefry (2,), rbg (4,)):
        # a checkpoint written under one impl cannot be resumed under
        # another, and the StandardRestore shape error is cryptic — check
        # the stored rng shape up front and say what actually went wrong
        try:
            # item_metadata warns (absl) about items it lacks restore
            # handlers for; it's only being used here to read shapes
            import logging
            absl_log = logging.getLogger("absl")
            prev_level = absl_log.level
            absl_log.setLevel(logging.ERROR)
            try:
                saved_rng = self.mngr.item_metadata(step)["state"]["rng"]
            finally:
                absl_log.setLevel(prev_level)
        except Exception:
            saved_rng = None
        if (saved_rng is not None and hasattr(saved_rng, "shape")
                and tuple(saved_rng.shape)
                != tuple(state_template.rng.shape)):
            raise ValueError(
                f"checkpoint step {step} stores an rng key of shape "
                f"{tuple(saved_rng.shape)} but this run uses "
                f"{tuple(state_template.rng.shape)} — it was written under "
                f"a different PRNG impl; rerun with the matching --rng-impl")
        target = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            state_template)
        if shardings is not None:
            target = jax.tree_util.tree_map(
                lambda t, s: jax.ShapeDtypeStruct(t.shape, t.dtype,
                                                  sharding=s),
                target, shardings)
        args = {"state": ocp.args.StandardRestore(target)}
        if batcher is not None:
            args["data"] = ocp.args.JsonRestore()
        out = self.mngr.restore(step, args=ocp.args.Composite(**args))
        if batcher is not None and out.get("data") is not None:
            batcher.restore(out["data"])
        return out["state"]

    def restore_latest(self, state_template: TrainState, batcher: Any = None,
                       shardings: Any = None) -> Optional[TrainState]:
        step = self.latest_step()
        if step is None:
            return None
        return self.restore(step, state_template, batcher, shardings)

    def wait(self) -> None:
        self.mngr.wait_until_finished()

    def close(self) -> None:
        self.mngr.close()
