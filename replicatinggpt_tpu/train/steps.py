"""Jitted train / eval steps.

One compiled ``train_step(state, batch) -> (state, metrics)`` replaces the
reference's eager zero_grad/forward/backward/step sequence (GPT1.py:227-233,
GPT-2.py:223-228); a jitted K-batch eval replaces ``estimate_loss``
(GPT1.py:85-98) — same semantics (dropout off, mean over eval_iters fresh
batches per split) but compiled, so the 400-forwards-per-eval cost
(SURVEY.md §3.3) stops dominating wall-clock.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp

from ..config import ModelConfig, TrainConfig
from ..models.gpt import forward
from .state import TrainState, make_optimizer


def loss_fn(params, batch, cfg: ModelConfig, rng=None, train=False,
            attention_fn=None, blocks_fn=None):
    x, y = batch
    # tokens may arrive as uint8/uint16 (narrow host->device transfers —
    # the loaders pick the smallest dtype covering the vocab); widen on
    # device where the cast is free
    if x.dtype != jnp.int32:
        x = x.astype(jnp.int32)
    if y.dtype != jnp.int32:
        y = y.astype(jnp.int32)
    _, loss = forward(params, x, cfg, targets=y, rng=rng, train=train,
                      attention_fn=attention_fn, blocks_fn=blocks_fn)
    return loss


def _accum_grads(params, batch, *, mcfg: ModelConfig, rng, train,
                 attention_fn, blocks_fn, accum: int):
    """Mean loss/grads over ``accum`` stacked microbatches (each array of
    ``batch`` is (accum, b, T)) via an on-device ``lax.scan`` — one
    microbatch's activations live at a time, so the effective batch
    ``accum * b`` costs single-microbatch activation memory. Equal-sized
    microbatches make the mean-of-means identical to the full-batch mean."""
    vg = jax.value_and_grad(loss_fn)

    def body(carry, xs):
        loss_sum, gsum = carry
        mb, j = xs
        loss, g = vg(params, mb, mcfg,
                     rng=None if rng is None else jax.random.fold_in(rng, j),
                     train=train, attention_fn=attention_fn,
                     blocks_fn=blocks_fn)
        return (loss_sum + loss,
                jax.tree_util.tree_map(jnp.add, gsum, g)), None

    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    (loss_sum, gsum), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), zeros),
        (batch, jnp.arange(accum)), length=accum)
    inv = 1.0 / accum
    return (loss_sum * inv,
            jax.tree_util.tree_map(lambda g: g * inv, gsum))


def _one_step(state: TrainState, batch, *, mcfg: ModelConfig, optimizer,
              with_grad_norm: bool, attention_fn, blocks_fn, accum: int = 1
              ) -> Tuple[TrainState, Dict[str, Any]]:
    """The single optimizer step shared by make_train_step (jitted 1:1) and
    make_train_scan (scanned K:1) — one body, so the two dispatch shapes
    cannot drift apart semantically."""
    rng = jax.random.fold_in(state.rng, state.step)
    train = mcfg.dropout > 0 or mcfg.attn_dropout > 0
    if accum > 1:
        loss, grads = _accum_grads(
            state.params, batch, mcfg=mcfg, rng=rng if train else None,
            train=train, attention_fn=attention_fn, blocks_fn=blocks_fn,
            accum=accum)
    else:
        loss, grads = jax.value_and_grad(loss_fn)(
            state.params, batch, mcfg, rng=rng, train=train,
            attention_fn=attention_fn, blocks_fn=blocks_fn)
    updates, opt_state = optimizer.update(grads, state.opt_state,
                                          state.params)
    params = jax.tree_util.tree_map(
        lambda p, u: (p + u.astype(p.dtype)), state.params, updates)
    new_state = TrainState(step=state.step + 1, params=params,
                           opt_state=opt_state, rng=state.rng)
    metrics = {"loss": loss}
    if with_grad_norm:
        metrics["grad_norm"] = jax.tree_util.tree_reduce(
            lambda a, g: a + jnp.sum(jnp.square(g.astype(jnp.float32))),
            grads, jnp.float32(0.0)) ** 0.5
    return new_state, metrics


def make_train_step(mcfg: ModelConfig, tcfg: TrainConfig,
                    donate: bool = True,
                    with_grad_norm: bool = False,
                    attention_fn=None, blocks_fn=None) -> Callable:
    """Build the jitted train step. Sharded execution comes from the
    shardings already attached to ``state``/``batch`` arrays (GSPMD); this
    function is mesh-agnostic. ``with_grad_norm`` adds a tree-wide grad-norm
    reduction to the metrics (off by default — it costs a full-tree
    reduction per step). ``attention_fn`` overrides the attention core —
    the sequence-parallel paths (ring / Ulysses) plug in here.

    With ``tcfg.grad_accum_steps > 1`` the batch arrays are stacked
    ``(accum, batch_size, T)`` microbatches (host-assembled like the K-step
    superbatch, sharded P(None,'data','seq') on mesh runs)."""
    step = partial(_one_step, mcfg=mcfg, optimizer=make_optimizer(tcfg),
                   with_grad_norm=with_grad_norm, attention_fn=attention_fn,
                   blocks_fn=blocks_fn, accum=tcfg.grad_accum_steps)
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_train_scan(mcfg: ModelConfig, tcfg: TrainConfig, k: int,
                    donate: bool = True,
                    with_grad_norm: bool = False,
                    attention_fn=None, blocks_fn=None) -> Callable:
    """K train steps per dispatch: ``(state, (K,B,T) batches) -> (state,
    {'loss': (K,), ...})`` with an on-device ``lax.scan`` over the steps;
    metrics come back stacked, one entry per step.

    Why this exists: a single-step dispatch pays one host->device round trip
    per optimizer step, which on a remote/tunneled TPU (or any small model
    whose step time is comparable to dispatch latency) can dominate
    wall-clock. Scanning K steps on device amortizes that overhead to 1/K
    and lets the host assemble the next superbatch while the chip runs.
    Shares ``_one_step`` with ``make_train_step`` (same per-step RNG fold on
    ``state.step``), so loss curves are unchanged — asserted in
    tests/test_train.py::test_train_scan_matches_single_steps."""
    one = partial(_one_step, mcfg=mcfg, optimizer=make_optimizer(tcfg),
                  with_grad_norm=with_grad_norm, attention_fn=attention_fn,
                  blocks_fn=blocks_fn, accum=tcfg.grad_accum_steps)

    def run(state: TrainState, batches) -> Tuple[TrainState, Dict[str, Any]]:
        xs, ys = batches  # (K, B, T) each; (K, accum, B, T) under accumulation
        return jax.lax.scan(lambda s, b: one(s, b), state, (xs, ys),
                            length=k)

    return jax.jit(run, donate_argnums=(0,) if donate else ())


def make_eval_step(mcfg: ModelConfig, attention_fn=None,
                   blocks_fn=None) -> Callable:
    """Jitted single-batch eval loss (dropout off — GPT1.py:88 model.eval)."""

    @jax.jit
    def eval_step(params, batch) -> jnp.ndarray:
        return loss_fn(params, batch, mcfg, rng=None, train=False,
                       attention_fn=attention_fn, blocks_fn=blocks_fn)

    return eval_step


def make_eval_scan(mcfg: ModelConfig, attention_fn=None,
                   blocks_fn=None) -> Callable:
    """Jitted K-batch eval: ``(params, (K,B,T) xs/ys) -> (K,) losses`` via
    an on-device ``lax.scan`` — the whole estimate_loss pass in one
    dispatch per split instead of eval_iters of them (each dispatch costs
    ~30 ms over a tunneled TPU; the reference's eval is 400 separate
    forwards, SURVEY.md §3.3)."""

    @jax.jit
    def eval_scan(params, batches) -> jnp.ndarray:
        def body(carry, b):
            return carry, loss_fn(params, b, mcfg, rng=None, train=False,
                                  attention_fn=attention_fn,
                                  blocks_fn=blocks_fn)
        _, losses = jax.lax.scan(body, None, batches)
        return losses

    return eval_scan


def estimate_loss(params, batchers: Dict[str, Any], eval_step: Callable,
                  eval_iters: int, device_put: Callable = None,
                  eval_scan: Callable = None,
                  superbatch_put: Callable = None) -> Dict[str, float]:
    """Mean loss over ``eval_iters`` fresh batches for each split —
    ``estimate_loss`` semantics (GPT1.py:85-98), including the quirk that
    'train' loss is itself a random K-batch sample (SURVEY.md §8-Q8).

    With ``eval_scan`` (from :func:`make_eval_scan`), each split is one
    stacked dispatch; identical batches and per-batch losses either way
    (tests/test_train.py::test_estimate_loss_scan_matches_loop). Sharded
    runs pass ``superbatch_put`` to place the stacked (K, B, T) arrays with
    the P(None,'data','seq') superbatch sharding (multi-host: per-process
    rows assembled via make_array_from_process_local_data)."""
    import numpy as np
    out = {}
    if eval_scan is not None and superbatch_put is None:
        assert device_put is None or device_put is jax.device_put, (
            "eval_scan on a sharded run needs superbatch_put to keep the "
            "batch sharding on the stacked (K,B,T) arrays")
    for split, batcher in batchers.items():
        if eval_scan is not None:
            xs, ys = zip(*(batcher.next_batch()
                           for _ in range(eval_iters)))
            stacked = (np.stack(xs), np.stack(ys))
            if superbatch_put is not None:
                stacked = tuple(superbatch_put(a) for a in stacked)
            losses = eval_scan(params, stacked)
            # one fetch per split is the contract:
            out[split] = float(jnp.mean(losses))  # graftlint: disable=GL004
        else:
            total = None
            for _ in range(eval_iters):
                xb, yb = batcher.next_batch()
                if device_put is not None:
                    xb, yb = device_put(xb), device_put(yb)
                # accumulate ON DEVICE — float() here would force a
                # device round-trip per eval batch (the host stall
                # graftlint GL004 exists for; eval_iters syncs/split
                # measured as the dominant eval cost over a tunneled
                # TPU before eval_scan existed)
                loss = eval_step(params, (xb, yb))
                total = loss if total is None else total + loss
            # one fetch per split is the contract:
            out[split] = float(total) / eval_iters  # graftlint: disable=GL004
    return out
