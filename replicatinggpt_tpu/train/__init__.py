from .state import TrainState, create_train_state, make_optimizer
from .steps import make_train_step, make_eval_step, estimate_loss

__all__ = ["TrainState", "create_train_state", "make_optimizer",
           "make_train_step", "make_eval_step", "estimate_loss"]
