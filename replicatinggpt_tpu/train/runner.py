"""End-to-end training runner: the framework's L5/L6 (SURVEY.md §1).

Drives the full reference pipeline — corpus → tokenize → split → train loop
with periodic train/val eval → sample → checkpoint (GPT1.py:215-241) — on
top of the jitted steps, with optional mesh sharding, async device prefetch,
structured logging, and resumable checkpoints.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..config import Config
from ..data.dataset import TokenDataset, load_corpus
from ..data.loader import make_batcher, prefetch
from ..faults.inject import (apply_loss_fault, apply_train_state_fault,
                             fire as fault_fire)
from ..faults.supervise import (LossTracker, NonFiniteLossError,
                                SupervisionConfig)
from ..models.gpt import param_count
from ..tokenizers import get_tokenizer
from ..utils.logging import StepLogger
from ..utils.sanitize import (CompileGuard, check_finite, sanitize_enabled,
                              sanitized)
from ..utils.telemetry import ENGINE_TRACK, NULL
from .state import TrainState, create_train_state
from .steps import estimate_loss, make_eval_step, make_train_step


@dataclass
class TrainResult:
    state: TrainState
    history: list          # [(step, train_loss, val_loss)]
    final_eval: Dict[str, float]
    tokenizer: Any
    tokens_per_sec_per_chip: float


def _make_lr_reader(tcfg):
    """step -> learning rate for the log line, or None when the schedule
    is a bare constant with no warmup (the reference's fixed-lr loop,
    GPT1.py:218 — an lr column there would be noise). Any real schedule
    (cosine, or constant with warmup) logs its current value. Built once
    per run: the schedule closure is reconstructed here, not per log
    boundary."""
    from .state import lr_schedule_fn
    sched = lr_schedule_fn(tcfg)
    if not callable(sched):
        return lambda step: None
    return lambda step: float(sched(step))


def _resolve_vocab(cfg: Config, tokenizer) -> Config:
    """Make model vocab consistent with the tokenizer (fixes SURVEY.md
    §8-B1/B5, where reference vocab/tokenizer mismatches crashed training).
    Keeps a configured vocab that is >= tokenizer vocab (padded vocabs like
    50304 are MXU-friendlier than 50257)."""
    v = tokenizer.vocab_size
    if cfg.model.vocab_size < v:
        import dataclasses as dc
        cfg = cfg.replace(model=dc.replace(cfg.model, vocab_size=v))
    return cfg


def train(cfg: Config, *, mesh=None, logger: Optional[StepLogger] = None,
          checkpoint_manager=None, resume: bool = False,
          profile_dir: Optional[str] = None,
          profile_start: int = 10, profile_steps: int = 5,
          stop_event=None,
          supervision: Optional[SupervisionConfig] = None,
          skip_data_steps: int = 0, telemetry=None) -> TrainResult:
    """``stop_event`` (a ``threading.Event``-like object) requests a
    graceful stop: the loop finishes the in-flight dispatch, saves a
    checkpoint (when a manager is present), and returns normally — the
    preemption story for TPU VMs, where SIGTERM precedes eviction (the
    CLI wires this to SIGTERM/SIGINT; the reference loses the entire run,
    SURVEY.md §5 failure-detection row).

    ``supervision`` (a :class:`~replicatinggpt_tpu.faults.supervise.
    SupervisionConfig`) turns on per-dispatch loss checks: a non-finite
    or spiking loss raises a typed error that
    ``faults.supervise.supervised_train`` converts into a rollback to
    the last verified checkpoint — each check is one host sync, the
    price of detection latency. ``skip_data_steps`` (supervisor-driven)
    advances the data cursor that many optimizer steps after restore,
    stepping past a data window that keeps blowing the loss up.

    ``telemetry`` (utils.telemetry.Telemetry) records the training
    timeline: one span per dispatch (host dispatch time — the device
    runs async; pair with ``profile_dir`` for the device-side view),
    spans around eval passes, and instants at checkpoint saves — the
    host half of a step-time attribution, exportable to Perfetto next
    to the ``jax.profiler`` capture. None means the zero-cost NULL
    recorder."""
    logger = logger or StepLogger()
    tel = telemetry or NULL
    text = load_corpus(cfg.dataset)
    tokenizer = get_tokenizer(cfg.tokenizer, corpus_text=text,
                              cache_dir=os.path.dirname(cfg.dataset) or ".")
    cfg = _resolve_vocab(cfg, tokenizer)
    mcfg, tcfg = cfg.model, cfg.train

    ds = TokenDataset.from_text(text, tokenizer, tcfg.val_fraction)
    logger.log(f"dataset: {len(ds.train):,} train / {len(ds.val):,} val "
               f"tokens, vocab {tokenizer.vocab_size}")

    # Multi-host: each process assembles only its slice of the global batch
    # (rows land in the global array via make_array_from_process_local_data
    # in the prefetch producer). Single-process: local == global, seeds
    # untouched so the reference-seeded run is bit-stable.
    n_proc = jax.process_count()
    seed = tcfg.seed
    proc = 0
    if n_proc > 1:
        from ..parallel.distributed import (is_coordinator,
                                            local_batch_slice,
                                            per_process_seed)
        sl = local_batch_slice(tcfg.batch_size)
        local_bs = sl.stop - sl.start
        seed = per_process_seed(tcfg.seed)
        proc = jax.process_index()
        # the batch's 'data' dim must split along process boundaries for
        # make_array_from_process_local_data to assemble per-host rows
        assert cfg.mesh.data % n_proc == 0, (
            f"multi-host runs need the 'data' mesh axis ({cfg.mesh.data}) "
            f"to span the {n_proc} processes")
        logger.quiet = not is_coordinator()
    else:
        local_bs = tcfg.batch_size

    train_batcher = make_batcher(tcfg.sampling, ds.train, local_bs,
                                 mcfg.block_size, seed=seed,
                                 shard=(proc, n_proc))
    eval_batchers = {
        "train": make_batcher("random", ds.train, local_bs,
                              mcfg.block_size, seed=seed + 1),
        "val": make_batcher("random", ds.val, local_bs,
                            mcfg.block_size, seed=seed + 2),
    }

    rng = jax.random.PRNGKey(tcfg.seed)
    batch_sharding = None
    n_chips = 1
    if mesh is not None:
        from ..parallel.mesh import make_batch_sharding, shard_train_state
        batch_sharding = make_batch_sharding(mesh)
        n_chips = mesh.size
        state = shard_train_state(
            lambda: create_train_state(rng, mcfg, tcfg), mesh, cfg.mesh)
    else:
        # commit the fresh state to an explicit device: jit keys on
        # placement, and an uncommitted initial state whose successor
        # comes back committed can split the cache into a throwaway
        # first program (the serve engine's commit_default rationale —
        # and the train CompileGuard below would flag it as a
        # recompile)
        state = jax.device_put(
            create_train_state(rng, mcfg, tcfg),
            jax.config.jax_default_device or jax.local_devices()[0])
    logger.log(f"model: {param_count(state.params):,} params "
               f"({mcfg.n_layer}L/{mcfg.n_head}H/{mcfg.n_embd}C, "
               f"dtype={mcfg.dtype})")

    attention_fn = blocks_fn = None
    if mesh is not None:
        from ..parallel import select_attention_fn, select_blocks_fn
        blocks_fn = select_blocks_fn(mcfg, cfg.mesh, mesh)
        if blocks_fn is not None:
            logger.log(f"pipeline parallelism: {cfg.mesh.pipe} stages, "
                       f"{cfg.mesh.microbatches or 2 * cfg.mesh.pipe} "
                       f"microbatches")
        else:
            attention_fn = select_attention_fn(mcfg, cfg.mesh, mesh)
            if attention_fn is not None:
                # impl_name may differ from the configured impl ('auto'
                # or explicit 'flash' route to ring/ulysses on a seq
                # mesh; DP/FSDP/TP meshes get the shard_map wrapper)
                resolved = getattr(attention_fn, "impl_name",
                                   mcfg.attention_impl)
                if cfg.mesh.seq > 1:
                    logger.log(f"sequence parallelism: seq axis "
                               f"{cfg.mesh.seq}, impl {resolved!r} "
                               f"(configured {mcfg.attention_impl!r})")
                else:
                    axes = [a for a, n in (("data", cfg.mesh.data),
                                           ("model", cfg.mesh.model))
                            if n > 1] or ["data"]
                    on_tpu = jax.default_backend() == "tpu"
                    logger.log(f"mesh attention: {resolved!r} shard_map "
                               f"wrapper over {tuple(axes)}; local core "
                               + ("Pallas flash (SDPA/einsum off the "
                                  "kernel envelope)" if on_tpu
                                  else "SDPA/einsum (non-TPU backend)"))
    if (mesh is not None
            and mcfg.attention_impl in ("auto", "ring", "ulysses")
            and attention_fn is None and blocks_fn is None):
        # No shard_map wrapper claimed the attention ('auto' off-TPU, at
        # sub-crossover T, or with heads indivisible by the 'model'
        # axis) — pin the local core to einsum so 'auto' can never
        # resolve to a bare pallas_call inside the sharded jit program
        # (the kernel has no GSPMD partitioning rule). Explicit 'flash'
        # never reaches here: on an active mesh select_attention_fn
        # always returns a wrapper for it (shard_map or seq-parallel).
        import dataclasses as dc
        prev_impl = mcfg.attention_impl
        mcfg = dc.replace(mcfg, attention_impl="einsum")
        logger.log(f"attention_impl {prev_impl!r} -> 'einsum': mesh run "
                   "where the shard_map flash wrapper does not apply")
    # steady-state contract, same as the serve engine's: ONE compiled
    # program per dispatch shape; a silent mid-run recompile (shape /
    # weak-type / placement drift) raises RecompileError naming the
    # step instead of quietly halving throughput
    train_step = CompileGuard(
        make_train_step(mcfg, tcfg, attention_fn=attention_fn,
                        blocks_fn=blocks_fn), "train/step")
    super_sharding = None
    superbatch_put = None
    if mesh is not None:
        from ..parallel.distributed import global_batch
        from ..parallel.mesh import make_superbatch_sharding
        super_sharding = make_superbatch_sharding(mesh)
        superbatch_put = (lambda a: global_batch(a, super_sharding,
                                                 batch_axis=1))
    # the whole eval pass rides one stacked dispatch per split; sharded runs
    # keep the batch sharding via the P(None,'data','seq') superbatch layout
    from .steps import make_eval_scan
    eval_scan = make_eval_scan(mcfg, attention_fn=attention_fn,
                               blocks_fn=blocks_fn)
    train_scan = None
    scan_k = 1
    if tcfg.steps_per_dispatch > 1:
        # Chunks never cross an eval/checkpoint boundary, so a dispatch
        # larger than those cadences could never run — clamp it. (Log
        # cadence does NOT clamp: log lines inside a chunk are emitted
        # from the stacked per-step losses after it completes.)
        scan_k = tcfg.steps_per_dispatch
        for interval in (tcfg.eval_interval, tcfg.checkpoint_every):
            if interval:
                scan_k = min(scan_k, interval)
        if scan_k != tcfg.steps_per_dispatch:
            logger.log(f"steps_per_dispatch clamped "
                       f"{tcfg.steps_per_dispatch} -> {scan_k} to fit the "
                       f"eval/checkpoint cadence")
        if scan_k > 1:
            from .steps import make_train_scan
            train_scan = CompileGuard(
                make_train_scan(mcfg, tcfg, scan_k,
                                attention_fn=attention_fn,
                                blocks_fn=blocks_fn), "train/scan")
        else:
            scan_k = 1
    eval_step = make_eval_step(mcfg, attention_fn=attention_fn,
                               blocks_fn=blocks_fn)
    if batch_sharding is not None:
        from ..parallel.distributed import global_batch
        dput = (lambda a: global_batch(a, batch_sharding))
    else:
        dput = jax.device_put

    start_step = 0
    if checkpoint_manager is not None and resume:
        # Random-sampling batcher state is a host-local RNG; restoring the
        # (single, primary-host) saved copy onto every host would collapse
        # the per-process decorrelation. The sequential cursor is global
        # state and restores safely on any host count.
        restore_batcher = (train_batcher
                           if (n_proc == 1 or tcfg.sampling == "sequential")
                           else None)
        if restore_batcher is None:
            logger.log("multi-host resume: random-batcher RNG state not "
                       "restored; streams re-seeded per process")
        # restore straight into the mesh layout: every leaf of the live
        # state already carries its NamedSharding (shard_train_state), so
        # orbax lays each array out shard-by-shard — an FSDP-sized model
        # never materializes replicated (which would blow HBM)
        restore_shardings = None
        if mesh is not None:
            restore_shardings = jax.tree_util.tree_map(
                lambda x: x.sharding, state)
        restored = checkpoint_manager.restore_latest(
            state, restore_batcher, shardings=restore_shardings)
        if restored is not None:
            state = restored
            start_step = int(jax.device_get(state.step))
            logger.log(f"resumed from step {start_step}")

    history = []
    accum = max(tcfg.grad_accum_steps, 1)
    if accum > 1:
        logger.log(f"gradient accumulation: {accum} x {tcfg.batch_size} "
                   f"rows/optimizer step "
                   f"(effective batch {accum * tcfg.batch_size})")
    tokens_per_batch = tcfg.batch_size * mcfg.block_size * accum
    # ship tokens in the smallest dtype covering the vocab (2-4x less H2D
    # traffic); the jitted steps widen to int32 on device (steps.loss_fn)
    wire = (np.uint8 if mcfg.vocab_size <= 0xff
            else np.uint16 if mcfg.vocab_size <= 0xffff else np.int32)
    narrow = ((x.astype(wire), y.astype(wire))
              for x, y in iter(train_batcher))
    if skip_data_steps:
        # supervisor-directed recovery: the same data window blew the
        # loss up twice — draw and discard whole optimizer steps so the
        # resumed run trains past it (the cursor snapshot feed() saves
        # reflects the advanced position)
        for _ in range(skip_data_steps * accum):
            next(narrow)
        logger.log(f"supervisor: data cursor advanced {skip_data_steps} "
                   f"optimizer step(s) past the offending window")

    def chunk_at(i: int) -> int:
        """Steps the dispatch issued at iteration ``i`` advances: scan_k,
        or 1 when an eval/checkpoint/max_iters boundary is closer. Pure in
        ``i``, so the feed producer below and the consuming loop walk the
        same schedule independently."""
        if train_scan is None:
            return 1
        room = tcfg.max_iters - i
        for interval in (tcfg.eval_interval, tcfg.checkpoint_every):
            if interval:
                room = min(room, interval - i % interval)
        return scan_k if room >= scan_k else 1

    def feed():
        # host-side assembly of exactly what each dispatch consumes: a
        # (B, T) batch, or a host-stacked (K, B, T) superbatch for scan
        # dispatches (prefetch shards 3-d items with P(None,'data','seq'),
        # so mesh runs keep their batch sharding through the scan).
        # Each item carries the batcher-state snapshot taken right after
        # its batches were drawn: the prefetch producer runs ahead of the
        # consumed step, so a mid-run checkpoint must save the cursor
        # as-of-consumption, not the live (raced-ahead) batcher state.
        def draw_step():
            # one optimizer step's batch: (B, T), or stacked (accum, B, T)
            # microbatches under gradient accumulation
            if accum == 1:
                return next(narrow)
            xs, ys = zip(*(next(narrow) for _ in range(accum)))
            return np.stack(xs), np.stack(ys)

        i = start_step
        while i < tcfg.max_iters:
            c = chunk_at(i)
            if c > 1:
                xs, ys = zip(*(draw_step() for _ in range(c)))
                item = (np.stack(xs), np.stack(ys))
            else:
                item = draw_step()
            yield (*item, train_batcher.state())
            i += c

    class _ConsumedCursor:
        """Batcher-shaped view holding the snapshot matching the consumed
        step — what checkpoints must persist (see feed())."""

        def __init__(self, snap):
            self.snap = snap

        def state(self):
            return self.snap

    cursor = _ConsumedCursor(train_batcher.state())
    batches_raw = prefetch(feed(), sharding=batch_sharding)

    def batches_iter():
        for *batch, snap in batches_raw:
            cursor.snap = snap
            yield tuple(batch)

    batches = batches_iter()
    import time

    from ..utils.profiling import trace_window
    if profile_dir and start_step + profile_start >= tcfg.max_iters:
        # clamp so a short/resumed run still produces the promised trace
        profile_start = max(tcfg.max_iters - start_step - profile_steps, 0)
    profiler = trace_window(profile_dir, start=start_step + profile_start,
                            n_steps=profile_steps)
    if profile_dir:
        logger.log(f"profiling steps {start_step + profile_start}.."
                   f"{start_step + profile_start + profile_steps} "
                   f"-> {profile_dir}")
    t0 = time.perf_counter()
    tokens_seen = 0
    logger.reset_timer()
    def _stop_requested(it: int) -> bool:
        if stop_event is None:
            return False
        if n_proc == 1:
            return stop_event.is_set()
        # Multi-host: signal delivery is skewed across hosts, and acting on
        # a process-local flag would have hosts leave the loop at different
        # iterations — the collective checkpoint save then deadlocks. Agree
        # on the coordinator's flag, but only at checkpoint boundaries (a
        # blocking host collective per step would throttle the loop); with
        # no checkpoint cadence there is nothing durable to gain by
        # stopping early, so the signal is ignored (logged at setup).
        if (tcfg.checkpoint_every and it > start_step
                and it % tcfg.checkpoint_every == 0):
            from jax.experimental import multihost_utils
            return bool(multihost_utils.broadcast_one_to_all(
                np.int32(stop_event.is_set())))
        return False

    if stop_event is not None and n_proc > 1 and not tcfg.checkpoint_every:
        logger.log("note: graceful stop disabled (multi-host run without "
                   "checkpoint_every; no agreed boundary to stop at)")

    tokens_since_log = 0
    lr_at = _make_lr_reader(tcfg)
    stopped_early = False
    tracker = None
    n_dispatches = 0
    if supervision is not None:
        tracker = LossTracker(supervision)
        logger.log(f"supervision: loss checked every "
                   f"{supervision.check_every} dispatch(es)"
                   + (f", spike budget {supervision.spike_factor:.1f}x EMA"
                      if supervision.spike_factor else ""))
    import contextlib
    sanitizer = contextlib.ExitStack()
    if sanitize_enabled():
        # GRAFT_SANITIZE=1: jax tracer-leak + NaN checks for the whole
        # loop, host finiteness check on every logged loss (below) —
        # debug equipment, off by default (costs compile time/fusions)
        logger.log("GRAFT_SANITIZE=1: tracer-leak + NaN checks enabled")
        sanitizer.enter_context(sanitized(True))
    try:
        it = start_step
        while it < tcfg.max_iters:
            # chaos seam (no-op without an installed FaultPlan): raises
            # SIGTERM through the real handler, or corrupts the live
            # state — the faults the supervision layer must survive
            flt = fault_fire("train/step", index=it)
            if flt is not None:
                state = apply_train_state_fault(flt, state)
            if _stop_requested(it):
                stopped_early = True
                logger.log(f"stop requested at step {it}; "
                           "checkpointing and exiting")
                if checkpoint_manager is not None:
                    checkpoint_manager.save(state, cursor)
                break
            if (tcfg.eval_interval and it % tcfg.eval_interval == 0):
                with tel.span("train/eval", step=it):
                    losses = estimate_loss(state.params, eval_batchers,
                                           eval_step, tcfg.eval_iters,
                                           device_put=dput,
                                           eval_scan=eval_scan,
                                           superbatch_put=superbatch_put)
                logger.log_eval(it, losses["train"], losses["val"])
                history.append((it, losses["train"], losses["val"]))
                logger.reset_timer()
            # after the eval block so the trace captures train steps only
            profiler.step(it)
            # a chunk never crosses an eval/checkpoint boundary, so those
            # cadences behave exactly as in the single-step loop; the feed
            # producer assembled this dispatch's batch to the same schedule
            chunk = chunk_at(it)
            t_disp_us = tel.now_us() if tel.enabled else 0.0
            if chunk > 1:
                state, metrics = train_scan(state, next(batches))
            else:
                state, metrics = train_step(state, next(batches))
            if tel.enabled:
                # host dispatch time only: the device runs this chunk
                # asynchronously (profile_dir's XLA capture carries the
                # device-side cost; annotate-linked via span names)
                tel.complete("train/dispatch", ENGINE_TRACK, t_disp_us,
                             tel.now_us() - t_disp_us, step=it,
                             chunk=chunk)
            prev_it, it = it, it + chunk
            tokens_seen += tokens_per_batch * chunk
            tokens_since_log += tokens_per_batch * chunk
            n_dispatches += 1
            if (tracker is not None
                    and n_dispatches % supervision.check_every == 0):
                losses_arr = metrics["loss"]
                # one reviewed sync per supervised dispatch — detection
                # latency is what supervision buys with it
                sup_loss = float(losses_arr if chunk == 1    # graftlint: disable=GL004
                                 else losses_arr[-1])
                flt = fault_fire("train/loss", index=it - 1)
                if flt is not None:
                    sup_loss = apply_loss_fault(flt, sup_loss)
                tracker.check(it - 1, sup_loss)
            if tcfg.log_interval:
                # most recent log boundary crossed by this chunk (one line
                # per chunk even if it spans several boundaries)
                b = (it // tcfg.log_interval) * tcfg.log_interval
                if b > prev_it:
                    losses_arr = metrics["loss"]
                    loss_b = (losses_arr if chunk == 1
                              else losses_arr[b - prev_it - 1])
                    # one reviewed sync per LOG boundary, not per step;
                    # the fetch is also the NaN tripwire under sanitize
                    loss_val = float(loss_b)  # graftlint: disable=GL004
                    if sanitize_enabled():
                        check_finite(loss_val, f"train loss at step {b - 1}")
                    if not np.isfinite(loss_val):
                        # a NaN loss is a dead run whether or not anyone
                        # is supervising — raise the typed error (the
                        # supervisor rolls back; an unsupervised caller
                        # at least dies naming the step, not 10k steps
                        # later at the final eval)
                        raise NonFiniteLossError(b - 1, loss_val)
                    logger.log_step(b - 1, loss_val, tokens_since_log,
                                    n_chips, lr=lr_at(b - 1))
                    tokens_since_log = 0
            if (checkpoint_manager is not None and tcfg.checkpoint_every
                    and it % tcfg.checkpoint_every == 0):
                tel.instant("train/checkpoint", step=it)
                checkpoint_manager.save(state, cursor)
    finally:
        profiler.close()
        sanitizer.close()
    jax.block_until_ready(state.params)
    wall = time.perf_counter() - t0
    end_step = int(jax.device_get(state.step))
    # under a preemption stop, keep the epilogue cheap: a short eval, and
    # the checkpoint was already written before leaving the loop
    # under a stop, also skip eval_scan: its (8,B,T) shape was never
    # compiled and a fresh XLA compile is exactly what the grace window
    # cannot afford — 8 already-compiled eval_step dispatches are cheap
    final_eval = estimate_loss(state.params, eval_batchers, eval_step,
                               min(tcfg.eval_iters, 8) if stopped_early
                               else tcfg.eval_iters, device_put=dput,
                               eval_scan=None if stopped_early
                               else eval_scan,
                               superbatch_put=superbatch_put)
    logger.log_eval(end_step, final_eval["train"], final_eval["val"])
    history.append((end_step, final_eval["train"], final_eval["val"]))
    if checkpoint_manager is not None and not stopped_early:
        checkpoint_manager.save(state, cursor)
    tps = tokens_seen / wall / n_chips if wall > 0 else 0.0
    logger.log(f"trained {tokens_seen:,} tokens in {wall:.1f}s "
               f"({tps:,.0f} tok/s/chip)")
    return TrainResult(state=state, history=history, final_eval=final_eval,
                       tokenizer=tokenizer, tokens_per_sec_per_chip=tps)
