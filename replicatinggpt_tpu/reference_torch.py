"""PyTorch-CPU reference backend.

BASELINE.json's north star keeps "the PyTorch path … as the CPU reference"
sharing the tokenizer and config with the JAX backend. This module is that
path: a from-scratch torch implementation of the same architecture driven by
the same :class:`~replicatinggpt_tpu.config.ModelConfig`, with lossless
weight transfer to/from the JAX param pytree. It serves three roles:

1. numerical parity oracle for the JAX model (tests/test_torch_parity.py);
2. the CPU-reference throughput baseline for bench.py (the "<1/50
   wall-clock" BASELINE.md target is measured against this);
3. the capability equivalent of the reference's torch training path
   (GPT1.py/GPT-2.py), with their §8 bugs fixed.

Weights are stored in the same (in, out) kernel layout as the JAX pytree
(applied as ``x @ W``), so transfer is a plain tree copy — no transposes.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

import numpy as np
import torch
import torch.nn.functional as F
from torch import nn

from .config import ModelConfig


def _act(x: torch.Tensor, kind: str) -> torch.Tensor:
    # matches jax.nn.gelu's default tanh approximation
    return F.gelu(x, approximate="tanh") if kind == "gelu" else F.relu(x)


class RefBlock(nn.Module):
    def __init__(self, cfg: ModelConfig):
        super().__init__()
        C = cfg.n_embd
        self.cfg = cfg
        self.ln1_scale = nn.Parameter(torch.ones(C))
        self.ln1_bias = nn.Parameter(torch.zeros(C))
        self.qkv_kernel = nn.Parameter(torch.empty(C, 3 * C))
        self.qkv_bias = nn.Parameter(torch.zeros(3 * C))
        self.attn_out_kernel = nn.Parameter(torch.empty(C, C))
        self.attn_out_bias = nn.Parameter(torch.zeros(C))
        self.ln2_scale = nn.Parameter(torch.ones(C))
        self.ln2_bias = nn.Parameter(torch.zeros(C))
        self.mlp_up_kernel = nn.Parameter(torch.empty(C, 4 * C))
        self.mlp_up_bias = nn.Parameter(torch.zeros(4 * C))
        self.mlp_down_kernel = nn.Parameter(torch.empty(4 * C, C))
        self.mlp_down_bias = nn.Parameter(torch.zeros(C))

    def forward(self, x: torch.Tensor) -> torch.Tensor:
        cfg = self.cfg
        B, T, C = x.shape
        H, D = cfg.n_head, cfg.head_dim
        h = F.layer_norm(x, (C,), self.ln1_scale, self.ln1_bias,
                         cfg.layernorm_eps)
        qkv = h @ self.qkv_kernel + self.qkv_bias
        q, k, v = qkv.split(C, dim=-1)
        q, k, v = (t.view(B, T, H, D).transpose(1, 2) for t in (q, k, v))
        attn = F.scaled_dot_product_attention(
            q, k, v, is_causal=True,
            dropout_p=cfg.attn_dropout if self.training else 0.0)
        attn = attn.transpose(1, 2).reshape(B, T, C)
        attn = attn @ self.attn_out_kernel + self.attn_out_bias
        x = x + F.dropout(attn, cfg.dropout, self.training)
        h = F.layer_norm(x, (C,), self.ln2_scale, self.ln2_bias,
                         cfg.layernorm_eps)
        h = _act(h @ self.mlp_up_kernel + self.mlp_up_bias, cfg.activation)
        h = h @ self.mlp_down_kernel + self.mlp_down_bias
        return x + F.dropout(h, cfg.dropout, self.training)


class RefGPT(nn.Module):
    """Decoder-only LM with the framework's exact architecture semantics."""

    def __init__(self, cfg: ModelConfig):
        super().__init__()
        cfg.validate()
        self.cfg = cfg
        C, V = cfg.n_embd, cfg.vocab_size
        self.wte = nn.Parameter(torch.empty(V, C))
        self.wpe = nn.Parameter(torch.empty(cfg.block_size, C))
        self.blocks = nn.ModuleList(RefBlock(cfg)
                                    for _ in range(cfg.n_layer))
        self.ln_f_scale = nn.Parameter(torch.ones(C))
        self.ln_f_bias = nn.Parameter(torch.zeros(C))
        if not cfg.tied_head:
            self.lm_head = nn.Parameter(torch.empty(C, V))
        self._init()

    def _init(self):
        cfg = self.cfg
        std, rstd = cfg.init_std, cfg.init_std * (2 * cfg.n_layer) ** -0.5
        with torch.no_grad():
            self.wte.normal_(0, std)
            self.wpe.normal_(0, std)
            if not cfg.tied_head:
                self.lm_head.normal_(0, std)
            for b in self.blocks:
                b.qkv_kernel.normal_(0, std)
                b.mlp_up_kernel.normal_(0, std)
                b.attn_out_kernel.normal_(0, rstd)
                b.mlp_down_kernel.normal_(0, rstd)

    def forward(self, idx: torch.Tensor,
                targets: Optional[torch.Tensor] = None
                ) -> Tuple[torch.Tensor, Optional[torch.Tensor]]:
        cfg = self.cfg
        B, T = idx.shape
        assert T <= cfg.block_size
        x = self.wte[idx] + self.wpe[:T]
        for b in self.blocks:
            x = b(x)
        x = F.layer_norm(x, (cfg.n_embd,), self.ln_f_scale, self.ln_f_bias,
                         cfg.layernorm_eps)
        head = self.wte.t() if cfg.tied_head else self.lm_head
        logits = x @ head
        if targets is None:
            return logits, None
        loss = F.cross_entropy(logits.view(B * T, -1), targets.view(B * T))
        return logits, loss


# ---------------------------------------------------------------------------
# weight transfer: JAX pytree <-> RefGPT (same layout, plain copies)
# ---------------------------------------------------------------------------

def params_to_torch(params: Dict, model: RefGPT) -> RefGPT:
    def t(a):
        return torch.from_numpy(np.asarray(a, dtype=np.float32))

    with torch.no_grad():
        model.wte.copy_(t(params["wte"]))
        model.wpe.copy_(t(params["wpe"]))
        model.ln_f_scale.copy_(t(params["ln_f_scale"]))
        model.ln_f_bias.copy_(t(params["ln_f_bias"]))
        if not model.cfg.tied_head:
            model.lm_head.copy_(t(params["lm_head"]))
        bl = params["blocks"]
        for i, b in enumerate(model.blocks):
            for name in ("ln1_scale", "ln1_bias", "qkv_kernel", "qkv_bias",
                         "attn_out_kernel", "attn_out_bias", "ln2_scale",
                         "ln2_bias", "mlp_up_kernel", "mlp_up_bias",
                         "mlp_down_kernel", "mlp_down_bias"):
                getattr(b, name).copy_(t(bl[name][i]))
    return model


def torch_to_params(model: RefGPT) -> Dict:
    def n(p):
        return p.detach().cpu().numpy().astype(np.float32)

    names = ("ln1_scale", "ln1_bias", "qkv_kernel", "qkv_bias",
             "attn_out_kernel", "attn_out_bias", "ln2_scale", "ln2_bias",
             "mlp_up_kernel", "mlp_up_bias", "mlp_down_kernel",
             "mlp_down_bias")
    blocks = {name: np.stack([n(getattr(b, name)) for b in model.blocks])
              for name in names}
    params = {"wte": n(model.wte), "wpe": n(model.wpe), "blocks": blocks,
              "ln_f_scale": n(model.ln_f_scale),
              "ln_f_bias": n(model.ln_f_bias)}
    if not model.cfg.tied_head:
        params["lm_head"] = n(model.lm_head)
    return params


# ---------------------------------------------------------------------------
# CPU-reference throughput (the bench.py baseline)
# ---------------------------------------------------------------------------

def measure_train_throughput(cfg: ModelConfig, batch_size: int = 64,
                             steps: int = 3, warmup: int = 1,
                             lr: float = 2e-4, seed: int = 0) -> float:
    """Train tokens/sec of the torch-CPU reference path (AdamW, same config
    the JAX backend runs). Used as BASELINE for vs_baseline ratios."""
    torch.manual_seed(seed)
    model = RefGPT(cfg)
    model.train()
    opt = torch.optim.AdamW(model.parameters(), lr=lr)
    g = torch.Generator().manual_seed(seed)
    x = torch.randint(0, cfg.vocab_size, (batch_size, cfg.block_size),
                      generator=g)
    y = torch.randint(0, cfg.vocab_size, (batch_size, cfg.block_size),
                      generator=g)

    def one_step():
        opt.zero_grad(set_to_none=True)
        _, loss = model(x, y)
        loss.backward()
        opt.step()

    for _ in range(warmup):
        one_step()
    t0 = time.perf_counter()
    for _ in range(steps):
        one_step()
    dt = time.perf_counter() - t0
    return batch_size * cfg.block_size * steps / dt
