"""Continuous-batching inference engine.

One pre-compiled multi-slot decode step, driven by a host-side
scheduler — the serving shape both the compiler-first O(1)-caching and
the pjit/TPU-scaling playbooks converge on (PAPERS.md): the device
program never changes at steady state, and all request-level dynamism
(arrivals, lengths, completions, cancellations) lives in cheap host
bookkeeping plus small per-step input arrays.

Per step the engine:

1. expires deadlines (queued and active),
2. admits queued prompts into free pool slots — chunked prefill
   (``models.gpt.prefill_chunk_into_slot``) writes the prompt's K/V
   into the slot's cache region under ONE compiled program regardless
   of prompt length,
3. runs ONE jitted ``decode_step_multi`` over ALL slots — per-slot
   positions, per-slot active mask, per-slot RNG streams, per-slot
   sampling params (``sample.generate.sample_tokens_batched``) — and
   fetches the (n_slots,) sampled tokens.

Zero recompiles at steady state: the decode program is keyed only on
the (static) model config and pool shape, the prefill program only on
the chunk shape; both are module-level jits whose cache sizes the tests
assert stay flat across a long replay (tests/test_serve.py).

Observability: per-request TTFT / decode tok/s / queue wait, engine
counters (admissions, rejections, completions, tokens), slot-occupancy
and queue-depth gauges, batch-fill-ratio and step-latency histograms —
through ``utils.logging.Metrics`` and ``utils.profiling.StepTimer``,
with ``annotate()`` spans around the prefill and decode phases.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ModelConfig
from ..models.gpt import decode_step_multi, prefill_chunk_into_slot
from ..sample.generate import sample_tokens_batched
from ..utils.logging import Metrics
from ..utils.profiling import StepTimer, annotate
from ..utils.sanitize import CompileGuard, check_in_bounds, sanitize_enabled
from .cache_pool import CachePool
from .requests import (FINISH_CANCELLED, FINISH_DEADLINE, FINISH_LENGTH_CAP,
                       FINISH_MAX_TOKENS, Request, RequestResult)
from .scheduler import Scheduler


@dataclass(frozen=True)
class EngineConfig:
    """Engine sizing. ``prefill_chunk=0`` auto-sizes to
    min(64, block_size): small enough that short prompts don't pay a
    huge padded chunk, large enough that long prompts take few chunk
    dispatches — and ONE compiled prefill program either way."""

    pool_size: int = 8
    max_queue: int = 64
    prefill_chunk: int = 0

    def chunk(self, block_size: int) -> int:
        """Effective prefill chunk: the requested (or auto) size rounded
        DOWN to a divisor of block_size. Divisibility is a correctness
        requirement, not a preference: the final chunk of a P-token
        prompt is dispatched at offset (ceil(P/c)-1)*c and padded to c,
        so a non-divisor c could push the padded chunk past the cache
        buffer — and jax.lax.dynamic_update_slice silently CLAMPS
        out-of-bounds starts, which would overwrite valid earlier K/V
        instead of erroring. With c | block_size, ceil(P/c)*c <=
        block_size for every admissible P."""
        c = min(self.prefill_chunk or min(64, block_size), block_size)
        while block_size % c:
            c -= 1
        return c


@dataclass
class _Active:
    """Host-side record of a request occupying a slot."""

    req: Request
    t_submit: float
    t_admit: float
    cap: int                      # max new tokens this slot can produce
    capped: bool                  # cap < req.max_new_tokens (context limit)
    tokens: List[int] = field(default_factory=list)
    t_first_token: float = 0.0
    t_last_token: float = 0.0


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def _engine_decode(params, tok, pos, active, cache, rngs, temp, top_k,
                   top_p, greedy, cfg: ModelConfig):
    """The steady-state program: one multi-slot decode + batched sample.

    All request-level inputs are small (n_slots,) arrays — traced, so
    admissions/completions/sampling changes never retrace. Inactive
    slots run at position 0 (their writes land in cache regions the
    next occupant's prefill overwrites before attending) and their
    sampled token is masked to 0.
    """
    pos_eff = jnp.where(active, pos, 0)
    logits, cache = decode_step_multi(params, tok, pos_eff, cache, cfg)
    splits = jax.vmap(lambda k: jax.random.split(k, 2))(rngs)
    nxt = sample_tokens_batched(splits[:, 0], logits, temp, top_k, top_p,
                                greedy)
    return jnp.where(active, nxt, 0), cache, splits[:, 1]


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def _engine_prefill(params, chunk, offset, slot, cache, cfg: ModelConfig):
    return prefill_chunk_into_slot(params, chunk, offset, slot, cache, cfg)


def compile_counts() -> Dict[str, int]:
    """Process-wide compiled-program counts for the two engine entry
    points (module-level jits, so they accumulate across engines). The
    replay driver's before/after bookkeeping reads these; the *live*
    steady-state enforcement is per-engine via :class:`CompileGuard`
    (utils.sanitize), which raises from the offending step instead of
    reporting after the fact."""
    return {"decode": _engine_decode._cache_size(),
            "prefill": _engine_prefill._cache_size()}


class Engine:
    """Continuous-batching engine over a pooled KV cache.

    Host API (single-threaded by design — drive it from one loop):

    - ``submit(req)`` -> None (accepted) or a rejected ``RequestResult``
      (backpressure / validation, with the reason as finish_reason);
    - ``cancel(request_id)`` -> bool;
    - ``step()`` -> list of requests finishing this step;
    - ``drain()`` -> run steps until idle, return all finishes;
    - ``metrics_summary()`` -> counters/gauges/histograms + step-latency
      percentiles.
    """

    def __init__(self, params, cfg: ModelConfig,
                 ecfg: EngineConfig = EngineConfig(),
                 clock: Callable[[], float] = time.monotonic):
        cfg.validate()
        self.params = params
        self.cfg = cfg
        self.ecfg = ecfg
        self.clock = clock
        self.pool = CachePool(cfg, ecfg.pool_size)
        self.scheduler = Scheduler(ecfg.max_queue, cfg.block_size,
                                   clock=clock)
        self.metrics = Metrics()
        self.step_timer = StepTimer()
        P = ecfg.pool_size
        self._chunk = ecfg.chunk(cfg.block_size)
        self._tok = np.zeros((P,), np.int32)
        self._pos = np.zeros((P,), np.int32)
        self._active = np.zeros((P,), bool)
        self._temp = np.ones((P,), np.float32)
        self._top_k = np.zeros((P,), np.int32)
        self._top_p = np.zeros((P,), np.float32)
        self._greedy = np.zeros((P,), bool)
        # committed up front for the same jit-key stability reason as
        # CachePool.cache (the array becomes a committed jit output
        # after the first step)
        from .cache_pool import commit_default
        self._rngs = commit_default(
            jnp.stack([jax.random.PRNGKey(i) for i in range(P)]))
        self._slots: Dict[int, _Active] = {}
        self._pending: List[RequestResult] = []  # cancellations between steps
        self.n_steps = 0
        # the steady-state contract, enforced live: each entry point may
        # compile ONE program for this engine's shapes (counted relative
        # to engine construction — the module jit caches accumulate
        # across engines); a second compile raises RecompileError from
        # the step that caused it. Replaces the ad-hoc two-program
        # bookkeeping the first serving PR shipped (compile_counts()
        # remains for offline summaries).
        self._decode_guard = CompileGuard(_engine_decode, "serve/decode")
        self._prefill_guard = CompileGuard(_engine_prefill, "serve/prefill")
        self._sanitize = sanitize_enabled()

    # ---------------------------------------------------------------- API

    def submit(self, req: Request) -> Optional[RequestResult]:
        self.metrics.inc("requests_submitted")
        reason = self.scheduler.submit(req)
        if reason is not None:
            self.metrics.inc(reason)
            return RequestResult(id=req.id, tokens=[], finish_reason=reason)
        return None

    def cancel(self, request_id: str) -> bool:
        """Cancel a queued or running request. The terminal
        ``RequestResult`` (with any tokens already produced) surfaces
        from the next ``step()``; True iff the request was found."""
        now = self.clock()
        if self.scheduler.cancel(request_id):
            self.metrics.inc("finished_" + FINISH_CANCELLED)
            self._pending.append(RequestResult(
                id=request_id, tokens=[], finish_reason=FINISH_CANCELLED))
            return True
        slot = self.pool.slot_of(request_id)
        if slot is None:
            return False
        self._pending.append(self._finish_slot(slot, FINISH_CANCELLED, now))
        return True

    @property
    def idle(self) -> bool:
        return (not self._active.any() and len(self.scheduler) == 0
                and not self._pending)

    def step(self) -> List[RequestResult]:
        """One scheduling iteration: expire -> admit -> decode."""
        finished: List[RequestResult] = self._pending
        self._pending = []
        now = self.clock()

        for req, t_submit, reason in self.scheduler.drain_expired(now):
            finished.append(self._finish_unstarted(req, t_submit, reason,
                                                   now))
        for slot in list(self._slots):
            dl = self._slots[slot].req.deadline
            if dl is not None and now >= dl:
                finished.append(self._finish_slot(slot, FINISH_DEADLINE,
                                                  now))

        admitted, dropped = self.scheduler.admit(self.pool.n_free, now)
        for req, t_submit, reason in dropped:
            finished.append(self._finish_unstarted(req, t_submit, reason,
                                                   now))
        for req, t_submit in admitted:
            self._admit(req, t_submit, now)

        self.metrics.gauge("queue_depth", self.scheduler.depth)
        self.metrics.gauge("slots_active", int(self._active.sum()))
        self.metrics.gauge("slot_occupancy", self.pool.occupancy)

        if self._active.any():
            finished.extend(self._decode_once())
        return finished

    def drain(self, max_steps: int = 1_000_000) -> List[RequestResult]:
        out: List[RequestResult] = []
        for _ in range(max_steps):
            if self.idle:
                return out
            out.extend(self.step())
        raise RuntimeError(f"engine did not drain in {max_steps} steps")

    def metrics_summary(self) -> dict:
        s = self.metrics.summary()
        s["step_latency"] = self.step_timer.summary(skip=1)
        s["n_steps"] = self.n_steps
        s["compile_counts"] = compile_counts()
        s["compile_guards"] = {"decode": self._decode_guard.stats(),
                               "prefill": self._prefill_guard.stats()}
        return s

    # ----------------------------------------------------------- internals

    def _admit(self, req: Request, t_submit: float, now: float) -> None:
        slot = self.pool.acquire(req.id)
        assert slot is not None, "scheduler admitted past pool capacity"
        P = int(req.prompt.size)
        S = self.pool.seq_len
        # decode step i runs at position P-1+i (the first rewrites the
        # last prompt position), so the slot supports S - P + 1 new
        # tokens before the write position would leave the buffer
        room = S - P + 1
        cap = min(req.max_new_tokens, room)
        chunk = self._chunk
        n_chunks = -(-P // chunk)
        # the host-side bound the jitted prefill (offset traced) relies
        # on: the LAST padded chunk must land inside the slot buffer,
        # else dynamic_update_slice clamp-corrupts earlier K/V (lint
        # GL006 / the PR 1 bug). Holds by construction — scheduler
        # rejects P > block_size and EngineConfig.chunk divides it —
        # this assert keeps the invariant from silently rotting.
        check_in_bounds((n_chunks - 1) * chunk, chunk, S,
                        what=f"prefill of {P}-token prompt in {chunk}-chunks")
        padded = np.zeros((n_chunks * chunk,), np.int32)
        padded[:P] = req.prompt
        cache = self.pool.cache
        with annotate("serve/prefill"):
            for c in range(n_chunks):
                cache = self._prefill_guard(
                    self.params, jnp.asarray(padded[None,
                                                    c * chunk:(c + 1) * chunk]),
                    jnp.int32(c * chunk), jnp.int32(slot), cache, self.cfg)
        self.pool.cache = cache
        self._tok[slot] = req.prompt[-1]
        self._pos[slot] = P - 1
        self._active[slot] = True
        sp = req.sampling
        self._temp[slot] = sp.temperature
        self._top_k[slot] = sp.top_k
        self._top_p[slot] = sp.top_p
        self._greedy[slot] = sp.greedy
        self._rngs = self._rngs.at[slot].set(jax.random.PRNGKey(req.rng_seed))
        self._slots[slot] = _Active(req=req, t_submit=t_submit, t_admit=now,
                                    cap=cap,
                                    capped=cap < req.max_new_tokens)
        self.metrics.inc("requests_admitted")
        self.metrics.inc("prefill_tokens", P)
        self.metrics.observe("queue_wait_s", now - t_submit)

    def _decode_once(self) -> List[RequestResult]:
        with annotate("serve/decode"):
            self.step_timer.start()
            nxt, cache, rngs = self._decode_guard(
                self.params, jnp.asarray(self._tok), jnp.asarray(self._pos),
                jnp.asarray(self._active), self.pool.cache, self._rngs,
                jnp.asarray(self._temp), jnp.asarray(self._top_k),
                jnp.asarray(self._top_p), jnp.asarray(self._greedy),
                self.cfg)
            self.step_timer.lap(nxt)
        self.pool.cache = cache
        self._rngs = rngs
        toks = np.asarray(nxt)
        if self._sanitize:
            # GRAFT_SANITIZE: sampled ids must be valid vocab entries
            # (an out-of-range id would clamp in the next embedding
            # gather and silently decode garbage)
            bad = (toks < 0) | (toks >= self.cfg.vocab_size)
            if bad.any():
                raise FloatingPointError(
                    f"sanitize: decode produced out-of-range token(s) "
                    f"{toks[bad][:4].tolist()} (vocab "
                    f"{self.cfg.vocab_size})")
        now = self.clock()
        self.n_steps += 1
        n_active = int(self._active.sum())
        self.metrics.observe("batch_fill_ratio",
                             n_active / self.ecfg.pool_size)
        self.metrics.inc("decode_steps")
        self.metrics.inc("decode_tokens", n_active)
        finished: List[RequestResult] = []
        for slot in list(self._slots):
            if not self._active[slot]:
                continue
            st = self._slots[slot]
            st.tokens.append(int(toks[slot]))
            if len(st.tokens) == 1:
                st.t_first_token = now
                self.metrics.observe("ttft_s", now - st.t_submit)
            st.t_last_token = now
            self._tok[slot] = toks[slot]
            self._pos[slot] += 1
            if len(st.tokens) >= st.cap:
                reason = (FINISH_LENGTH_CAP if st.capped
                          else FINISH_MAX_TOKENS)
                finished.append(self._finish_slot(slot, reason, now))
        return finished

    def _finish_slot(self, slot: int, reason: str,
                     now: float) -> RequestResult:
        st = self._slots.pop(slot)
        self._active[slot] = False
        self.pool.release(slot)
        n = len(st.tokens)
        decode_tps = 0.0
        if n > 1 and st.t_last_token > st.t_first_token:
            decode_tps = (n - 1) / (st.t_last_token - st.t_first_token)
        res = RequestResult(
            id=st.req.id, tokens=st.tokens, finish_reason=reason,
            queue_wait_s=st.t_admit - st.t_submit,
            ttft_s=(st.t_first_token - st.t_submit) if n else 0.0,
            decode_tokens_per_s=decode_tps, total_s=now - st.t_submit)
        self.metrics.inc(f"finished_{reason}")
        if decode_tps:
            self.metrics.observe("decode_tokens_per_s", decode_tps)
        return res

    def _finish_unstarted(self, req: Request, t_submit: float, reason: str,
                          now: float) -> RequestResult:
        self.metrics.inc(f"finished_{reason}")
        return RequestResult(id=req.id, tokens=[], finish_reason=reason,
                             queue_wait_s=now - t_submit,
                             total_s=now - t_submit)
