"""Continuous-batching inference engine.

One pre-compiled multi-slot decode step, driven by a host-side
scheduler — the serving shape both the compiler-first O(1)-caching and
the pjit/TPU-scaling playbooks converge on (PAPERS.md): the device
program never changes at steady state, and all request-level dynamism
(arrivals, lengths, completions, cancellations) lives in cheap host
bookkeeping plus small per-step input arrays.

Per step the engine:

1. expires deadlines (queued and active),
2. admits queued prompts into free pool slots, gated on free PAGES as
   well as free slots (serve/pages.py: the KV cache is a paged pool +
   per-slot page tables with radix prefix reuse) — admission claims the
   longest cached prefix and chunked prefill
   (``models.gpt.prefill_chunk_paged``) writes only the UNCACHED tail's
   K/V through the slot's page table, under ONE compiled program
   regardless of prompt length or prefix-hit length,
3. runs ONE jitted decode dispatch over ALL slots — per-slot page
   tables, positions, active mask, RNG streams and sampling params
   (``sample.generate.sample_tokens_batched``). At steady state (no
   admission, finish bookkeeping, or speculative re-probe pending) the
   dispatch is a WINDOW of ``EngineConfig.decode_window`` decode steps
   rolled into one program (``models.gpt.decode_window_paged``: a
   lax.scan over the step body with per-slot budget/EOS masks computed
   ON DEVICE, so a slot finishing mid-window idles inside it instead of
   forcing an early exit), the step state ``(tok, pos, active, budget,
   rngs)`` lives on the device and is DONATED from window to window
   alongside the cache, and the host runs AHEAD of the device: window
   N+1 is dispatched before window N's token block is fetched
   (one async ``copy_to_host_async`` + ``np.asarray`` per window, not
   one blocking snapshot per token — the BENCH_r03 dispatch-tax fix,
   ROADMAP item 2). Anything that must mutate per-slot state host-side
   (an admission, an active-deadline expiry, a cancel, a speculative
   mode flip) first drains the in-flight window and falls back to a
   blocked k=1 dispatch for that step. With a drafter attached
   (serve/speculative.py) the decode phase is instead ONE jitted
   ``_engine_verify``: score a static (k+1)-token drafted window per
   slot against the pooled cache and commit 1..k+1 accepted tokens —
   up to k+1 tokens per slot per full-model forward, interleaved with
   chunked prefill admissions exactly like plain decode (and with
   multi-token decode windows while speculation is degraded).

Zero recompiles at steady state: the decode/verify programs are keyed
only on the (static) model config, pool/page shapes, draft width and
the engine's sharding plan, the prefill program only on the chunk
shape, the COW page copy on the pool shape alone; page tables,
positions and every other request-level input are traced fixed-shape
arrays, so admissions, prefix hits, LRU evictions and copy-on-write
splits all happen without a recompile. All are module-level jits whose
cache sizes the tests assert stay flat across a long replay
(tests/test_serve.py, tests/test_speculative.py, tests/test_pages.py).

Sharded serving (``EngineConfig.mesh_data``/``mesh_model``, the
``--mesh-shape`` knob): the SAME engine runs GSPMD-partitioned over a
(data, model) mesh — params take the decode TP layout, the paged pool
shards its physical page axis over 'data' and its model dim over
'model' (parallel.mesh.page_pool_pspec, designed first per ROADMAP),
and every program above carries the engine's static
``ServeShardings`` bundle so the pool layout survives each traced body
(donation needs matching shardings to alias) while the step state and
the per-window token block stay replicated — the host fetch contract
(one ``np.asarray`` per window, reading a local shard) is unchanged.
Request-level architecture, host bookkeeping and the paged Pallas
fallback routing (ops/paged_pallas.paged_kernel_mesh_ok) are all
mesh-agnostic; greedy streams are token-identical across mesh shapes
(tests/test_serve_mesh.py).

Observability: per-request TTFT / decode tok/s / queue wait, engine
counters (admissions, rejections, completions, tokens), slot-occupancy
and queue-depth gauges, batch-fill-ratio and step-latency histograms —
through ``utils.logging.Metrics`` and ``utils.profiling.StepTimer``,
with ``annotate()`` spans around the prefill and decode phases.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ModelConfig
from ..faults.inject import fire as fault_fire
from ..faults.watchdog import (LoadShedder, ResilienceConfig, SpecHealth,
                               StepWatchdog)
from ..models.gpt import (decode_window_paged, prefill_chunk_paged,
                          verify_step_paged)
from ..sample.generate import sample_tokens_batched
from ..utils.logging import Metrics
from ..utils.profiling import StepTimer, annotate
from ..utils.sanitize import CompileGuard, check_in_bounds, sanitize_enabled
from ..utils.telemetry import ENGINE_TRACK, NULL, SLOT_TRACK_BASE
from .pages import PagedCachePool
from .requests import (FINISH_CANCELLED, FINISH_DEADLINE, FINISH_EOS,
                       FINISH_LENGTH_CAP, FINISH_MAX_TOKENS, FINISH_SHED,
                       REJECT_BAD_REQUEST, Request, RequestResult)
from .scheduler import Scheduler
from .speculative import (DraftContext, Drafter, spec_accept_and_sample,
                          timed_draft)


@dataclass(frozen=True)
class EngineConfig:
    """Engine sizing. ``prefill_chunk=0`` auto-sizes to
    min(64, block_size): small enough that short prompts don't pay a
    huge padded chunk, large enough that long prompts take few chunk
    dispatches — and ONE compiled prefill program either way."""

    pool_size: int = 8
    max_queue: int = 64
    prefill_chunk: int = 0
    # --- paged KV cache (serve/pages.py) --------------------------------
    page_size: int = 0        # tokens per KV page; 0 = min(16, block_size)
    max_pages: int = 0        # logical pages per slot; 0 = ceil(block/page)
    n_pages: int = 0          # physical pool pages; 0 = pool_size*max_pages
                              # (the contiguous pool's HBM exactly); fewer
                              # pages shrinks HBM and admission gates on it
    prefix_cache: bool = True  # radix prefix reuse (False: pages only)
    paged_kernel: bool = False  # opt-in Pallas paged decode fast path
                                # (TPU, packed cache layout only):
                                # prefers the fused all-layers kernel
                                # (ops/decode_pallas.py), falls back to
                                # the per-layer one (ops/paged_pallas)
    decode_window: int = 1      # decode steps rolled into one dispatch
                                # at steady state (the --decode-window
                                # knob): 1 = the blocked step-per-
                                # dispatch loop; >1 enables the async
                                # double-buffered window path — the
                                # engine still falls back to k=1 for
                                # any step with an admission, active-
                                # deadline expiry, cancel, or
                                # speculative verify/re-probe pending
    # --- serving mesh (parallel/mesh.py, the --mesh-shape knob) ---------
    mesh_data: int = 1          # 'data' axis: the paged pool's physical
                                # page axis shards across it — each chip
                                # stores n_pages/data pages, so the same
                                # per-chip HBM holds data× more
                                # aggregate pages (capacity multiplier)
    mesh_model: int = 1         # 'model' axis: Megatron TP over the
                                # decode/prefill/verify programs
                                # (attention+MLP FLOPs multiplier);
                                # params shard by the training TP specs,
                                # replicated over 'data'

    @property
    def mesh_shape(self) -> tuple:
        return (self.mesh_data, self.mesh_model)

    def chunk(self, block_size: int) -> int:
        """Effective prefill chunk — see ``cache_pool.prefill_chunk_size``
        for the divisor-rounding rule and why it is load-bearing."""
        from .cache_pool import prefill_chunk_size
        return prefill_chunk_size(self.prefill_chunk, block_size)

    def warmup_tokens(self) -> int:
        """Tokens a warmup request must generate so that warmup compiles
        EVERY steady-state decode program: the admission step runs the
        k=1 fallback, every later step a full window — so a windowed
        engine needs the request to outlive the admission step by at
        least one whole window (two, for slack against scheduling
        details). ONE definition, shared by the replay warmup and the
        worker's readiness warmup: they must never disagree, or one
        deployment path compiles the window program mid-traffic and
        breaks the recompiles_after_warmup == 0 invariant."""
        return 1 if self.decode_window <= 1 else 2 * self.decode_window + 2


@dataclass
class _Active:
    """Host-side record of a request occupying a slot."""

    req: Request
    t_submit: float
    t_admit: float
    cap: int                      # max new tokens this slot can produce
    capped: bool                  # cap < req.max_new_tokens (context limit)
    tokens: List[int] = field(default_factory=list)
    t_first_token: float = 0.0
    t_last_token: float = 0.0


@dataclass
class _InFlight:
    """One dispatched-but-not-yet-fetched decode window. ``toks`` and
    ``emitted`` are the dispatch's (k, n_slots) device outputs; their
    host copy starts the moment the dispatch launches
    (``copy_to_host_async``) so the drain's ``np.asarray`` overlaps
    device compute instead of stalling on it."""

    toks: jax.Array               # (k, n_slots) sampled tokens
    emitted: jax.Array            # (k, n_slots) bool live-at-step mask
    k: int                        # static window width of the dispatch
    t0_us: float                  # launch timestamp (telemetry clock)
    t_wall: float                 # launch timestamp (perf_counter)
    n_active: int                 # live slots at launch


@partial(jax.jit, static_argnames=("cfg", "k", "use_pallas", "use_fused",
                                   "shardings"),
         donate_argnames=("tok", "pos", "active", "budget", "cache",
                          "rngs"))
def _engine_decode_window(params, tok, pos, active, budget, eos, tables,
                          cache, rngs, temp, top_k, top_p, greedy,
                          cfg: ModelConfig, k: int,
                          use_pallas: bool = False,
                          use_fused: bool = False, shardings=None):
    """The steady-state program: ``k`` multi-slot PAGED decode + batched
    sample steps in ONE dispatch (``models.gpt.decode_window_paged``),
    with the whole per-slot step state ``(tok, pos, active, budget,
    rngs)`` donated alongside the cache — at k > 1 the engine feeds each
    window the previous window's returned state without ever touching
    the host, so the old buffers alias the new in place.

    All request-level inputs are small traced arrays — the (n_slots,)
    step vectors plus the (n_slots, max_pages) page tables — so
    admissions/completions/prefix-hits/evictions/COW remaps never
    retrace, and the window width is static: a slot that exhausts its
    budget or samples its eos token mid-window goes inactive ON DEVICE
    and idles for the window's remainder (partial windows are a masked
    tail, never a second program). Inactive slots run at position 0
    with their cache writes DROPPED inside ``decode_step_paged`` (a
    released slot's stale table may reference pages another request now
    owns) and their sampled token is masked to 0.

    ``shardings`` (parallel.mesh.ServeShardings; STATIC — hashable, one
    value per engine, so sharded and unsharded engines are distinct
    programs under the same budget discipline) runs the whole window on
    the serving mesh: the page pool stays pinned to its (data, model)
    PartitionSpec through every scan step (donation needs matching in/
    out shardings to alias), the step state and the (k, n_slots) token
    block leave fully replicated — the caller's ``np.asarray`` fetch is
    a local read, never a cross-device gather.
    """
    def sample_fn(rngs, logits):
        splits = jax.vmap(lambda r: jax.random.split(r, 2))(rngs)
        nxt = sample_tokens_batched(splits[:, 0], logits, temp, top_k,
                                    top_p, greedy)
        return nxt, splits[:, 1]

    return decode_window_paged(params, tok, pos, active, budget, eos,
                               tables, cache, rngs, cfg,
                               sample_fn=sample_fn, length=k,
                               use_pallas=use_pallas, use_fused=use_fused,
                               shardings=shardings)


@partial(jax.jit, static_argnames=("cfg", "shardings"),
         donate_argnames=("cache",))
def _engine_prefill(params, chunk, offset, limit, table_row, cache,
                    cfg: ModelConfig, shardings=None):
    return prefill_chunk_paged(params, chunk, offset, limit, table_row,
                               cache, cfg, shardings=shardings)


@partial(jax.jit, static_argnames=("cfg", "shardings"),
         donate_argnames=("cache", "rngs"))
def _engine_verify(params, window, pos, m, active, tables, cache, rngs,
                   temp, top_k, top_p, greedy, cfg: ModelConfig,
                   shardings=None):
    """The speculative steady-state program: ONE target forward over a
    static (n_slots, k+1) window against the PAGED pool + per-position
    acceptance. Draft count k is carried by the window's static width,
    so a fixed --spec-k means exactly one extra compiled program next
    to decode/prefill. All request-level inputs — positions, valid-
    draft counts, page tables, sampling params, the drafted tokens —
    are traced fixed-shape arrays, so acceptance outcomes never
    retrace. Inactive slots run at position 0 with zero valid drafts
    and dropped writes; their outputs are masked. ``shardings`` runs
    the verify forward on the serving mesh (pool pinned per layer) with
    the acceptance outputs replicated for the host commit.
    """
    logits, cache = verify_step_paged(params, window, pos, m, active,
                                      tables, cache, cfg,
                                      shardings=shardings)
    m_eff = jnp.where(active, m, 0)
    n_acc, out, rngs = spec_accept_and_sample(rngs, logits, window, m_eff,
                                              temp, top_k, top_p, greedy)
    n_acc = jnp.where(active, n_acc, 0)
    out = jnp.where(active[:, None], out, 0)
    if shardings is not None:
        n_acc = jax.lax.with_sharding_constraint(n_acc, shardings.rep)
        out = jax.lax.with_sharding_constraint(out, shardings.rep)
        rngs = jax.lax.with_sharding_constraint(rngs, shardings.rep)
    return n_acc, out, cache, rngs


@partial(jax.jit, static_argnames=("shardings",),
         donate_argnames=("cache",))
def _engine_page_copy(cache, src, dst, shardings=None):
    """Copy-on-write page split: duplicate physical page ``src`` into
    ``dst`` across all layers of both pool arrays. One program for any
    (src, dst) — both traced scalars — warmed at engine construction so
    the first real COW mid-replay cannot cost a compile. The caller
    bounds dst host-side (check_in_bounds below no-ops on tracers). On
    a serving mesh the copy crosses data shards when src and dst land
    on different chips — GSPMD inserts the collective; the output stays
    pinned to the pool spec so the donated buffers alias."""
    out = {}
    for name, arr in cache.items():
        check_in_bounds(dst, 1, arr.shape[1], what="COW page copy")
        page = jax.lax.dynamic_index_in_dim(arr, src, 1, keepdims=True)
        new = jax.lax.dynamic_update_slice_in_dim(arr, page, dst, axis=1)
        if shardings is not None:
            new = jax.lax.with_sharding_constraint(new, shardings.cache)
        out[name] = new
    return out


def engine_summary_block(engine: "Engine") -> dict:
    """The per-replica block of the fleet summary — ONE definition
    consumed by both sides of the process boundary (the in-process
    ``router.Replica.summary_block`` and the worker's ``summary`` RPC),
    so the multiproc bench artifact can never silently diverge in
    shape from the in-process one."""
    s = engine.metrics_summary()
    return {
        "occupancy_mean": round(
            s["histograms"].get("batch_fill_ratio", {})
            .get("mean", 0.0), 4),
        "n_steps": engine.n_steps,
        "pages": s["pages"],
        "finished": {k: int(v) for k, v in
                     engine.metrics.counters.items()
                     if k.startswith("finished_")},
    }


def compile_counts() -> Dict[str, int]:
    """Process-wide compiled-program counts for the engine entry points
    (module-level jits, so they accumulate across engines), including
    the speculative verify step, the COW page copy, and the model
    drafter's two programs. The replay driver's before/after
    bookkeeping reads these; the *live* steady-state enforcement is
    per-engine via :class:`CompileGuard` (utils.sanitize), which raises
    from the offending step instead of reporting after the fact."""
    from .speculative import _draft_decode_k, _draft_prefill
    return {"decode": _engine_decode_window._cache_size(),
            "prefill": _engine_prefill._cache_size(),
            "verify": _engine_verify._cache_size(),
            "page_copy": _engine_page_copy._cache_size(),
            "draft_decode": _draft_decode_k._cache_size(),
            "draft_prefill": _draft_prefill._cache_size()}


class Engine:
    """Continuous-batching engine over a pooled KV cache.

    Host API (single-threaded by design — drive it from one loop):

    - ``submit(req)`` -> None (accepted) or a rejected ``RequestResult``
      (backpressure / validation, with the reason as finish_reason);
    - ``cancel(request_id)`` -> bool;
    - ``step()`` -> list of requests finishing this step;
    - ``drain()`` -> run steps until idle, return all finishes;
    - ``metrics_summary()`` -> counters/gauges/histograms + step-latency
      percentiles.
    """

    def __init__(self, params, cfg: ModelConfig,
                 ecfg: EngineConfig = EngineConfig(),
                 clock: Callable[[], float] = time.monotonic,
                 drafter: Optional[Drafter] = None,
                 rcfg: Optional[ResilienceConfig] = None,
                 journal=None, telemetry=None, track_base: int = 0,
                 track_label: str = ""):
        """``rcfg`` (faults.watchdog.ResilienceConfig) opts into the
        self-healing policies — stall watchdog, speculative auto-disable
        with re-probe, load shedding; None/all-zero changes nothing.
        ``journal`` (serve.journal.RequestJournal) records accepted and
        finished requests for restart recovery. ``telemetry`` (a
        utils.telemetry.Telemetry, ideally sharing this engine's
        ``clock`` so request envelopes and step spans land on one
        timeline) opts into request-lifecycle tracing: one span tree
        per request on per-slot tracks plus step/draft spans and
        prefix-hit/COW/eviction/recovery instants; None means the
        zero-cost NULL recorder and changes nothing. ``track_base``
        offsets every track id this engine emits on — the fleet router
        gives replica ``i`` base ``i * REPLICA_TRACK_STRIDE`` so N
        replicas share one recorder without colliding tracks
        (``track_label`` prefixes the human-readable track names)."""
        cfg.validate()
        self.params = params
        self.cfg = cfg
        self.ecfg = ecfg
        self.clock = clock
        self.drafter = drafter
        self.tel = telemetry or NULL
        self._tb = track_base
        if self.tel.enabled:
            self.tel.name_track(self._tb + ENGINE_TRACK,
                                f"{track_label}engine")
            for s in range(ecfg.pool_size):
                self.tel.name_track(self._tb + SLOT_TRACK_BASE + s,
                                    f"{track_label}slot {s}")
        if drafter is not None:
            dcfg = getattr(drafter, "cfg", None)
            if dcfg is not None:       # model drafter: pools must line up
                assert dcfg.vocab_size == cfg.vocab_size, \
                    "draft model must share the target vocab"
                assert dcfg.block_size == cfg.block_size, \
                    "draft model must share the target block_size"
                assert drafter.pool_size == ecfg.pool_size, \
                    "draft pool must match the engine pool"
        # serving mesh (parallel/mesh.py): params take the decode TP
        # layout (Megatron over 'model', replicated over 'data'), the
        # page pool its (data, model) PartitionSpec — both placed ONCE
        # here; every jitted program then carries the same static
        # ServeShardings bundle, so GSPMD runs the whole engine sharded
        # without any program gaining a second compiled variant.
        # Drafter params/caches stay single-device (they are separate
        # jits over separate state — prefix reuse logic is unchanged).
        self.mesh = None
        self._plan = None
        if ecfg.mesh_data > 1 or ecfg.mesh_model > 1:
            from ..parallel.mesh import (make_serve_mesh,
                                         serve_param_shardings,
                                         serve_shardings)
            from .pages import pool_geometry
            self.mesh = make_serve_mesh(ecfg.mesh_data, ecfg.mesh_model)
            _, _, n_pages_eff = pool_geometry(
                cfg, ecfg.pool_size, ecfg.page_size, ecfg.max_pages,
                ecfg.n_pages)
            self._plan = serve_shardings(self.mesh, cfg, n_pages_eff,
                                         ecfg.mesh_data, ecfg.mesh_model)
            self.params = jax.device_put(
                self.params,
                serve_param_shardings(cfg, self.mesh, ecfg.mesh_model))
        self._rep = self._plan.rep if self._plan is not None else None
        self.pool = PagedCachePool(
            cfg, ecfg.pool_size, page_size=ecfg.page_size,
            max_pages=ecfg.max_pages, n_pages=ecfg.n_pages,
            prefix_cache=ecfg.prefix_cache, telemetry=self.tel,
            sharding=(self._plan.cache if self._plan is not None
                      else None),
            mesh_shape=(ecfg.mesh_data, ecfg.mesh_model))
        self.scheduler = Scheduler(ecfg.max_queue, cfg.block_size,
                                   clock=clock)
        self.metrics = Metrics()
        self.step_timer = StepTimer()
        P = ecfg.pool_size
        self._chunk = ecfg.chunk(cfg.block_size)
        self._window = max(int(ecfg.decode_window), 1)
        # Pallas paged-decode route: static per engine (one compiled
        # program either way); packed layout + TPU backend + envelope.
        # The FUSED all-layers kernel (one launch per decode step,
        # page-table scalar-prefetch inside the layer loop) is
        # preferred; the per-layer paged-attention kernel is the
        # fallback when the layer weights don't fit its VMEM envelope.
        from ..ops import decode_pallas, paged_pallas
        itemsize = jnp.dtype(self.pool.cache["k"].dtype).itemsize
        # (the mesh gate lives inside the two supported() calls below
        # — ops.paged_pallas.paged_kernel_mesh_ok is the one seam)
        kernel_ok = (ecfg.paged_kernel
                     and cfg.decode_cache_layout == "packed"
                     and paged_pallas._paged_attn_backend_ok())
        self._use_fused = bool(
            kernel_ok and decode_pallas.fused_paged_decode_supported(
                cfg, P, self.pool.page_size, itemsize, mesh=self.mesh))
        self._use_pallas = bool(
            kernel_ok and not self._use_fused
            and paged_pallas.paged_decode_supported(
                cfg.n_head, cfg.head_dim, self.pool.page_size, itemsize,
                mesh=self.mesh))
        self._tok = np.zeros((P,), np.int32)
        # ALIAS of pool.positions (one host buffer): the pool exposes the
        # committed frontier to drafters, the engine advances it in place
        self._pos = self.pool.positions
        self._active = np.zeros((P,), bool)
        self._budget = np.zeros((P,), np.int32)   # tokens still allowed
        self._eos = np.full((P,), -1, np.int32)   # per-slot stop token
        self._temp = np.ones((P,), np.float32)
        self._top_k = np.zeros((P,), np.int32)
        self._top_p = np.zeros((P,), np.float32)
        self._greedy = np.zeros((P,), bool)
        # async window machinery: the device-resident donated step state
        # (tok, pos, active, budget) between window dispatches — None
        # means "host mirrors are authoritative, re-upload at the next
        # launch" — and the in-flight dispatch whose token block has
        # not been fetched yet (double buffering: window N+1 launches
        # before window N's block is read)
        self._dev_state = None
        self._inflight: Optional[_InFlight] = None
        # committed up front for the same jit-key stability reason as
        # CachePool.cache (the array becomes a committed jit output
        # after the first step)
        from .cache_pool import commit_default
        # rng streams are (P, 2): their bootstrap commit must use the
        # rank-2 replicated REPRESENTATION (ServeShardings.rep2) — the
        # jit cache key is representational, and the window programs
        # propagate the rng state out rank-matched
        self._rngs = commit_default(
            jnp.stack([jax.random.PRNGKey(i) for i in range(P)]),
            sharding=(self._plan.rep2 if self._plan is not None
                      else None))
        self._slots: Dict[int, _Active] = {}
        self._pending: List[RequestResult] = []  # cancellations between steps
        self.n_steps = 0
        # the steady-state contract, enforced live: each entry point may
        # compile ONE program for this engine's shapes (counted relative
        # to engine construction — the module jit caches accumulate
        # across engines); a second compile raises RecompileError from
        # the step that caused it. Replaces the ad-hoc two-program
        # bookkeeping the first serving PR shipped (compile_counts()
        # remains for offline summaries).
        # a windowed engine legitimately owns TWO decode programs: the
        # k=decode_window steady-state window and the k=1 fallback it
        # drops to around admissions/finishes/spec transitions
        self._decode_guard = CompileGuard(
            _engine_decode_window, "serve/decode",
            max_programs=2 if self._window > 1 else 1)
        self._prefill_guard = CompileGuard(_engine_prefill, "serve/prefill")
        self._verify_guard = CompileGuard(_engine_verify, "serve/verify")
        self._copy_guard = CompileGuard(_engine_page_copy, "serve/page-copy")
        # warm the COW program NOW (page 0 onto itself — a value no-op):
        # the first real copy-on-write happens mid-replay, where a
        # compile would break the pinned-flat compile_counts invariant
        self.pool.cache = self._copy_guard(self.pool.cache, jnp.int32(0),
                                           jnp.int32(0),
                                           shardings=self._plan)
        self._sanitize = sanitize_enabled()
        # self-healing (faults.watchdog): all policies opt-in via rcfg.
        # Degraded transitions move between the two already-budgeted
        # steady-state programs (verify <-> decode), so CompileGuard
        # keeps enforcing zero recompiles through every mode switch.
        self.rcfg = rcfg or ResilienceConfig()
        self.journal = journal
        self._spec_active = drafter is not None
        self._watchdog = (StepWatchdog(self.rcfg, telemetry=self.tel)
                          if self.rcfg.watchdog_on else None)
        self._spec_health = (SpecHealth(self.rcfg, telemetry=self.tel)
                             if (self.rcfg.spec_guard_on
                                 and drafter is not None) else None)
        self._shedder = (LoadShedder(self.rcfg, telemetry=self.tel)
                         if self.rcfg.shed_on else None)
        self._probe_pending = False
        self._spec_pinned = False     # operator pin (set_spec_active)
        #: host-side log of resilience events (bounded — see _event),
        #: for tests/ops
        self.events: List[str] = []

    # ---------------------------------------------------------------- API

    def submit(self, req: Request) -> Optional[RequestResult]:
        self.metrics.inc("requests_submitted")
        if (self.pool.slot_of(req.id) is not None
                or self.scheduler.contains(req.id)):
            # an id must be unique among in-flight requests: results,
            # cancellation, the journal and the pool's reverse index all
            # key on it
            self.metrics.inc(REJECT_BAD_REQUEST)
            return RequestResult(id=req.id, tokens=[],
                                 finish_reason=REJECT_BAD_REQUEST)
        eos = req.eos_token_id
        if eos is not None and not (0 <= int(eos) < self.cfg.vocab_size):
            # the device-side stop mask compares sampled ids against
            # this value; an out-of-vocab eos can never match and is a
            # caller bug — reject it loudly
            self.metrics.inc(REJECT_BAD_REQUEST)
            return RequestResult(id=req.id, tokens=[],
                                 finish_reason=REJECT_BAD_REQUEST)
        reason = self.scheduler.submit(req)
        if reason is not None:
            # an expired-at-submit deadline is a terminal finish, not a
            # backpressure rejection — count it with the finishes
            self.metrics.inc("finished_" + reason
                             if reason == FINISH_DEADLINE else reason)
            return RequestResult(id=req.id, tokens=[], finish_reason=reason)
        if self.journal is not None:
            self.journal.record_submit(req)
        return None

    def cancel(self, request_id: str, migrated: bool = False) -> bool:
        """Cancel a queued or running request. The terminal
        ``RequestResult`` (with any tokens already produced) surfaces
        from the next ``step()``; True iff the request was found. An
        active request's slot and its reserved KV pages are released
        IMMEDIATELY (not at the next step) — a cancelled mid-stream
        request must not hold capacity while its terminal result waits
        to surface. ``migrated=True`` is the fleet router's re-route
        path: the request is not ending, it is moving to another
        replica — the telemetry envelope closes tagged ``migrated`` (a
        non-terminal segment, see tools/trace_check.py) and the journal
        still records a finish so THIS replica's journal replay never
        resurrects it."""
        now = self.clock()
        if self.scheduler.cancel(request_id):
            self.metrics.inc("finished_" + FINISH_CANCELLED)
            self._journal_finish(request_id, FINISH_CANCELLED)
            self._pending.append(RequestResult(
                id=request_id, tokens=[], finish_reason=FINISH_CANCELLED))
            return True
        slot = self.pool.slot_of(request_id)
        if slot is None:
            return False
        # cancel-during-window: fetch the in-flight dispatch first so
        # the tokens it already committed ride the terminal result, and
        # the slot + pages release at the window boundary — never while
        # a dispatch that writes through the slot's table is in flight
        self._pending.extend(self._drain_pending())
        slot = self.pool.slot_of(request_id)
        if slot is None:
            # the drained window finished it naturally; its terminal
            # result is already pending
            return True
        self._pending.append(self._finish_slot(slot, FINISH_CANCELLED, now,
                                               migrated=migrated))
        return True

    def partial_tokens(self, request_id: str) -> Optional[List[int]]:
        """Tokens committed so far for an ACTIVE request (host list
        copy; None when the request holds no slot — still queued, or
        already finished). The streaming front door (serve/http.py) and
        the fleet router's delivery dedupe poll this between steps."""
        slot = self.pool.slot_of(request_id)
        if slot is None or slot not in self._slots:
            return None
        return list(self._slots[slot].tokens)

    def in_flight_ids(self) -> List[str]:
        """Every accepted-but-unfinished request id: queued first (in
        arrival order), then active slots. The router's re-route path
        reads this for a wedged replica (for a DEAD one it replays the
        journal instead — host memory died with the replica)."""
        queued = self.scheduler.ids()
        active = [self._slots[s].req.id for s in sorted(self._slots)]
        return queued + active

    def slot_track(self, slot: int) -> int:
        """Telemetry track id of a slot (``track_base``-offset) — the
        router closes a killed replica's open request envelopes on the
        right tracks."""
        return self._tb + SLOT_TRACK_BASE + slot

    @property
    def idle(self) -> bool:
        return (not self._active.any() and len(self.scheduler) == 0
                and not self._pending and self._inflight is None)

    def step(self) -> List[RequestResult]:
        """One scheduling iteration: expire -> shed -> admit -> decode,
        with the self-healing policies (watchdog / speculative health /
        shedding) folded around the decode phase when configured.

        With ``decode_window > 1`` the steady-state decode phase is the
        double-buffered window path: dispatch the NEXT k-step window,
        then fetch the previous one's token block — the host stays one
        window ahead of the device. Any step that must mutate per-slot
        state host-side (admission possible, an active deadline
        expired, a speculative verify or re-probe due) first drains the
        in-flight window and runs the blocked k=1 (or verify) dispatch
        instead; queued-deadline expiry and overload shedding are
        host-only and never break a window."""
        finished: List[RequestResult] = self._pending
        self._pending = []
        now = self.clock()
        t_wall = time.perf_counter()
        t_step_us = self.tel.now_us() if self.tel.enabled else 0.0

        for req, t_submit, reason in self.scheduler.drain_expired(now):
            finished.append(self._finish_unstarted(req, t_submit, reason,
                                                   now))
        if self._shedder is not None:
            n_shed = self._shedder.observe(self.scheduler.depth,
                                           self.ecfg.max_queue)
            if n_shed:
                for req, t_submit in self.scheduler.shed(n_shed):
                    finished.append(self._finish_unstarted(
                        req, t_submit, FINISH_SHED, now))
                self.metrics.inc("shed_requests", n_shed)
                self._event(f"step {self.n_steps}: shed {n_shed} "
                                   f"queued request(s) under sustained "
                                   f"overload")

        expired = [slot for slot in list(self._slots)
                   if self._slots[slot].req.deadline is not None
                   and now >= self._slots[slot].req.deadline]

        # speculative re-probe countdown while degraded (auto-disabled
        # only: an operator pin via set_spec_active(False) must stick)
        reprobe = False
        if (self.drafter is not None and not self._spec_active
                and not self._spec_pinned
                and self._spec_health is not None
                and self._active.any()):
            reprobe = self._spec_health.tick_disabled()

        use_spec = (self.drafter is not None
                    and (self._spec_active or reprobe))
        # steady state = nothing needs the host to touch per-slot state
        # before the next dispatch. A deep backlog whose head cannot
        # admit (pool full / not enough pages) does NOT break windows:
        # arrivals batch up and admit at the next window boundary.
        windowed = (self._window > 1 and not use_spec and not expired
                    and not self._head_admissible()
                    and bool(self._active.any()))

        if not windowed:
            # a host mutation is coming: fetch the in-flight window
            # first — its tokens commit now, finished slots' pages and
            # slots free at this window boundary
            finished.extend(self._drain_pending())
            for slot in expired:
                if slot in self._slots:   # may have finished in the drain
                    finished.append(self._finish_slot(
                        slot, FINISH_DEADLINE, now))
            if reprobe:
                self.set_spec_active(True)
                self._probe_pending = True
                self.metrics.inc("spec_reprobes")
                self._event(f"step {self.n_steps}: re-probing "
                                   f"speculative decoding")
            # one-at-a-time admission: each _admit changes page
            # availability, so the fits check must see fresh allocator
            # state per request (FIFO preserved — a head that does not
            # fit blocks the queue rather than being skipped, so big
            # requests cannot starve)
            while self.pool.n_free > 0:
                admitted, dropped = self.scheduler.admit(1, now,
                                                         fits=self._fits)
                for req, t_submit, reason in dropped:
                    finished.append(self._finish_unstarted(req, t_submit,
                                                           reason, now))
                if not admitted:
                    break
                req, t_submit = admitted[0]
                self._admit(req, t_submit, now)

        self.metrics.gauge("queue_depth", self.scheduler.depth)
        self.metrics.gauge("slots_active", int(self._active.sum()))
        self.metrics.gauge("slot_occupancy", self.pool.occupancy)
        self.metrics.gauge("pages_in_use", self.pool.alloc.pages_in_use)

        # chaos seam: an artificially slow/stuck step (no-op without an
        # installed FaultPlan) — what the watchdog must catch
        flt = fault_fire("serve/step", index=self.n_steps)
        if flt is not None and flt.kind == "delay":
            time.sleep(flt.arg)

        if self._active.any():
            if windowed:
                with annotate("serve/decode"):
                    # every live slot's remaining budget fits one more
                    # window => that window is the LAST (barring eos,
                    # which only ends sooner): no point dispatching
                    # blind past it
                    last = int(self._budget[self._active].max()
                               ) <= self._window
                    if self._inflight is not None and last:
                        # the in-flight window already finishes
                        # everything — just fetch it
                        finished.extend(self._drain_pending())
                    elif last:
                        finished.extend(self._drain_window(
                            self._launch(self._window)))
                    else:
                        # double buffering: launch window N+1 BEFORE
                        # fetching window N's token block
                        nxt = self._launch(self._window)
                        finished.extend(self._drain_pending())
                        self._inflight = nxt
            else:
                spec_now = self.drafter is not None and self._spec_active
                finished.extend(self._verify_once() if spec_now
                                else self._decode_once())
            if self._watchdog is not None:
                dur = time.perf_counter() - t_wall
                if self._watchdog.observe(dur):
                    self.metrics.inc("watchdog_stalls")
                    self.metrics.gauge("last_stall_s", dur)
                    self._event(f"step {self.n_steps}: stall — "
                                       f"{dur * 1e3:.1f} ms step against "
                                       f"a p99-derived budget")
        elif self._inflight is not None:
            # endgame: every slot finished while a window was in flight
            # — fetch it (it emits nothing) so drain() reaches idle
            finished.extend(self._drain_pending())
        if self.tel.enabled:
            self.tel.complete("engine_step", self._tb + ENGINE_TRACK,
                              t_step_us,
                              self.tel.now_us() - t_step_us,
                              step=self.n_steps,
                              queue_depth=self.scheduler.depth,
                              n_active=int(self._active.sum()),
                              n_finished=len(finished))
        return finished

    def set_spec_active(self, active: bool) -> None:
        """Flip speculative decoding between its verify program and the
        plain decode program (both CompileGuard-budgeted — no new
        compilations at steady state). Re-enabling resyncs stateful
        drafters from host-side histories: tokens committed while
        degraded never went through the drafter's cache. A manual
        disable through this method PINS the degraded mode — the
        auto-re-probe policy leaves it alone until set_spec_active(True)
        lifts the pin (the auto-disable path flips ``_spec_active``
        directly and stays re-probeable)."""
        active = active and self.drafter is not None
        if active and not self._spec_active:
            # an in-flight decode window holds tokens the drafters'
            # resync must see — fetch it before reading histories
            self._pending.extend(self._drain_pending())
            hists = self._histories()
            for slot in self._slots:
                if self._active[slot] and hists[slot] is not None:
                    self.drafter.resync(slot, hists[slot])
        self._spec_pinned = not active and self.drafter is not None
        self._spec_active = active

    @property
    def spec_active(self) -> bool:
        return self._spec_active

    def _journal_finish(self, request_id: str, reason: str) -> None:
        if self.journal is not None:
            self.journal.record_finish(request_id, reason)

    def _event(self, msg: str) -> None:
        # a soak run with recurring degradations must not grow host
        # memory without bound (the Metrics reservoir rationale)
        self.events.append(msg)
        if len(self.events) > 256:
            del self.events[:len(self.events) - 256]

    def drain(self, max_steps: int = 1_000_000) -> List[RequestResult]:
        out: List[RequestResult] = []
        for _ in range(max_steps):
            if self.idle:
                return out
            out.extend(self.step())
        raise RuntimeError(f"engine did not drain in {max_steps} steps")

    def metrics_summary(self) -> dict:
        s = self.metrics.summary()
        s["step_latency"] = self.step_timer.summary(skip=1)
        s["n_steps"] = self.n_steps
        s["compile_counts"] = compile_counts()
        s["compile_guards"] = {"decode": self._decode_guard.stats(),
                               "prefill": self._prefill_guard.stats(),
                               "verify": self._verify_guard.stats(),
                               "page_copy": self._copy_guard.stats()}
        # paged-pool health: bench dashboards key on this block (schema
        # pinned in tests/test_pages.py)
        s["pages"] = self.pool.stats()
        # dispatch amortization: the host tax per dispatch vs per token
        # (the serve-side analogue of the train bench's dispatch split;
        # BENCH_r03 measured 77.4 ms blocked vs 12.1 ms/step amortized)
        c = self.metrics.counters
        disp = self.metrics.hist_summary("decode_dispatch_s")
        n_disp = int(c.get("decode_dispatches", 0))
        dec_tokens = int(c.get("dispatch_tokens", 0))
        mean_ms = disp.get("mean", 0.0) * 1e3
        s["dispatch"] = {
            "window_k": self._window,
            "dispatches": n_disp,
            "mean_dispatch_ms": round(mean_ms, 4),
            "host_dispatch_ms_per_token": (
                round(mean_ms * n_disp / dec_tokens, 4)
                if dec_tokens else 0.0),
        }
        c = self.metrics.counters
        s["recovery"] = {
            "watchdog_stalls": int(c.get("watchdog_stalls", 0)),
            "spec_disables": int(c.get("spec_disables", 0)),
            "spec_reprobes": int(c.get("spec_reprobes", 0)),
            "shed_requests": int(c.get("shed_requests", 0)),
            "spec_active": self._spec_active,
            "events": list(self.events[-32:]),
        }
        if self.drafter is not None:
            c = self.metrics.counters
            drafted = c.get("spec_draft_tokens", 0)
            slot_steps = c.get("slot_steps", 0)
            s["speculative"] = {
                "drafter": self.drafter.name,
                "k": self.drafter.k,
                "accept_rate": (round(c.get("spec_accepted_tokens", 0)
                                      / drafted, 4) if drafted else 0.0),
                "mean_tokens_per_step": (round(c.get("decode_tokens", 0)
                                               / slot_steps, 3)
                                         if slot_steps else 0.0),
                "draft_overhead_s":
                    self.metrics.hist_summary("draft_overhead_s"),
            }
        return s

    # ----------------------------------------------------------- internals

    def _cap(self, req: Request) -> int:
        """Decode budget for a request: decode step i runs at position
        P-1+i (the first rewrites the last prompt position), so a slot
        supports S - P + 1 new tokens before the write position would
        leave the logical buffer."""
        return min(req.max_new_tokens,
                   self.pool.seq_len - int(req.prompt.size) + 1)

    def _fits(self, req: Request) -> bool:
        """Admission gate beyond free slots: enough free (or LRU-
        reclaimable) pages for the request's WHOLE lifetime — prompt
        minus cached prefix plus the full decode budget, reserved
        eagerly so an admitted request can never strand mid-decode."""
        return self.pool.can_admit(req.prompt, self._cap(req))

    def _admit(self, req: Request, t_submit: float, now: float) -> None:
        P = int(req.prompt.size)
        cap = self._cap(req)
        t_admit_us = self.tel.now_us() if self.tel.enabled else 0.0
        # acquire claims the longest radix-cached prefix, reserves the
        # remaining pages, and sets pool.positions[slot] = P - 1 (which
        # self._pos aliases — the first decode rewrites the last prompt
        # index)
        adm = self.pool.acquire(req.id, req.prompt, cap)
        assert adm is not None, "scheduler admitted past pool capacity"
        slot = adm.slot
        tid = self._tb + SLOT_TRACK_BASE + slot
        if self.tel.enabled:
            # the request's span tree opens BACKDATED to its submit
            # time (viewers sort by ts, so out-of-order emission is
            # fine); the queue phase closes it out to this admission
            ts_sub = self.tel.ts_us(t_submit)
            self.tel.begin("request", tid, ts_us=ts_sub, request=req.id,
                           prompt_tokens=P, max_new_tokens=cap)
            self.tel.complete("queue", tid, ts_sub,
                              self.tel.ts_us(now) - ts_sub,
                              request=req.id)
        for src, dst in adm.cow:
            # copy-on-write split of a fully-cached prompt's frontier
            # page; program warmed at construction (budget 1)
            check_in_bounds(dst, 1, self.pool.n_pages, what="COW page")
            self.tel.instant("cow_split", tid, src=src, dst=dst,
                             request=req.id)
            self.pool.cache = self._copy_guard(self.pool.cache,
                                               jnp.int32(src),
                                               jnp.int32(dst),
                                               shardings=self._plan)
        claimed = adm.claimed
        S = self.pool.seq_len
        if claimed < P:
            chunk = self._chunk
            n_chunks = -(-(P - claimed) // chunk)
            # host-side bound for the jitted prefill (offset traced):
            # every REAL token position must sit inside the logical
            # buffer — padded tail positions are routed to scatter-drop
            # inside prefill_chunk_paged, so only [claimed, P) matters
            check_in_bounds(claimed, P - claimed, S,
                            what=f"prefill of {P}-token prompt from "
                                 f"{claimed} in {chunk}-chunks")
            padded = np.zeros((n_chunks * chunk,), np.int32)
            padded[:P - claimed] = req.prompt[claimed:]
            table_row = jnp.asarray(self.pool.tables[slot])
            cache = self.pool.cache
            with annotate("serve/prefill"):
                for c in range(n_chunks):
                    tc_us = (self.tel.now_us() if self.tel.enabled
                             else 0.0)
                    cache = self._prefill_guard(
                        self.params,
                        jnp.asarray(padded[None,
                                           c * chunk:(c + 1) * chunk]),
                        jnp.int32(claimed + c * chunk), jnp.int32(P),
                        table_row, cache, self.cfg,
                        shardings=self._plan)
                    if self.tel.enabled:
                        # host dispatch time (the device runs async);
                        # a jax.profiler capture of the same run shows
                        # the device-side cost under serve/prefill
                        self.tel.complete(
                            "prefill_chunk", tid, tc_us,
                            self.tel.now_us() - tc_us, chunk=c,
                            n_chunks=n_chunks, request=req.id)
            self.pool.cache = cache
        # registration AFTER the prefill wrote the pages: a same-step
        # neighbor may claim them the moment they hit the radix
        self.pool.commit_admission(slot)
        if self.drafter is not None:
            # drafters keep their own (unpaged) cache and see the full
            # prompt — prefix reuse is a target-pool concern
            self.drafter.on_admit(slot, req.prompt)
        self._tok[slot] = req.prompt[-1]
        self._active[slot] = True
        self._budget[slot] = cap
        self._eos[slot] = (-1 if req.eos_token_id is None
                           else int(req.eos_token_id))
        # host mirrors changed: the next window launch re-uploads them
        # (admission only runs with no dispatch in flight)
        self._dev_state = None
        sp = req.sampling
        self._temp[slot] = sp.temperature
        self._top_k[slot] = sp.top_k
        self._top_p[slot] = sp.top_p
        self._greedy[slot] = sp.greedy
        self._rngs = self._rngs.at[slot].set(jax.random.PRNGKey(req.rng_seed))
        self._slots[slot] = _Active(req=req, t_submit=t_submit, t_admit=now,
                                    cap=cap,
                                    capped=cap < req.max_new_tokens)
        if self.tel.enabled:
            self.tel.complete("admit", tid, t_admit_us,
                              self.tel.now_us() - t_admit_us,
                              request=req.id, cached_tokens=claimed,
                              prefill_tokens=P - claimed)
        self.metrics.inc("requests_admitted")
        self.metrics.inc("prefill_tokens", P - claimed)
        self.metrics.inc("prefix_hit_tokens", claimed)
        self.metrics.observe("queue_wait_s", now - t_submit)

    def _head_admissible(self) -> bool:
        """Whether this step could admit: a free slot AND a queued,
        unexpired head that fits the page gate. While False, a backlog
        does not break decode windows — arrivals batch at window
        boundaries (the scheduler's strict FIFO is unchanged: only the
        HEAD is consulted, exactly like the admission loop)."""
        if self.pool.n_free <= 0:
            return False
        head = self.scheduler.peek()
        return head is not None and self._fits(head[0])

    def _launch(self, k: int) -> _InFlight:
        """Dispatch one ``k``-step decode window WITHOUT fetching its
        results. The donated device step state from the previous
        dispatch feeds straight back in when the host hasn't touched
        per-slot state since (``_dev_state``); otherwise the host
        mirrors are uploaded once. The token block's device->host copy
        starts immediately (``copy_to_host_async``), so by the time
        ``_drain_window`` reads it the transfer has been overlapping
        device compute."""
        t0_us = self.tel.now_us() if self.tel.enabled else 0.0
        t_wall = time.perf_counter()
        n_active = int(self._active.sum())
        if self._dev_state is None:
            # host-side bound for the traced window writes: every REAL
            # write position (bounded by the per-slot budget — the
            # admission cap's pos + budget <= seq_len invariant) stays
            # inside the logical buffer
            check_in_bounds(
                np.where(self._active,
                         self._pos + np.minimum(
                             np.maximum(self._budget, 1), k) - 1, 0),
                1, self.pool.seq_len, what="decode window write")
            # committed, like every engine-owned jit input: the state
            # must enter this call exactly as it leaves the donated
            # steady-state loop (a committed output), or the jit cache
            # keys the two placements as two programs — on a mesh that
            # means replicated over every device (the constrained
            # window output's placement), not one chip
            from .cache_pool import commit_default
            state = tuple(commit_default(jnp.asarray(a),
                                         sharding=self._rep) for a in
                          (self._tok, self._pos, self._active,
                           self._budget))
        else:
            state = self._dev_state
        tok, pos, active, budget = state
        toks, emitted, tok, pos, active, budget, cache, rngs = \
            self._decode_guard(
                self.params, tok, pos, active, budget,
                jnp.asarray(self._eos), jnp.asarray(self.pool.tables),
                self.pool.cache, self._rngs, jnp.asarray(self._temp),
                jnp.asarray(self._top_k), jnp.asarray(self._top_p),
                jnp.asarray(self._greedy), self.cfg, k=k,
                use_pallas=self._use_pallas, use_fused=self._use_fused,
                shardings=self._plan)
        self.pool.cache = cache
        self._rngs = rngs
        self._dev_state = (tok, pos, active, budget)
        for out in (toks, emitted):
            copy_async = getattr(out, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        # the host-side dispatch tax this PR amortizes: arg conversion +
        # trace-cache lookup + enqueue, all BEFORE any device wait (the
        # bench dispatch-split line reads this histogram)
        self.metrics.inc("decode_dispatches")
        self.metrics.observe("decode_dispatch_s",
                             time.perf_counter() - t_wall)
        return _InFlight(toks=toks, emitted=emitted, k=k, t0_us=t0_us,
                         t_wall=t_wall, n_active=n_active)

    def _drain_pending(self) -> List[RequestResult]:
        if self._inflight is None:
            return []
        w, self._inflight = self._inflight, None
        return self._drain_window(w)

    def _commit_tokens(self, slot: int, st: _Active, committed: List[int],
                       now: float, t0_us: float, dur_us: float) -> None:
        """Append a dispatch's committed tokens to a slot's host record
        — ONE definition for the decode-window and speculative-verify
        drains: TTFT on the first token, one ``token`` telemetry
        instant per committed token interpolated across the dispatch
        span (indices are the request's running count — the strictly-
        increasing contract tools/trace_check.py enforces), and the
        ``_tok``/``_pos``/``_budget`` mirrors advanced."""
        tid = self._tb + SLOT_TRACK_BASE + slot
        first = not st.tokens
        base = len(st.tokens)
        st.tokens.extend(committed)
        if self.tel.enabled:
            n = len(committed)
            for j in range(n):
                self.tel.instant("token", tid,
                                 ts_us=t0_us + dur_us * (j + 1) / n,
                                 request=st.req.id, index=base + j + 1)
        if first:
            st.t_first_token = now
            self.metrics.observe("ttft_s", now - st.t_submit)
        st.t_last_token = now
        self._tok[slot] = st.tokens[-1]
        self._pos[slot] += len(committed)
        self._budget[slot] = st.cap - len(st.tokens)

    def _drain_window(self, w: _InFlight) -> List[RequestResult]:
        """Fetch one dispatched window's token block (ONE host snapshot
        per window — ``np.asarray`` on the async-copied outputs) and run
        the host bookkeeping: append tokens, advance the mirrors,
        finish slots whose budget ran out or whose eos landed. Slots
        that finished mid-window already idled on device; their pages
        and slot free HERE, at the window boundary."""
        toks = np.asarray(w.toks)
        emitted = np.asarray(w.emitted)
        now = self.clock()
        self.n_steps += 1
        self.step_timer.laps.append(time.perf_counter() - w.t_wall)
        n_tok = int(emitted.sum())
        if self._sanitize:
            # GRAFT_SANITIZE: sampled ids must be valid vocab entries
            # (an out-of-range id would clamp in the next embedding
            # gather and silently decode garbage)
            live = toks[emitted]
            bad = (live < 0) | (live >= self.cfg.vocab_size)
            if bad.any():
                raise FloatingPointError(
                    f"sanitize: decode produced out-of-range token(s) "
                    f"{live[bad][:4].tolist()} (vocab "
                    f"{self.cfg.vocab_size})")
        self.metrics.observe("batch_fill_ratio",
                             w.n_active / self.ecfg.pool_size)
        self.metrics.inc("decode_steps")
        self.metrics.inc("decode_tokens", n_tok)
        # plain-decode tokens only (decode_tokens also counts verify
        # commits): the denominator of host_dispatch_ms_per_token —
        # dispatch time is only accumulated on this path, so a
        # spec-enabled run must not dilute the ratio
        self.metrics.inc("dispatch_tokens", n_tok)
        tel_on = self.tel.enabled
        # span end at ts_us(now) — the same clock reading the finish
        # path stamps on a request's E event, so a slot's last decode
        # span never spills past its request envelope
        dur_us = (self.tel.ts_us(now) - w.t0_us) if tel_on else 0.0
        if tel_on:
            self.tel.complete("decode_step", self._tb + ENGINE_TRACK,
                              w.t0_us, dur_us, step=self.n_steps,
                              n_active=w.n_active, k=w.k, tokens=n_tok)
        finished: List[RequestResult] = []
        for slot in list(self._slots):
            # emitted[:, slot] is a prefix mask: a slot deactivates once
            # inside a window and never re-arms
            n_emit = int(emitted[:, slot].sum())
            if n_emit == 0:
                continue
            st = self._slots[slot]
            if tel_on:
                self.tel.complete("decode",
                                  self._tb + SLOT_TRACK_BASE + slot,
                                  w.t0_us, dur_us,
                                  step=self.n_steps, request=st.req.id,
                                  k=w.k, tokens=n_emit)
            self._commit_tokens(slot, st,
                                [int(t) for t in toks[:n_emit, slot]],
                                now, w.t0_us, dur_us)
            eos = int(self._eos[slot])
            if eos >= 0 and st.tokens[-1] == eos:
                # the device deactivated the slot the step its eos
                # landed (emission stops right there — the eos token is
                # the stream's last)
                finished.append(self._finish_slot(
                    slot, FINISH_EOS, now, device_stopped=True))
            elif self._budget[slot] <= 0:
                reason = (FINISH_LENGTH_CAP if st.capped
                          else FINISH_MAX_TOKENS)
                finished.append(self._finish_slot(
                    slot, reason, now, device_stopped=True))
        # deferred radix registration: the full prompt page holding
        # position P-1 becomes shareable once the frontier passed it
        self.pool.flush_pending()
        return finished

    def _decode_once(self) -> List[RequestResult]:
        """Blocked k=1 decode: dispatch one step and immediately fetch
        it — the fallback around host-side state mutations (admission,
        deadline, cancel, speculative transitions)."""
        with annotate("serve/decode"):
            return self._drain_window(self._launch(1))

    def _histories(self) -> List[Optional[np.ndarray]]:
        """Per-slot prompt+generated token history — pure host data (the
        engine appends every committed token), so drafters never pay a
        device sync for it."""
        out: List[Optional[np.ndarray]] = [None] * self.ecfg.pool_size
        for slot, st in self._slots.items():
            # fromiter, not asarray: tokens is a host list of ints — no
            # device round-trip here, and the conversion can't be
            # mistaken (by reader or linter) for one
            out[slot] = np.concatenate(
                [st.req.prompt,
                 np.fromiter(st.tokens, np.int32, len(st.tokens))])
        return out

    def _verify_once(self) -> List[RequestResult]:
        """One speculative step: host-side draft -> ONE jitted verify
        over all slots -> commit 1..k+1 tokens per slot. The drafter's
        proposals are clamped per slot by cache room (the window's last
        REAL write position must stay inside the slot buffer) and by
        the remaining token budget, both host-side — the device program
        only ever sees traced (n_slots,)-sized inputs."""
        k = self.drafter.k
        S = self.pool.seq_len
        P = self.ecfg.pool_size
        # verify works off the host mirrors and advances them below:
        # any device-resident window state is stale after this step
        self._dev_state = None
        ctx = DraftContext(
            tok=self._tok, pos=self._pos, active=self._active,
            histories=(self._histories() if self.drafter.needs_history
                       else None))
        draft_toks, draft_len, dt = timed_draft(
            self.drafter, ctx, self.cfg.vocab_size, tel=self.tel,
            track=self._tb + ENGINE_TRACK)
        self.metrics.observe("draft_overhead_s", dt)
        t0_us = self.tel.now_us() if self.tel.enabled else 0.0
        m = np.zeros((P,), np.int32)
        for slot, st in self._slots.items():
            if not self._active[slot]:
                continue
            room = S - 1 - int(self._pos[slot])
            budget = st.cap - len(st.tokens) - 1
            m[slot] = max(0, min(int(draft_len[slot]), k, room, budget))
        window = np.zeros((P, k + 1), np.int32)
        window[:, 0] = self._tok
        window[:, 1:] = draft_toks
        # the host-side bound the traced verify writes rely on: every
        # ACTIVE slot's real window positions (j <= m) stay inside the
        # slot buffer; padding positions route to an explicit
        # scatter-drop (GL006). Scoped to active slots: a released
        # slot's stale frontier can legitimately sit at S (a request
        # that finished by filling its buffer), and the verify program
        # runs those slots at position 0 anyway.
        check_in_bounds(np.where(self._active, self._pos + m, 0), 1, S,
                        what="speculative verify window")
        with annotate("serve/verify"):
            self.step_timer.start()
            n_acc, out, cache, rngs = self._verify_guard(
                self.params, jnp.asarray(window), jnp.asarray(self._pos),
                jnp.asarray(m), jnp.asarray(self._active),
                jnp.asarray(self.pool.tables), self.pool.cache,
                self._rngs, jnp.asarray(self._temp),
                jnp.asarray(self._top_k), jnp.asarray(self._top_p),
                jnp.asarray(self._greedy), self.cfg,
                shardings=self._plan)
            self.step_timer.lap(n_acc)
        self.pool.cache = cache
        self._rngs = rngs
        # ONE host snapshot per verify step for every slot's outcome
        # (np.asarray, not jax.device_get: the engine's step loop is
        # GL004-clean — syncs happen once per dispatch, never per token)
        n_acc_h = np.asarray(n_acc)
        out_h = np.asarray(out)
        if self._sanitize:
            bad = (out_h < 0) | (out_h >= self.cfg.vocab_size)
            if bad.any():
                raise FloatingPointError(
                    f"sanitize: verify produced out-of-range token(s) "
                    f"{out_h[bad][:4].tolist()} (vocab "
                    f"{self.cfg.vocab_size})")
        now = self.clock()
        self.n_steps += 1
        n_active = int(self._active.sum())
        drafted = int(m.sum())
        accepted = int(n_acc_h.sum())
        emitted = accepted + n_active          # +1 correction/bonus each
        self.metrics.observe("batch_fill_ratio", n_active / P)
        self.metrics.inc("decode_steps")
        self.metrics.inc("decode_tokens", emitted)
        self.metrics.inc("slot_steps", n_active)
        self.metrics.inc("spec_draft_tokens", drafted)
        self.metrics.inc("spec_accepted_tokens", accepted)
        if drafted:
            self.metrics.observe("accept_rate", accepted / drafted)
        self.metrics.observe("tokens_per_slot_step", emitted / n_active)
        tel_on = self.tel.enabled
        dur_us = (self.tel.ts_us(now) - t0_us) if tel_on else 0.0
        if tel_on:
            self.tel.complete("verify_step", self._tb + ENGINE_TRACK,
                              t0_us, dur_us,
                              step=self.n_steps, n_active=n_active,
                              drafted=drafted, accepted=accepted)
        if self._spec_health is not None:
            if self._spec_health.observe(drafted, accepted):
                # the drafter is a pure tax at this accept rate: fall
                # back to plain decode (same shapes, already-budgeted
                # program) and re-probe later with backoff
                self._spec_active = False
                self._probe_pending = False
                self._spec_health.on_disable()
                self.metrics.inc("spec_disables")
                self._event(
                    f"step {self.n_steps}: speculative decoding disabled "
                    f"(windowed accept rate below "
                    f"{self.rcfg.spec_disable_threshold})")
            elif (self._probe_pending
                  and len(self._spec_health.window)
                  >= self.rcfg.spec_window):
                self._probe_pending = False
                self._spec_health.on_reenable()
                self._event(f"step {self.n_steps}: speculative "
                                   f"re-probe healthy; backoff reset")
        finished: List[RequestResult] = []
        for slot in list(self._slots):
            if not self._active[slot]:
                continue
            st = self._slots[slot]
            n_emit = int(n_acc_h[slot]) + 1
            committed = [int(t) for t in out_h[slot, :n_emit]]
            eos = int(self._eos[slot])
            if eos >= 0 and eos in committed:
                # a drafted/accepted eos ends the stream there — drop
                # whatever the verify window committed past it
                n_emit = committed.index(eos) + 1
                committed = committed[:n_emit]
            if tel_on:
                self.tel.complete("verify",
                                  self._tb + SLOT_TRACK_BASE + slot,
                                  t0_us, dur_us, step=self.n_steps,
                                  request=st.req.id, drafted=int(m[slot]),
                                  committed=n_emit)
            self._commit_tokens(slot, st, committed, now, t0_us, dur_us)
            if eos >= 0 and st.tokens[-1] == eos:
                finished.append(self._finish_slot(slot, FINISH_EOS, now))
            elif len(st.tokens) >= st.cap:
                reason = (FINISH_LENGTH_CAP if st.capped
                          else FINISH_MAX_TOKENS)
                finished.append(self._finish_slot(slot, reason, now))
        self.pool.flush_pending()
        return finished

    def _finish_slot(self, slot: int, reason: str, now: float,
                     migrated: bool = False,
                     device_stopped: bool = False) -> RequestResult:
        st = self._slots.pop(slot)
        self._active[slot] = False
        if not device_stopped:
            # a host-initiated finish (cancel/deadline/migration): the
            # device-resident step state still believes the slot is
            # live — rebuild from the mirrors at the next launch.
            # Budget/eos finishes already flipped the slot off ON
            # DEVICE, so their state stays donatable as-is.
            self._dev_state = None
        if self.tel.enabled:
            extra = {"migrated": True} if migrated else {}
            self.tel.end("request", self._tb + SLOT_TRACK_BASE + slot,
                         ts_us=self.tel.ts_us(now), request=st.req.id,
                         reason=reason, n_tokens=len(st.tokens), **extra)
        self.pool.release(slot)
        if self.drafter is not None:
            self.drafter.on_release(slot)
        n = len(st.tokens)
        decode_tps = 0.0
        if n > 1 and st.t_last_token > st.t_first_token:
            decode_tps = (n - 1) / (st.t_last_token - st.t_first_token)
        res = RequestResult(
            id=st.req.id, tokens=st.tokens, finish_reason=reason,
            queue_wait_s=st.t_admit - st.t_submit,
            ttft_s=(st.t_first_token - st.t_submit) if n else 0.0,
            decode_tokens_per_s=decode_tps, total_s=now - st.t_submit)
        self.metrics.inc(f"finished_{reason}")
        self._journal_finish(st.req.id, reason)
        if decode_tps:
            self.metrics.observe("decode_tokens_per_s", decode_tps)
        return res

    def _finish_unstarted(self, req: Request, t_submit: float, reason: str,
                          now: float) -> RequestResult:
        # never admitted -> no slot track and no open envelope; one
        # instant marks the terminal outcome on the engine timeline
        self.tel.instant("request_unstarted", self._tb + ENGINE_TRACK,
                         ts_us=(self.tel.ts_us(now) if self.tel.enabled
                                else None),
                         request=req.id, reason=reason)
        self.metrics.inc(f"finished_{reason}")
        self._journal_finish(req.id, reason)
        return RequestResult(id=req.id, tokens=[], finish_reason=reason,
                             queue_wait_s=now - t_submit,
                             total_s=now - t_submit)
